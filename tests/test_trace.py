"""Flight-recorder tests (tpu_device_plugin/trace.py).

Covers the lock-free span/ring/histogram primitives, the concurrency
contract (writers appending while a reader drains must never produce a
torn or duplicated span), the /debug/flight HTTP surface, the crash-dump
hook, the structured-logging correlation, and the two scenario claims
from the ISSUE:

- a full claim story (prepare -> allocate -> hot-unplug orphan) is
  reconstructable purely from /debug/flight filtered by claim UID;
- an armed checkpoint.write fault shows up on the failing claim's trace
  (the flush span errors with the injected fault) and as a fault event
  in the ring.
"""

import json
import logging
import os
import sys
import threading
import time
import urllib.request

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer, make_driver, prepare
from tpu_device_plugin import faults, trace
from tpu_device_plugin.config import Config
from tpu_device_plugin.kubeletapi import drapb
from tpu_device_plugin.lifecycle_fsm import DeviceLifecycle
from tpu_device_plugin.log import JsonFormatter, KeyValueFormatter


@pytest.fixture(autouse=True)
def clean_trace():
    trace.reset()
    yield
    trace.reset()
    trace.configure(enabled=True, ring_size=256, slow_ms=250.0)


# ------------------------------------------------------------- primitives


def test_span_records_fields_and_duration():
    with trace.span("t.op", resource="r0", epoch_id=3):
        time.sleep(0.002)
    recs = trace.snapshot(op="t.op")
    assert len(recs) == 1
    rec = recs[0]
    assert rec["kind"] == "span"
    assert rec["op"] == "t.op"
    assert rec["outcome"] == "ok"
    assert rec["dur_ms"] >= 1.0
    assert rec["attrs"] == {"resource": "r0", "epoch_id": 3}
    assert rec["thread"] == threading.current_thread().name
    assert rec["parent"] is None


def test_child_span_and_event_inherit_parent_attrs():
    with trace.span("t.parent", claim_uid="u1", bdf="0000:00:04.0"):
        with trace.span("t.child", step="flush"):
            pass
        trace.event("t.evt", what="fired")
    child = trace.snapshot(op="t.child")[0]
    assert child["attrs"]["claim_uid"] == "u1"
    assert child["attrs"]["bdf"] == "0000:00:04.0"
    assert child["attrs"]["step"] == "flush"
    assert child["parent"] is not None
    evt = trace.snapshot(op="t.evt")[0]
    assert evt["kind"] == "event"
    assert evt["attrs"]["claim_uid"] == "u1"
    # inheritance makes the claim filter catch both
    assert {r["op"] for r in trace.snapshot(claim="u1")} == \
        {"t.parent", "t.child", "t.evt"}


def test_span_error_outcome_carries_exception_text():
    with pytest.raises(RuntimeError):
        with trace.span("t.fail", claim_uid="u9"):
            raise RuntimeError("boom in prepare")
    rec = trace.snapshot(op="t.fail")[0]
    assert rec["outcome"] == "error"
    assert "RuntimeError: boom in prepare" == rec["error"]


def test_ring_overwrites_oldest_and_counts():
    trace.configure(ring_size=8)
    trace.reset()
    for i in range(20):
        with trace.span("t.ring", i=i):
            pass
    recs = trace.snapshot(op="t.ring")
    assert len(recs) == 8                     # fixed size, oldest gone
    assert [r["attrs"]["i"] for r in recs] == list(range(12, 20))
    assert trace.stats()["spans_overwritten_total"] == 12
    assert trace.stats()["spans_recorded_total"] == 20


def test_disabled_trace_records_nothing():
    trace.configure(enabled=False)
    try:
        with trace.span("t.off") as sp:
            sp.set(x=1)                       # the null span accepts set()
        trace.event("t.off.evt")
        assert trace.snapshot(op="t.off") == []
        assert trace.stats()["spans_recorded_total"] == 0
    finally:
        trace.configure(enabled=True)


def test_snapshot_filters_claim_bdf_op_and_limit():
    with trace.span("a.one", claim_uid="u1", bdf="b1"):
        pass
    with trace.span("a.two", claim_uid="u2", bdf="b2"):
        pass
    trace.event("b.three", device="b1")
    assert {r["op"] for r in trace.snapshot(claim="u1")} == {"a.one"}
    # bdf filter matches attrs.bdf AND attrs.device
    assert {r["op"] for r in trace.snapshot(bdf="b1")} == \
        {"a.one", "b.three"}
    assert {r["op"] for r in trace.snapshot(op="a.")} == {"a.one", "a.two"}
    assert len(trace.snapshot(limit=2)) == 2
    # limit keeps the NEWEST records
    assert trace.snapshot(limit=1)[0]["op"] == "b.three"


# ------------------------------------------------- trace context (ISSUE 15)


def test_root_span_mints_context_and_children_inherit_it():
    with trace.span("c.root") as root:
        ctx = trace.current_context()
        with trace.span("c.child"):
            child_ctx = trace.current_context()
        with trace.span("c.sibling"):
            pass
    assert ctx is not None and len(ctx["trace_id"]) == 32
    assert len(ctx["span_id"]) == 16
    assert root.trace_id == ctx["trace_id"]
    # children share the trace, each with its own span id
    assert child_ctx["trace_id"] == ctx["trace_id"]
    assert child_ctx["span_id"] != ctx["span_id"]
    recs = trace.snapshot(op="c.")
    assert {r["trace_id"] for r in recs} == {ctx["trace_id"]}
    assert len({r["span_id"] for r in recs}) == 3
    # a NEW root mints a NEW trace
    with trace.span("c.other"):
        other = trace.current_context()
    assert other["trace_id"] != ctx["trace_id"]
    # outside any span: no context, no propagation
    assert trace.current_context() is None
    assert trace.propagate() is None


def test_traceparent_round_trip_and_malformed_inputs_counted_dropped():
    with trace.span("tp.root"):
        wire = trace.propagate()
        ctx = trace.current_context()
    assert wire == f"00-{ctx['trace_id']}-{ctx['span_id']}-01"
    parsed = trace.parse_traceparent(wire)
    assert parsed["trace_id"] == ctx["trace_id"]
    assert parsed["span_id"] == ctx["span_id"]
    assert parsed["sampled"] is True
    assert trace.stats()["ctx_propagated_total"] == 1
    before = trace.stats()["ctx_dropped_total"]
    for bad in ("", "garbage", "00-zz-yy-01", None, 42,
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero trace
                "00-" + "1" * 32 + "-" + "0" * 16 + "-01"):  # zero span
        assert trace.parse_traceparent(bad) is None
    assert trace.stats()["ctx_dropped_total"] == before + 7


def test_link_adopts_on_root_records_on_child_and_inherits_down():
    with trace.span("l.origin"):
        wire = trace.propagate()
        origin = trace.current_context()
    # a linked ROOT adopts the remote trace id (the boundary crossing
    # continues the trace) and records the remote parent as the link
    with trace.span("l.remote-root", link=wire):
        assert trace.current_context()["trace_id"] == origin["trace_id"]
    rec = trace.snapshot(op="l.remote-root")[0]
    assert rec["trace_id"] == origin["trace_id"]
    assert rec["link"]["span_id"] == origin["span_id"]
    # a linked CHILD keeps the local trace but records the link — and
    # grandchildren inherit it like attrs
    with trace.span("l.local-root"):
        local = trace.current_context()
        with trace.span("l.linked-child", link=wire):
            with trace.span("l.grandchild"):
                pass
    child = trace.snapshot(op="l.linked-child")[0]
    assert child["trace_id"] == local["trace_id"]
    assert child["link"]["trace_id"] == origin["trace_id"]
    grand = trace.snapshot(op="l.grandchild")[0]
    assert grand["link"]["trace_id"] == origin["trace_id"]
    assert trace.stats()["ctx_attached_total"] == 2   # explicit links only
    # dict-shaped links (the brokeripc/handoff carriers) work too
    with trace.span("l.dict-link", link={"trace_id": origin["trace_id"],
                                         "span_id": origin["span_id"]}):
        assert trace.current_context()["trace_id"] == origin["trace_id"]
    # a malformed link degrades to no-link (counted), never raises
    with trace.span("l.bad-link", link="not-a-traceparent"):
        pass
    assert "link" not in trace.snapshot(op="l.bad-link")[0]


def test_snapshot_trace_filter_matches_own_id_and_links():
    with trace.span("f.origin", claim_uid="u-f"):
        wire = trace.propagate()
        tid = trace.current_context()["trace_id"]
    with trace.span("f.unrelated"):
        pass
    with trace.span("f.migration"):
        with trace.span("f.dest-prepare", link=wire):
            pass
    ops = {r["op"] for r in trace.snapshot(trace=tid)}
    assert ops == {"f.origin", "f.dest-prepare"}
    # events join the trace through span inheritance and through links
    with trace.span("f.origin2", link=wire):
        trace.event("f.evt")
    assert "f.evt" in {r["op"] for r in trace.snapshot(trace=tid)}
    evt_alone = trace.parse_traceparent(wire)
    trace.event("f.lone-evt", link=evt_alone)
    assert "f.lone-evt" in {r["op"] for r in trace.snapshot(trace=tid)}


def test_since_ms_cursor_paginates_oldest_first_without_overlap():
    for i in range(10):
        with trace.span("pg.op", i=i):
            pass
    full = trace.snapshot(op="pg.")
    assert len(full) == 10
    # drain in pages of 3 from cursor 0; strict-greater cursor means no
    # record repeats and none is lost
    seen = []
    cursor = 0.0
    for _ in range(10):
        page, more = trace.drain(cursor, limit=3, op="pg.")
        if not page:
            assert not more
            break
        assert [r["attrs"]["i"] for r in page] == sorted(
            r["attrs"]["i"] for r in page)   # oldest first
        seen += [r["attrs"]["i"] for r in page]
        cursor = page[-1]["ts"] * 1e3
        assert more == (len(seen) < 10)
    assert seen == list(range(10))


def test_drain_page_extends_through_an_equal_timestamp_run():
    """The cursor is a timestamp: a page boundary inside a run of
    records sharing one ts would make the strictly-greater resume skip
    the run's tail — drain() must extend the page through it."""
    with trace.span("eq.root"):
        pass
    recs = trace.snapshot(op="eq.root")
    base_ts = recs[0]["ts"]
    # forge a run of 4 records sharing one timestamp (concurrent
    # threads can produce this for real; forging keeps it deterministic)
    ring = trace._ring()
    for i in range(4):
        ring.store({"kind": "event", "op": "eq.run", "thread": "t",
                    "seq": 1000 + i, "parent": None,
                    "ts": base_ts + 1.0, "outcome": "ok",
                    "attrs": {"i": i}})
    page, more = trace.drain(0.0, limit=2, op="eq.")
    # limit 2 lands mid-run: the page extends through the whole run
    run = [r for r in page if r["op"] == "eq.run"]
    assert len(run) == 4 and more is False
    # a full drain loop loses nothing
    seen, cursor = [], 0.0
    while True:
        page, more = trace.drain(cursor, limit=2, op="eq.run")
        if not page:
            break
        seen += [r["attrs"]["i"] for r in page]
        cursor = page[-1]["ts"] * 1e3
    assert sorted(seen) == [0, 1, 2, 3]


def test_histogram_exemplars_carry_the_observing_spans_trace():
    with trace.span("ex.slow", histogram="tdp_attach_wall_ms") as sp:
        time.sleep(0.002)
        tid = trace.current_context()["trace_id"]
    del sp
    snap = trace.histogram("tdp_attach_wall_ms").snapshot()
    assert snap["exemplars"], snap
    assert any(ex["trace_id"] == tid for ex in snap["exemplars"])
    # the exemplar's trace resolves back to the span that observed it
    assert trace.snapshot(trace=tid)[0]["op"] == "ex.slow"


def test_dump_carries_histogram_snapshots_and_registered_extras(tmp_path):
    trace.observe("tdp_kubeapi_rtt_ms", 7.0)
    trace.register_dump_extra("extra_block", lambda: {"k": 1})
    trace.register_dump_extra("raising_extra",
                              lambda: (_ for _ in ()).throw(
                                  RuntimeError("post-mortem boom")))
    try:
        path = str(tmp_path / "dump.json")
        assert trace.dump("unit", path=path) == path
        with open(path) as f:
            payload = json.load(f)
        assert payload["histograms"]["tdp_kubeapi_rtt_ms"]["count"] == 1
        assert payload["extra_block"] == {"k": 1}
        # a raising extra degrades to an error note, never kills the dump
        assert "post-mortem boom" in payload["raising_extra"]["error"]
    finally:
        trace.unregister_dump_extra("extra_block")
        trace.unregister_dump_extra("raising_extra")


# ------------------------------------------------------------ concurrency


def test_concurrent_writers_and_reader_never_tear_or_duplicate():
    """The /debug/flight concurrency contract: writer threads appending
    while a reader drains must never produce torn or duplicated spans.
    Torn = a record missing required keys / partially built; duplicated =
    the same (thread, seq) twice in one snapshot."""
    n_threads, per_thread = 4, 400
    required = {"kind", "op", "thread", "seq", "ts", "outcome", "attrs"}
    stop = threading.Event()
    problems = []

    def writer(tid):
        for i in range(per_thread):
            with trace.span("t.conc", writer=tid, i=i):
                pass

    def reader():
        while not stop.is_set():
            snap = trace.snapshot(op="t.conc")
            seen = set()
            for rec in snap:
                if not required <= set(rec):
                    problems.append(("torn", rec))
                key = (rec["thread"], rec["seq"])
                if key in seen:
                    problems.append(("dup", key))
                seen.add(key)
                if rec["kind"] == "span" and "dur_ms" not in rec:
                    problems.append(("no-dur", rec))

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    rd = threading.Thread(target=reader)
    rd.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rd.join()
    assert problems == []
    # the final snapshot holds the last ring_size spans per writer thread
    final = trace.snapshot(op="t.conc")
    per_writer = {}
    for rec in final:
        per_writer.setdefault(rec["attrs"]["writer"], []).append(
            rec["attrs"]["i"])
    ring = trace.stats()["ring_size"]
    for tid, seen_is in per_writer.items():
        expect = list(range(per_thread - min(ring, per_thread), per_thread))
        assert sorted(seen_is) == expect, tid


def test_dead_thread_rings_are_bounded_and_charged_to_overwritten():
    """Thread churn (the idle-exiting checkpoint writer respawns per
    burst) must not accrete one ring per dead thread forever: only the
    newest _DEAD_RING_KEEP dead rings stay readable, and retired rings'
    records are charged to the overwritten counter (monotonic)."""
    for i in range(60):
        t = threading.Thread(target=lambda i=i: trace.event("t.short", i=i))
        t.start()
        t.join()
    stats = trace.stats()
    assert stats["rings"] <= trace._DEAD_RING_KEEP + 2, stats
    assert stats["events_recorded_total"] == 60
    # the NEWEST dead threads' records are still readable post-mortem
    recs = trace.snapshot(op="t.short")
    assert recs and recs[-1]["attrs"]["i"] == 59
    assert stats["spans_overwritten_total"] >= 60 - (
        trace._DEAD_RING_KEEP + 2)


def test_histogram_cells_are_adopted_across_thread_churn():
    """Same churn property for histogram shards: a new thread's first
    observe adopts a dead owner's cell (lossless — shards are sums), so
    the cell count is bounded by peak LIVE threads, not thread count."""
    hist = trace.Histogram("t_adopt_ms", "test", bounds=(1.0, 10.0))
    for _ in range(30):
        t = threading.Thread(target=lambda: hist.observe(0.5))
        t.start()
        t.join()
    snap = hist.snapshot()
    assert snap["count"] == 30          # adoption loses nothing
    assert snap["buckets"] == [(1.0, 30), (10.0, 30)]
    assert len(hist._cells) <= 3        # not one cell per dead thread


# ------------------------------------------------------------- histograms


def test_histogram_buckets_are_cumulative_and_exact_across_threads():
    hist = trace.Histogram("t_hist_ms", "test", bounds=(1.0, 10.0, 100.0))
    values = [0.5, 5.0, 50.0, 500.0]

    def worker():
        for v in values:
            hist.observe(v)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = hist.snapshot()
    assert snap["count"] == 8 * len(values)
    assert snap["sum"] == pytest.approx(8 * sum(values))
    assert snap["buckets"] == [(1.0, 8), (10.0, 16), (100.0, 24)]


def test_span_histogram_option_observes_duration():
    before = trace.histogram("tdp_attach_wall_ms").snapshot()["count"]
    with trace.span("t.timed", histogram="tdp_attach_wall_ms"):
        pass
    after = trace.histogram("tdp_attach_wall_ms").snapshot()
    assert after["count"] == before + 1
    assert after["sum"] > 0


def test_render_prometheus_histogram_families_are_well_formed():
    trace.observe("tdp_kubeapi_rtt_ms", 3.0)
    trace.observe("tdp_kubeapi_rtt_ms", 30000.0)   # beyond the last bound
    lines = trace.render_prometheus()
    text = "\n".join(lines)
    assert "# TYPE tdp_kubeapi_rtt_ms histogram" in text
    assert "# HELP tdp_kubeapi_rtt_ms" in text
    bucket_lines = [ln for ln in lines
                    if ln.startswith("tdp_kubeapi_rtt_ms_bucket")]
    # +Inf terminal bucket equals _count; cumulative monotone
    assert bucket_lines[-1] == 'tdp_kubeapi_rtt_ms_bucket{le="+Inf"} 2'
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts)
    assert "tdp_kubeapi_rtt_ms_count 2" in text
    assert any(ln.startswith("tdp_kubeapi_rtt_ms_sum ") for ln in lines)
    assert "tdp_trace_spans_total" in text


# ------------------------------------------------------ slow spans + logs


def test_slow_span_lands_in_slow_log_and_structured_logger(caplog):
    trace.configure(slow_ms=1.0)
    with caplog.at_level(logging.WARNING, logger="tpu_device_plugin.trace"):
        with trace.span("t.slow", claim_uid="u-slow"):
            time.sleep(0.005)
    slow = trace.slow_spans()
    assert [r["op"] for r in slow] == ["t.slow"]
    assert trace.stats()["slow_spans_total"] == 1
    assert any("slow span" in r.message and "t.slow" in r.message
               for r in caplog.records)


def test_per_op_threshold_overrides_global():
    trace.configure(slow_ms=0.0)               # everything is "slow"...
    old = trace.SLOW_THRESHOLDS_MS.get("t.fastpath")
    trace.SLOW_THRESHOLDS_MS["t.fastpath"] = 10_000.0
    try:
        with trace.span("t.fastpath"):
            pass
        assert trace.slow_spans() == []        # ...except the override
    finally:
        if old is None:
            trace.SLOW_THRESHOLDS_MS.pop("t.fastpath", None)
        else:
            trace.SLOW_THRESHOLDS_MS["t.fastpath"] = old


def test_log_formatters_carry_active_span_context():
    rec = logging.LogRecord("dra", logging.INFO, __file__, 1,
                            "prepared claim", (), None)
    with trace.span("t.ctx", claim_uid="u7", resource="tpu-v4"):
        kv = KeyValueFormatter().format(rec)
        js = json.loads(JsonFormatter().format(rec))
    assert "claim_uid=u7" in kv and "resource=tpu-v4" in kv
    assert js["ctx"] == {"claim_uid": "u7", "resource": "tpu-v4"}
    # outside a span: no context tail
    assert "claim_uid" not in KeyValueFormatter().format(rec)
    assert "ctx" not in json.loads(JsonFormatter().format(rec))


# ------------------------------------------------------- dump + crash hook


def test_dump_writes_ring_and_slow_log(tmp_path):
    with trace.span("t.dumped", claim_uid="u3"):
        pass
    path = str(tmp_path / "flight.json")
    assert trace.dump("unit-test", path=path) == path
    with open(path) as f:
        payload = json.load(f)
    assert payload["reason"] == "unit-test"
    assert any(r["op"] == "t.dumped" for r in payload["spans"])
    assert "stats" in payload and "slow" in payload


def test_crash_hook_dumps_and_chains(tmp_path, monkeypatch):
    # a cli test earlier in the session may have left the hook installed
    # (cli.main installs it; install is idempotent) — clear it so THIS
    # test's monkeypatched hook is the one being chained to
    trace.uninstall_crash_hook()
    path = str(tmp_path / "crash.json")
    monkeypatch.setenv("TDP_TRACE_DUMP_PATH", path)
    chained = []
    monkeypatch.setattr(sys, "excepthook", lambda *a: chained.append(a))
    trace.install_crash_hook()
    try:
        with trace.span("t.pre-crash"):
            pass
        try:
            raise ValueError("kaboom")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        assert os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["reason"] == "unhandled-exception:ValueError"
        assert any(r["op"] == "t.pre-crash" for r in payload["spans"])
        assert len(chained) == 1               # previous hook still ran
    finally:
        trace.uninstall_crash_hook()


# ------------------------------------------------------------ HTTP surface


class _StubManager:
    def __init__(self):
        self.running = threading.Event()
        self.plugins = []
        self.pending = []


def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def test_debug_flight_endpoint_serves_filtered_ring():
    from tpu_device_plugin.status import StatusServer
    server = StatusServer(_StubManager(), port=0)
    server.start()
    try:
        with trace.span("http.one", claim_uid="u-a", bdf="0000:00:04.0"):
            pass
        with trace.span("http.two", claim_uid="u-b"):
            pass
        body = _get_json(server.port, "/debug/flight")
        assert {"spans", "slow", "stats", "filters"} <= set(body)
        ops = [r["op"] for r in body["spans"]]
        assert "http.one" in ops and "http.two" in ops
        by_claim = _get_json(server.port, "/debug/flight?claim=u-a")
        assert [r["op"] for r in by_claim["spans"]] == ["http.one"]
        assert by_claim["filters"]["claim"] == "u-a"
        by_bdf = _get_json(server.port, "/debug/flight?bdf=0000:00:04.0")
        assert [r["op"] for r in by_bdf["spans"]] == ["http.one"]
        by_op = _get_json(server.port, "/debug/flight?op=http.&limit=1")
        assert [r["op"] for r in by_op["spans"]] == ["http.two"]
        # bad limit is a 400, not a stack trace
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(server.port, "/debug/flight?limit=bogus")
        assert err.value.code == 400
        # a BLANK filter value (typo'd $UID in an incident script) is a
        # 400 too — not a silent fall-through to the whole ring
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(server.port, "/debug/flight?claim=")
        assert err.value.code == 400
    finally:
        server.stop()


def test_debug_flight_trace_filter_pagination_and_fleet_endpoint():
    from tpu_device_plugin.status import StatusServer
    server = StatusServer(_StubManager(), port=0)
    server.start()
    try:
        with trace.span("fleet.origin", claim_uid="u-x"):
            tid = trace.current_context()["trace_id"]
            with trace.span("fleet.child"):
                pass
        with trace.span("fleet.noise"):
            pass
        # ?trace= narrows to the one trace
        body = _get_json(server.port, f"/debug/flight?trace={tid}")
        assert {r["op"] for r in body["spans"]} == \
            {"fleet.origin", "fleet.child"}
        # ?since_ms= pages oldest-first with a resumable cursor
        page = _get_json(server.port, "/debug/flight?since_ms=0&limit=2")
        assert len(page["spans"]) == 2 and page["more"] is True
        page2 = _get_json(
            server.port,
            f"/debug/flight?since_ms={page['next_since_ms']}&limit=2")
        assert page2["spans"] and not any(
            r["seq"] == page["spans"][-1]["seq"]
            and r["thread"] == page["spans"][-1]["thread"]
            for r in page2["spans"])
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(server.port, "/debug/flight?since_ms=bogus")
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(server.port, "/debug/flight?trace=")
        assert err.value.code == 400
        # the fleet endpoint serves the local ring under the fleet shape
        wf = _get_json(server.port, f"/debug/fleet/trace?trace={tid}")
        assert wf["trace"] == tid
        assert {r["op"] for r in wf["spans"]} == \
            {"fleet.origin", "fleet.child"}
        assert all(r["node"] == "local" for r in wf["spans"])
        assert wf["nodes"] == ["local"]
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(server.port, "/debug/fleet/trace")
        assert err.value.code == 400
    finally:
        server.stop()


def test_fleet_flight_merges_http_sources_and_degrades_on_failure():
    """FleetFlight over a REAL /debug/flight HTTP endpoint (the
    production source shape) + a dead source: the waterfall renders the
    answering node and notes the dead one."""
    from tpu_device_plugin.fleetplace import FleetFlight
    from tpu_device_plugin.status import StatusServer
    server = StatusServer(_StubManager(), port=0)
    server.start()
    try:
        with trace.span("hf.op", claim_uid="u-h"):
            tid = trace.current_context()["trace_id"]
        ff = FleetFlight()
        ff.add_http_source("node-a", f"http://127.0.0.1:{server.port}")
        ff.add_http_source("node-dead", "http://127.0.0.1:9/")  # refused
        story = ff.trace(tid)
        assert [r["op"] for r in story["spans"]] == ["hf.op"]
        assert story["spans"][0]["node"] == "node-a"
        assert "node-dead" in story["source_errors"]
        assert story["sources"] == 2
    finally:
        server.stop()


def test_status_carries_trace_stats():
    from tpu_device_plugin.status import StatusServer
    server = StatusServer(_StubManager(), port=0)
    try:
        with trace.span("s.one"):
            pass
        out = server.status()
        assert out["trace"]["spans_recorded_total"] >= 1
        assert out["trace"]["enabled"] is True
        text = server.metrics()
        assert "tdp_trace_spans_total" in text
        assert "tdp_attach_wall_ms_bucket" in text
    finally:
        server._httpd.server_close()


# -------------------------------------------------------- claim scenarios


@pytest.fixture()
def dra_rig(short_root):
    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 2))
    cfg = Config().with_root(short_root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    apiserver = FakeApiServer()
    driver = make_driver(cfg, apiserver)
    fsm = DeviceLifecycle()
    driver.attach_lifecycle(fsm)
    fsm.sync_inventory({f"0000:00:{4 + i:02x}.0": None for i in range(4)})
    yield host, cfg, apiserver, driver, fsm
    apiserver.stop()


def _claim_ops(uid):
    return [r["op"] for r in trace.snapshot(claim=uid)]


def test_claim_story_reconstructs_from_flight_filtered_by_uid(dra_rig):
    """ACCEPTANCE: prepare -> allocate -> hot-unplug orphan, reconstructed
    purely from the /debug/flight output filtered by claim UID."""
    from tpu_device_plugin.dra import slice_device_name
    _, _, apiserver, driver, fsm = dra_rig
    bdf = "0000:00:04.0"
    apiserver.add_claim("ns1", "c1", "uid-story", driver.driver_name,
                        [{"device": slice_device_name(bdf)}])
    claim = drapb.Claim(namespace="ns1", name="c1", uid="uid-story")
    resp = prepare(driver, claim)
    assert resp.claims["uid-story"].error == ""
    # hot-unplug the allocated chip (the FSM seam the lifecycle scenarios
    # drive; presence_reader is None so the event is trusted)
    fsm.note_fs_event(bdf, False)
    assert driver.orphaned_claims() == ["uid-story"]

    story = trace.snapshot(claim="uid-story")
    ops = [r["op"] for r in story]
    # the three acts, each present and in causal order:
    prepare_i = ops.index("dra.prepare.claim")
    alloc_i = ops.index("lifecycle.transition")     # bound -> allocated
    orphan_i = ops.index("lifecycle.claim.orphaned")
    assert story[alloc_i]["attrs"]["to"] == "allocated"
    assert story[alloc_i]["attrs"]["device"] == bdf
    assert prepare_i < orphan_i and alloc_i < orphan_i
    # the prepare decomposes: apiserver fetch + durability wait, each
    # carrying the claim uid by inheritance
    assert "kubeapi.request" in ops
    assert "dra.checkpoint.flush" in ops
    assert "dra.claim.orphaned" in ops
    # every record in the filtered story belongs to this claim
    for rec in story:
        assert rec["attrs"].get("claim_uid") == "uid-story"
    # and the whole story survives a JSON round-trip (the /debug/flight
    # transport) without loss
    assert json.loads(json.dumps(story)) == story


def test_armed_checkpoint_fault_shows_on_the_failing_claims_trace(dra_rig):
    """Chaos-run assertion: an armed checkpoint.write fault is visible as
    a fault event in the ring AND on the failing claim's filtered trace
    (the flush span errors with the injected fault text)."""
    from tpu_device_plugin.dra import slice_device_name
    _, _, apiserver, driver, fsm = dra_rig
    apiserver.add_claim("ns1", "c2", "uid-chaos", driver.driver_name,
                        [{"device": slice_device_name("0000:00:05.0")}])
    claim = drapb.Claim(namespace="ns1", name="c2", uid="uid-chaos")
    with faults.injected("checkpoint.write", count=1):
        resp = prepare(driver, claim)
    assert "injected fault at checkpoint.write" in \
        resp.claims["uid-chaos"].error
    # the fault event rides the commit span in the writer thread
    events = trace.snapshot(op="fault.checkpoint.write")
    assert events and events[0]["kind"] == "event"
    # the failing claim's trace carries the injected failure explicitly
    story = trace.snapshot(claim="uid-chaos")
    flush = [r for r in story if r["op"] == "dra.checkpoint.flush"]
    assert flush and flush[-1]["outcome"] == "error"
    assert "checkpoint.write" in flush[-1]["error"]
    claim_span = [r for r in story if r["op"] == "dra.prepare.claim"]
    assert claim_span and claim_span[-1]["outcome"] == "error"
    faults.reset()
