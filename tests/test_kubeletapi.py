"""Protocol layer: protobuf wire roundtrips + gRPC over a unix socket."""

import os
from concurrent import futures

import grpc
import pytest

from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.kubeletapi import pb


def test_device_roundtrip():
    d = pb.Device(
        ID="0000:00:05.0",
        health=api.HEALTHY,
        topology=pb.TopologyInfo(nodes=[pb.NUMANode(ID=1)]),
    )
    e = pb.Device.FromString(d.SerializeToString())
    assert e.ID == "0000:00:05.0"
    assert e.health == "Healthy"
    assert e.topology.nodes[0].ID == 1


def test_allocate_response_roundtrip():
    resp = pb.AllocateResponse(
        container_responses=[
            pb.ContainerAllocateResponse(
                envs={"PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4": "0000:00:05.0"},
                devices=[
                    pb.DeviceSpec(host_path="/dev/vfio/vfio",
                                  container_path="/dev/vfio/vfio",
                                  permissions="mrw"),
                ],
            )
        ]
    )
    e = pb.AllocateResponse.FromString(resp.SerializeToString())
    assert e.container_responses[0].envs[
        "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"] == "0000:00:05.0"
    assert e.container_responses[0].devices[0].permissions == "mrw"


class _EchoPlugin(api.DevicePluginServicer):
    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(get_preferred_allocation_available=True)

    def ListAndWatch(self, request, context):
        yield pb.ListAndWatchResponse(
            devices=[pb.Device(ID="d0", health=api.HEALTHY)])

    def Allocate(self, request, context):
        ids = list(request.container_requests[0].devices_ids)
        return pb.AllocateResponse(container_responses=[
            pb.ContainerAllocateResponse(envs={"IDS": ",".join(ids)})])


@pytest.fixture
def unix_server(tmp_path):
    sock = os.path.join(str(tmp_path), "plugin.sock")
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    api.add_device_plugin_servicer(server, _EchoPlugin())
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield sock
    server.stop(0)


def test_grpc_over_unix_socket(unix_server):
    with grpc.insecure_channel(f"unix://{unix_server}") as ch:
        stub = api.DevicePluginStub(ch)
        opts = stub.GetDevicePluginOptions(pb.Empty(), timeout=5)
        assert opts.get_preferred_allocation_available is True
        stream = stub.ListAndWatch(pb.Empty(), timeout=5)
        first = next(iter(stream))
        assert first.devices[0].ID == "d0"
        resp = stub.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["a", "b"])]),
            timeout=5)
        assert resp.container_responses[0].envs["IDS"] == "a,b"


class _Kubelet(api.RegistrationServicer):
    def __init__(self):
        self.requests = []

    def Register(self, request, context):
        self.requests.append(request)
        return pb.Empty()


def test_registration_service(tmp_path):
    sock = os.path.join(str(tmp_path), "kubelet.sock")
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    kubelet = _Kubelet()
    api.add_registration_servicer(server, kubelet)
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    try:
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            api.RegistrationStub(ch).Register(
                pb.RegisterRequest(version=api.API_VERSION,
                                   endpoint="tpukubevirt-v4.sock",
                                   resource_name="cloud-tpus.google.com/v4"),
                timeout=5)
        assert kubelet.requests[0].resource_name == "cloud-tpus.google.com/v4"
        assert kubelet.requests[0].version == "v1beta1"
    finally:
        server.stop(0)


def test_wire_contract_field_numbers():
    """Lock the kubelet v1beta1 wire contract as a test: field numbers and
    service names ARE the protocol (the reference vendors them from
    k8s.io/kubelet; any drift breaks interop with every kubelet)."""
    from tpu_device_plugin.kubeletapi import pb
    from tpu_device_plugin.kubeletapi.api import (
        _DEVICE_PLUGIN_SERVICE, _REGISTRATION_SERVICE, API_VERSION)

    def nums(msg):
        return {f.name: f.number for f in msg.DESCRIPTOR.fields}

    assert nums(pb.Device) == {"ID": 1, "health": 2, "topology": 3}
    assert nums(pb.TopologyInfo) == {"nodes": 1}
    assert nums(pb.NUMANode) == {"ID": 1}
    assert nums(pb.DeviceSpec) == {"container_path": 1, "host_path": 2,
                                   "permissions": 3}
    assert nums(pb.RegisterRequest) == {"version": 1, "endpoint": 2,
                                        "resource_name": 3, "options": 4}
    assert nums(pb.DevicePluginOptions) == {
        "pre_start_required": 1, "get_preferred_allocation_available": 2}
    cresp = nums(pb.ContainerAllocateResponse)
    assert cresp["envs"] == 1 and cresp["mounts"] == 2
    assert cresp["devices"] == 3 and cresp["annotations"] == 4
    assert cresp["cdi_devices"] == 5
    assert nums(pb.CDIDevice) == {"name": 1}
    assert nums(pb.ContainerAllocateRequest) == {"devices_ids": 1}
    pref = nums(pb.ContainerPreferredAllocationRequest)
    assert pref == {"available_deviceIDs": 1, "must_include_deviceIDs": 2,
                    "allocation_size": 3}
    assert _DEVICE_PLUGIN_SERVICE == "v1beta1.DevicePlugin"
    assert _REGISTRATION_SERVICE == "v1beta1.Registration"
    assert API_VERSION == "v1beta1"


def test_dra_wire_contract_field_numbers():
    """Lock the DRA v1beta1 + pluginregistration v1 wire contracts: the
    local descriptor package differs from upstream (see
    proto/dra_v1beta1.proto for why), so the method paths and field
    numbers asserted here are the ONLY wire-visible surface — they must
    match the published k8s.io/kubelet contracts exactly."""
    from tpu_device_plugin.kubeletapi import drapb, regpb
    from tpu_device_plugin.kubeletapi.draapi import (
        _DRA_SERVICE, _PLUGIN_REGISTRATION_SERVICE, DRA_API_VERSION,
        DRA_PLUGIN_TYPE)

    def nums(msg):
        return {f.name: f.number for f in msg.DESCRIPTOR.fields}

    assert nums(drapb.Claim) == {"namespace": 1, "uid": 2, "name": 3}
    assert nums(drapb.Device) == {"request_names": 1, "pool_name": 2,
                                  "device_name": 3, "cdi_device_ids": 4}
    assert nums(drapb.NodePrepareResourcesRequest) == {"claims": 1}
    assert nums(drapb.NodePrepareResourcesResponse) == {"claims": 1}
    assert nums(drapb.NodePrepareResourceResponse) == {"devices": 1,
                                                       "error": 2}
    assert nums(drapb.NodeUnprepareResourcesRequest) == {"claims": 1}
    assert nums(drapb.NodeUnprepareResourcesResponse) == {"claims": 1}
    assert nums(drapb.NodeUnprepareResourceResponse) == {"error": 1}
    assert nums(regpb.PluginInfo) == {"type": 1, "name": 2, "endpoint": 3,
                                      "supported_versions": 4}
    assert nums(regpb.RegistrationStatus) == {"plugin_registered": 1,
                                              "error": 2}
    assert _DRA_SERVICE == "v1beta1.DRAPlugin"
    assert _PLUGIN_REGISTRATION_SERVICE == "pluginregistration.Registration"
    assert DRA_API_VERSION == "v1beta1"
    assert DRA_PLUGIN_TYPE == "DRAPlugin"
