"""Persisted discovery snapshot durability (ISSUE 19).

The cache is derived data with zero tolerance for trust errors: a
rejected envelope must NEVER reach a plugin table (fallback = the
counted cold walk re-derives everything), and the write must be
crash-safe (temp + fsync + rename beside the DRA checkpoint) so a
reader observes either the old envelope or the new one, never a torn
write. Boot-level trust rules live in lifecycle.start(); this file
pins the envelope mechanics underneath them.
"""

import json
import os

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import faults
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import HostSnapshot, count_reads


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    yield
    faults.reset()


def _host(root, n=8):
    host = FakeHost(root)
    for i in range(n):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 4))
    return host


def _seed(root, n=8):
    """Scanned snapshot + saved cache; returns (cfg, cache_path)."""
    _host(root, n)
    cfg = Config().with_root(str(root))
    path = os.path.join(str(root), "discovery-snapshot.json")
    snap = HostSnapshot(cfg)
    snap.rescan()
    assert snap.save_cache(path)
    return cfg, path


def test_roundtrip_loads_and_revalidates_with_few_reads(tmp_path):
    cfg, path = _seed(tmp_path)
    snap = HostSnapshot(cfg)
    assert snap.load_cache(path) == "loaded"
    with count_reads() as counter:
        assert snap.revalidate() == set()
    # shallow tier only: membership listdirs + bus signature, not a
    # per-device walk (the 10x boot pin rides on this staying tiny)
    assert counter.reads <= 8, counter.paths
    registry, _ = snap.build_excluding(())
    assert len(registry.all_devices()) == 8


def test_unscanned_snapshot_refuses_to_save(tmp_path):
    _host(tmp_path)
    cfg = Config().with_root(str(tmp_path))
    snap = HostSnapshot(cfg)
    path = os.path.join(str(tmp_path), "discovery-snapshot.json")
    assert not snap.save_cache(path)
    assert not os.path.exists(path)


def test_save_is_atomic_replace_with_no_temp_residue(tmp_path,
                                                     monkeypatch):
    cfg, path = _seed(tmp_path)
    directory = os.path.dirname(path)
    # a crash at the rename boundary (ENOSPC, kill) leaves the OLD
    # envelope intact and no temp file behind
    before = open(path).read()
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("crash mid-write")

    monkeypatch.setattr(os, "replace", boom)
    snap = HostSnapshot(cfg)
    snap.rescan()
    assert not snap.save_cache(path)
    monkeypatch.setattr(os, "replace", real_replace)
    assert open(path).read() == before
    residue = [f for f in os.listdir(directory)
               if f.startswith(".snapshot-")]
    assert residue == [], residue
    # the old envelope still loads — a failed save costs nothing now
    assert HostSnapshot(cfg).load_cache(path) == "loaded"


def test_truncated_cache_refused_then_replaced_by_cold_walk(tmp_path):
    cfg, path = _seed(tmp_path)
    with open(path, "w") as f:
        f.write('{"version": 1, "records": {"0000:00')   # torn write
    snap = HostSnapshot(cfg)
    assert snap.load_cache(path) == "corrupt"
    assert snap.stats["snapshot_fallbacks"] == 1
    # fallback pays the counted cold walk, then re-seeds atomically
    with count_reads() as counter:
        registry, _ = snap.rescan()
    assert len(registry.all_devices()) == 8
    assert counter.reads >= 8 * 5
    assert snap.save_cache(path)
    assert HostSnapshot(cfg).load_cache(path) == "loaded"


def test_future_version_refused(tmp_path):
    cfg, path = _seed(tmp_path)
    with open(path) as f:
        env = json.load(f)
    env["version"] = 99
    with open(path, "w") as f:
        json.dump(env, f)
    # future versions refuse like past ones: derived data has no
    # migration ladder, one cold walk re-derives everything
    assert HostSnapshot(cfg).load_cache(path) == "version"


def test_signature_version_mismatch_refused(tmp_path):
    cfg, path = _seed(tmp_path)
    with open(path) as f:
        env = json.load(f)
    env["signature_version"] = -1
    with open(path, "w") as f:
        json.dump(env, f)
    assert HostSnapshot(cfg).load_cache(path) == "signature"


def test_missing_cache_is_quiet_fallback(tmp_path):
    _host(tmp_path)
    cfg = Config().with_root(str(tmp_path))
    snap = HostSnapshot(cfg)
    assert snap.load_cache(
        os.path.join(str(tmp_path), "nope.json")) == "missing"
    assert snap.stats["snapshot_fallbacks"] == 1


def test_fault_site_forces_cold_then_recovers(tmp_path):
    """`discovery.snapshot` armed: the load reads as untrusted (the
    torn-write/unreadable failure mode on demand) and the fallback
    counter ticks; once the fault exhausts, the SAME file loads."""
    cfg, path = _seed(tmp_path)
    faults.arm("discovery.snapshot", kind="drop", count=1)
    snap = HostSnapshot(cfg)
    assert snap.load_cache(path) == "fault"
    assert snap.stats["snapshot_fallbacks"] == 1
    assert snap.load_cache(path) == "loaded"


def test_revalidate_detects_membership_change_and_taints_model(tmp_path):
    """A device dir that vanished between boots invalidates on the
    shallow membership pass, and taint_groups expands to every sibling
    of its model — wave 1 must not ship a half-validated resource."""
    cfg, path = _seed(tmp_path)
    import shutil
    shutil.rmtree(os.path.join(cfg.pci_base_path, "0000:00:04.0"))
    snap = HostSnapshot(cfg)
    assert snap.load_cache(path) == "loaded"
    invalidated = snap.revalidate()
    assert "0000:00:04.0" in invalidated
    tainted = snap.taint_groups(invalidated)
    # all 8 seeded chips share device_id 0063 -> the whole model taints
    assert len(tainted) == 8
    registry, _ = snap.build_excluding(tainted)
    assert registry.all_devices() == []
