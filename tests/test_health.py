"""Health machinery: inotify watcher, monitor callbacks, native shim parity."""

import os
import subprocess
import threading
import time

import pytest

from tpu_device_plugin.health import HealthMonitor, InotifyWatcher
from tpu_device_plugin.native import DEAD, MISSING, OK, TpuHealth


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_inotify_create_delete(tmp_path):
    w = InotifyWatcher()
    w.watch_dir(str(tmp_path))
    try:
        f = tmp_path / "node"
        f.write_text("")
        events = w.poll(1.0)
        assert any(name == "node" and mask & 0x100 for _, name, mask in events)
        f.unlink()
        events = w.poll(1.0)
        assert any(name == "node" and mask & 0x200 for _, name, mask in events)
    finally:
        w.close()


def test_monitor_group_node_lifecycle(tmp_path):
    vfio = tmp_path / "dev" / "vfio"
    vfio.mkdir(parents=True)
    (vfio / "7").write_text("")
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={"7": str(vfio / "7")},
        group_bdfs={"7": ["0000:00:04.0"]},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: hits.append(("SOCKET", None, None)),
    )
    mon.start()
    try:
        (vfio / "7").unlink()
        assert _wait(lambda: ("7", False, "fs") in hits)
        (vfio / "7").write_text("")
        assert _wait(lambda: ("7", True, "fs") in hits)
        sock.unlink()
        assert _wait(lambda: ("SOCKET", None, None) in hits)
        assert _wait(lambda: not mon.is_alive())
    finally:
        mon.stop_event.set()


def test_monitor_probe_drives_health(tmp_path):
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    verdict = {"ok": True}
    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={},
        group_bdfs={"g": ["bdf0"]},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: None,
        probe=lambda bdf, node: verdict["ok"],
        poll_interval_s=0.1,
    )
    mon.start()
    try:
        assert _wait(lambda: ("g", True, "probe") in hits)
        verdict["ok"] = False
        assert _wait(lambda: ("g", False, "probe") in hits)
    finally:
        mon.stop_event.set()


def test_monitor_probe_exception_scores_unhealthy_not_thread_death(tmp_path):
    """Satellite bugfix: a raising probe used to propagate out of run() and
    silently kill the monitor thread. It must score the group Unhealthy,
    bump probe_errors (the tdp_probe_errors_total seam), and keep the
    monitor alive — a later clean probe recovers the group."""
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    behavior = {"raise": True}

    def probe(bdf, node):
        if behavior["raise"]:
            raise RuntimeError("sysfs went away mid-read")
        return True

    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={},
        group_bdfs={"g": ["bdf0"]},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: None,
        probe=probe,
        poll_interval_s=0.1,
    )
    mon.start()
    try:
        assert _wait(lambda: ("g", False, "probe") in hits)
        assert mon.is_alive(), "probe exception killed the monitor thread"
        assert mon.probe_errors >= 1
        behavior["raise"] = False
        assert _wait(lambda: ("g", True, "probe") in hits)
        assert mon.is_alive()
    finally:
        mon.stop_event.set()


def _quiesce_health_threads(timeout=3.0):
    """Wait out stray monitor/hub threads from earlier tests: the partial-
    event tests monkeypatch module-global select/os.read, and a straggler
    polling concurrently would consume the scripted chunks."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not any(t.name.startswith(("health-", "healthhub"))
                   for t in threading.enumerate()):
            return
        time.sleep(0.05)


def test_inotify_partial_trailing_event_carried_across_reads(monkeypatch):
    """Satellite bugfix: an event split at the 64 KiB read boundary (header
    or name truncated) must be carried into the next read, not discarded."""
    import struct

    from tpu_device_plugin import health as health_mod

    _quiesce_health_threads()
    w = InotifyWatcher()
    try:
        w.watch_dir("/tmp")
        wd = next(iter(w._wd_to_dir))
        name = b"node-x\0\0"
        whole = (struct.pack("iIII", wd, 0x100, 0, len(name)) + name
                 + struct.pack("iIII", wd, 0x200, 0, len(name)) + name)
        # split mid-way through the SECOND event's name bytes
        cut = len(whole) - 3
        chunks = [whole[:cut], whole[cut:]]
        monkeypatch.setattr(health_mod.select, "select",
                            lambda r, _w, x, t: (r, [], []))
        monkeypatch.setattr(health_mod.os, "read",
                            lambda fd, n: chunks.pop(0))
        first = w.poll(0)
        assert [(n, m) for _, n, m in first] == [("node-x", 0x100)]
        assert w._pending, "partial trailing event was discarded"
        second = w.poll(0)
        assert [(n, m) for _, n, m in second] == [("node-x", 0x200)]
        assert w._pending == b""
    finally:
        monkeypatch.undo()
        w.close()


def test_inotify_partial_header_carried(monkeypatch):
    """Even a split inside the 16-byte event header must survive the
    boundary."""
    import struct

    from tpu_device_plugin import health as health_mod

    _quiesce_health_threads()
    w = InotifyWatcher()
    try:
        w.watch_dir("/tmp")
        wd = next(iter(w._wd_to_dir))
        name = b"n\0\0\0"
        whole = struct.pack("iIII", wd, 0x100, 0, len(name)) + name
        chunks = [whole[:7], whole[7:]]  # cut inside the header
        monkeypatch.setattr(health_mod.select, "select",
                            lambda r, _w, x, t: (r, [], []))
        monkeypatch.setattr(health_mod.os, "read",
                            lambda fd, n: chunks.pop(0))
        assert w.poll(0) == []
        events = w.poll(0)
        assert [(n, m) for _, n, m in events] == [("n", 0x100)]
    finally:
        monkeypatch.undo()
        w.close()


# --- native shim -------------------------------------------------------------

@pytest.fixture(scope="session")
def native_lib(tmp_path_factory):
    """Build libtpuhealth.so with g++; skip native tests if no compiler."""
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "tpuhealth.cpp")
    out = str(tmp_path_factory.mktemp("native") / "libtpuhealth.so")
    try:
        subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-o", out, src, "-ldl"],
                       check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as exc:
        pytest.skip(f"cannot build native shim: {exc}")
    return out


@pytest.fixture(params=["native", "fallback"])
def shim(request, native_lib):
    if request.param == "native":
        t = TpuHealth(native_lib)
        assert t.is_native
        return t
    return TpuHealth("/nonexistent/libtpuhealth.so")


def test_probe_config_verdicts(shim, tmp_path):
    alive = tmp_path / "config_alive"
    alive.write_bytes(bytes([0xE0, 0x1A, 0x00, 0x00]))  # vendor 0x1ae0 LE
    assert shim.probe_config(str(alive)) == OK
    dead = tmp_path / "config_dead"
    dead.write_bytes(bytes([0xFF, 0xFF, 0xFF, 0xFF]))
    assert shim.probe_config(str(dead)) == DEAD
    zero = tmp_path / "config_zero"
    zero.write_bytes(bytes([0x00, 0x00]))
    assert shim.probe_config(str(zero)) == DEAD
    truncated = tmp_path / "config_trunc"
    truncated.write_bytes(b"\x01")
    assert shim.probe_config(str(truncated)) == DEAD
    assert shim.probe_config(str(tmp_path / "missing")) == MISSING


def test_probe_node_verdicts(shim, tmp_path):
    node = tmp_path / "accel0"
    node.write_text("")
    assert shim.probe_node(str(node)) == OK
    assert shim.probe_node(str(tmp_path / "gone")) == MISSING


def test_chip_alive_composite(shim, tmp_path):
    pci = tmp_path / "devices"
    bdf_dir = pci / "0000:00:04.0"
    bdf_dir.mkdir(parents=True)
    # no config file but device dir exists (fixture tree) -> alive
    assert shim.chip_alive(str(pci), "0000:00:04.0") is True
    (bdf_dir / "config").write_bytes(bytes([0xE0, 0x1A]))
    assert shim.chip_alive(str(pci), "0000:00:04.0") is True
    (bdf_dir / "config").write_bytes(bytes([0xFF, 0xFF]))
    assert shim.chip_alive(str(pci), "0000:00:04.0") is False
    # whole device vanished -> dead
    assert shim.chip_alive(str(pci), "0000:00:99.0") is False


def test_shared_node_fans_out_to_all_keys(tmp_path):
    """Logical partitions share one /dev/accelN: its removal must mark ALL
    of them unhealthy, not just the last-registered one."""
    dev = tmp_path / "dev"
    dev.mkdir()
    (dev / "accel0").write_text("")
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={"bdf-core0": str(dev / "accel0"),
                     "bdf-core1": str(dev / "accel0")},
        group_bdfs={},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: None,
    )
    mon.start()
    try:
        (dev / "accel0").unlink()
        assert _wait(lambda: ("bdf-core0", False, "fs") in hits)
        assert _wait(lambda: ("bdf-core1", False, "fs") in hits)
    finally:
        mon.stop_event.set()


def test_reconciliation_catches_eventless_changes(tmp_path):
    """sysfs emits no inotify events; the periodic existence scan must flag
    removals anyway, and flip nodes back when they reappear."""
    watched = tmp_path / "nodes"
    watched.mkdir()
    (watched / "n1").write_text("")
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={"g1": str(watched / "n1"),
                     # node whose parent dir doesn't exist yet at start
                     "g2": str(tmp_path / "late" / "n2")},
        group_bdfs={},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: None,
        poll_interval_s=0.2,
    )
    # polling mode: skip HealthMonitor.start() (which sets up inotify) and
    # run the thread directly with no watcher, as on an event-less fs
    assert mon._watcher is None
    threading.Thread.start(mon)
    try:
        assert _wait(lambda: ("g2", False, "fs") in hits)  # missing at start
        (tmp_path / "late").mkdir()
        (tmp_path / "late" / "n2").write_text("")
        assert _wait(lambda: ("g2", True, "fs") in hits)   # appeared later
        (watched / "n1").unlink()
        assert _wait(lambda: ("g1", False, "fs") in hits)  # removed, no event
    finally:
        mon.stop_event.set()


def test_reconciliation_in_watcher_mode(tmp_path):
    """Even with inotify active, a node in an unwatched (late) dir must be
    picked up by the periodic scan."""
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    hits = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={"g": str(tmp_path / "late" / "node")},
        group_bdfs={},
        on_device_health=lambda g, ok, src: hits.append((g, ok, src)),
        on_socket_removed=lambda: None,
        poll_interval_s=0.2,
    )
    mon.start()
    try:
        assert _wait(lambda: ("g", False, "fs") in hits)
        (tmp_path / "late").mkdir()
        (tmp_path / "late" / "node").write_text("")
        assert _wait(lambda: ("g", True, "fs") in hits)
    finally:
        mon.stop_event.set()


def test_foreign_so_falls_back(tmp_path):
    """A loadable .so without our symbols must degrade to the Python probe."""
    import ctypes.util
    libm = ctypes.util.find_library("m") or "/lib/x86_64-linux-gnu/libm.so.6"
    t = TpuHealth(libm)
    assert t.is_native is False
    # fallback still functional
    cfgf = tmp_path / "config"
    cfgf.write_bytes(bytes([0xE0, 0x1A]))
    assert t.probe_config(str(cfgf)) == OK


def test_chip_alive_ands_node_probe(shim, tmp_path):
    """Native verdict must also cover the chip's device node, so a vanished
    node flips health even when the inotify watcher is degraded."""
    pci = tmp_path / "devices"
    bdf_dir = pci / "0000:00:04.0"
    bdf_dir.mkdir(parents=True)
    (bdf_dir / "config").write_bytes(bytes([0xE0, 0x1A]))
    node = tmp_path / "vfio11"
    assert shim.chip_alive(str(pci), "0000:00:04.0", str(node)) is False
    node.write_text("")
    assert shim.chip_alive(str(pci), "0000:00:04.0", str(node)) is True


def test_monitor_probe_receives_group_node_path(tmp_path):
    sock_dir = tmp_path / "plugins"
    sock_dir.mkdir()
    sock = sock_dir / "p.sock"
    sock.write_text("")
    node = tmp_path / "vfio11"
    node.write_text("")
    seen = []
    mon = HealthMonitor(
        socket_path=str(sock),
        group_paths={"g": str(node)},
        group_bdfs={"g": ["bdf0"]},
        on_device_health=lambda g, ok, src: None,
        on_socket_removed=lambda: None,
        probe=lambda bdf, n: seen.append((bdf, n)) or True,
        poll_interval_s=0.1,
    )
    mon.start()
    try:
        assert _wait(lambda: ("bdf0", str(node)) in seen)
    finally:
        mon.stop_event.set()


def test_pci_status_register(shim, tmp_path):
    """Offset-6 status read: clean register, latched error bits, unreadable."""
    from tpu_device_plugin.native import PCI_STATUS_ERROR_MASK
    cfgf = tmp_path / "config"
    # 6 bytes header + status 0x0010 (cap list bit, no errors)
    cfgf.write_bytes(bytes([0xE0, 0x1A, 0x00, 0x00, 0x06, 0x04, 0x10, 0x00]))
    assert shim.pci_status(str(cfgf)) == 0x0010
    bdf_dir = tmp_path / "devices" / "0000:00:04.0"
    bdf_dir.mkdir(parents=True)
    (bdf_dir / "config").write_bytes(
        bytes([0xE0, 0x1A, 0, 0, 0, 0]) + (0x2010).to_bytes(2, "little"))
    # received-master-abort (bit 13) is in the mask; cap-list bit is not
    assert shim.chip_error_bits(str(tmp_path / "devices"),
                                "0000:00:04.0") == 0x2000
    assert 0x2000 & PCI_STATUS_ERROR_MASK
    # unreadable/truncated -> None / 0 (never an exception)
    assert shim.pci_status(str(tmp_path / "missing")) is None
    (bdf_dir / "config").write_bytes(b"\x01\x02")
    assert shim.chip_error_bits(str(tmp_path / "devices"), "0000:00:04.0") == 0


def test_chip_alive_logs_error_bits_once(shim, tmp_path, caplog):
    """Latched bus errors warn on change, never veto health."""
    import logging
    pci = tmp_path / "devices"
    bdf_dir = pci / "0000:00:04.0"
    bdf_dir.mkdir(parents=True)
    (bdf_dir / "config").write_bytes(
        bytes([0xE0, 0x1A, 0, 0, 0, 0]) + (0x4000).to_bytes(2, "little"))
    with caplog.at_level(logging.WARNING):
        assert shim.chip_alive(str(pci), "0000:00:04.0") is True
        assert shim.chip_alive(str(pci), "0000:00:04.0") is True
    warnings = [r for r in caplog.records if "error bits" in r.message]
    assert len(warnings) == 1  # logged on change only
    assert "0x4000" in warnings[0].message


def test_pci_status_error_paths(shim, tmp_path):
    """Unreadable/short/off-bus status reads never fabricate error bits."""
    import os
    # truncated at offset 6 -> native returns negative -> None
    short = tmp_path / "short_config"
    short.write_bytes(b"\x01\x02")
    assert shim.pci_status(str(short)) is None
    # all-FF (chip off the bus) -> status reads 0xFFFF -> bits suppressed
    pci = tmp_path / "ffdev"
    bdf = pci / "0000:00:04.0"
    bdf.mkdir(parents=True)
    (bdf / "config").write_bytes(b"\xff" * 8)
    assert shim.pci_status(str(bdf / "config")) == 0xFFFF
    assert shim.chip_error_bits(str(pci), "0000:00:04.0") == 0
    # unreadable (permissions) -> None on the native path too
    locked = tmp_path / "locked_config"
    locked.write_bytes(b"\x00" * 8)
    os.chmod(locked, 0)
    if os.geteuid() != 0:  # root bypasses permissions
        assert shim.pci_status(str(locked)) is None


def _pcie_config(cur_speed, cur_width, max_speed, max_width,
                 cap_at=0x40, vendor=(0xE0, 0x1A)) -> bytes:
    """A minimal 256-byte PCI config blob with one PCIe capability."""
    cfg = bytearray(256)
    cfg[0], cfg[1] = vendor
    cfg[0x06] = 0x10                       # status: capability list present
    cfg[0x34] = cap_at                     # first capability pointer
    cfg[cap_at] = 0x10                     # PCI Express capability id
    cfg[cap_at + 1] = 0x00                 # end of chain
    linkcap = (max_speed & 0xF) | ((max_width & 0x3F) << 4)
    cfg[cap_at + 0x0C:cap_at + 0x10] = linkcap.to_bytes(4, "little")
    linkstat = (cur_speed & 0xF) | ((cur_width & 0x3F) << 4)
    cfg[cap_at + 0x12:cap_at + 0x14] = linkstat.to_bytes(2, "little")
    return bytes(cfg)


def test_pcie_link_full_speed(shim, tmp_path):
    cfg = tmp_path / "config"
    cfg.write_bytes(_pcie_config(4, 16, 4, 16))
    link = shim.pcie_link(str(cfg))
    assert link == {"cur_speed": 4, "cur_width": 16,
                    "max_speed": 4, "max_width": 16}


def test_pcie_link_degraded_detected(shim, tmp_path):
    pci = tmp_path / "devices"
    bdf = pci / "0000:00:04.0"
    bdf.mkdir(parents=True)
    # trained gen1 x8 on a gen4 x16 part: degraded on both axes
    (bdf / "config").write_bytes(_pcie_config(1, 8, 4, 16))
    assert shim.chip_link_degraded(str(pci), "0000:00:04.0") is True
    # liveness must NOT be vetoed by a degraded link
    assert shim.chip_alive(str(pci), "0000:00:04.0") is True
    (bdf / "config").write_bytes(_pcie_config(4, 16, 4, 16))
    assert shim.chip_link_degraded(str(pci), "0000:00:04.0") is False


def test_pcie_link_capability_chain_walk(shim, tmp_path):
    """PCIe capability found behind another capability in the chain."""
    cfg = bytearray(_pcie_config(3, 8, 3, 8, cap_at=0x60))
    cfg[0x34] = 0x40
    cfg[0x40] = 0x01       # PM capability first
    cfg[0x41] = 0x60       # -> PCIe capability next
    p = tmp_path / "config"
    p.write_bytes(bytes(cfg))
    link = shim.pcie_link(str(p))
    assert link and link["cur_width"] == 8 and link["max_speed"] == 3


def test_pcie_link_unreachable_cases(shim, tmp_path):
    # fixture-tree config too short for the capability area
    short = tmp_path / "short"
    short.write_bytes(bytes([0xE0, 0x1A]))
    assert shim.pcie_link(str(short)) is None
    # no capability list bit
    nocap = tmp_path / "nocap"
    nocap.write_bytes(bytes(256))
    assert shim.pcie_link(str(nocap)) is None
    # off-bus chip
    dead = tmp_path / "dead"
    dead.write_bytes(b"\xff" * 256)
    assert shim.pcie_link(str(dead)) is None
    assert shim.pcie_link(str(tmp_path / "missing")) is None
    # degraded check never vetoes or errors on unreachable links
    pci = tmp_path / "devices"
    (pci / "0000:00:05.0").mkdir(parents=True)
    assert shim.chip_link_degraded(str(pci), "0000:00:05.0") is False
