"""Hand-rolled gRPC service/stub wiring for the kubelet v1beta1 API.

grpcio's generic handler API lets us register method handlers without
generated service stubs. Method paths (`/v1beta1.DevicePlugin/...`) and the
constants below are part of the kubelet contract (reference:
vendor/k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/constants.go:19-46).
"""

from __future__ import annotations

import grpc

from . import deviceplugin_v1beta1_pb2 as pb

# -- kubelet contract constants ------------------------------------------------
API_VERSION = "v1beta1"
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"
KUBELET_SOCKET = DEVICE_PLUGIN_PATH + "kubelet.sock"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

_DEVICE_PLUGIN_SERVICE = "v1beta1.DevicePlugin"
_REGISTRATION_SERVICE = "v1beta1.Registration"


class DevicePluginServicer:
    """Server-side interface for the DevicePlugin service (5 RPCs)."""

    def GetDevicePluginOptions(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetDevicePluginOptions")

    def ListAndWatch(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "ListAndWatch")

    def GetPreferredAllocation(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetPreferredAllocation")

    def Allocate(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Allocate")

    def PreStartContainer(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "PreStartContainer")


def add_device_plugin_servicer(server: grpc.Server, servicer: DevicePluginServicer) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_DEVICE_PLUGIN_SERVICE, handlers),)
    )


class DevicePluginStub:
    """Client stub for the DevicePlugin service (what the kubelet dials)."""

    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{_DEVICE_PLUGIN_SERVICE}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


class RegistrationServicer:
    """Server-side interface for the Registration service (fake kubelet in tests)."""

    def Register(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Register")


def add_registration_servicer(server: grpc.Server, servicer: RegistrationServicer) -> None:
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_REGISTRATION_SERVICE, handlers),)
    )


class RegistrationStub:
    """Client stub for the kubelet Registration service (the plugin dials this)."""

    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{_REGISTRATION_SERVICE}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )
