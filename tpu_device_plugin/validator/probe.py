"""Slice validation probe: the north-star measurement.

BASELINE.md's metric is "VMI TPU-attach → jax.devices() latency; chips
allocatable/node". Inside the guest this module measures the guest-side
portion: process start → backend init → `jax.devices()` enumerated → first
compiled training step done, then burns the slice in and reports per-chip
throughput. Exit code is non-zero when the slice is unusable, so a VMI
startup probe can gate workload admission on it.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_PROCESS_START = time.monotonic()


@dataclass
class SliceReport:
    ok: bool
    platform: str = ""
    n_devices: int = 0
    device_kinds: List[str] = field(default_factory=list)
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    devices_visible_s: float = 0.0   # process start -> jax.devices() returned
    first_step_s: float = 0.0        # process start -> first compiled step done
    step_time_s: float = 0.0         # steady-state step latency
    tflops_per_chip: float = 0.0     # burn-in matmul throughput
    matmul_tflops: float = 0.0       # peak-ish single-chip bf16 matmul
    hbm_gbps: float = 0.0            # single-chip memory bandwidth estimate
    loss_start: float = 0.0
    loss_end: float = 0.0
    # physics context (validator/peaks.py): datasheet peaks for the chip
    # generation and every throughput as a fraction of them. 0 = unknown
    # generation (CPU tests, future chips) — fractions only exist when the
    # denominator is a datasheet fact.
    peak_tflops: float = 0.0         # per-chip datasheet bf16 peak
    peak_hbm_gbps: float = 0.0       # per-chip datasheet HBM bandwidth
    mfu: float = 0.0                 # tflops_per_chip / peak (train mode)
    microbench_mfu: float = 0.0      # matmul_tflops / peak
    hbm_frac: float = 0.0            # hbm_gbps / peak_hbm_gbps
    # True when a microbench reading exceeded ~1.05x the datasheet peak:
    # the measurement is a timing artifact and the run is REFUSED as ok
    # (VERDICT r3: an impossible 289 TF on a 197 TF-peak v5e must never
    # again be recorded as a valid result)
    perf_suspect: bool = False
    # serving mode (--mode infer): forward-only latency percentiles
    infer_p50_ms: float = 0.0
    infer_p99_ms: float = 0.0
    tokens_per_s: float = 0.0
    # True when the failure is the CALLER's configuration (bad flag combo
    # only detectable once the mesh is known), not a broken slice — probes
    # gating VMI admission must not treat these as hardware failures
    invalid_config: bool = False
    error: str = ""

    def to_json(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True)


def _workload_flops(cfg) -> float:
    """Model training FLOPs per step (fwd+bwd ~= 3x fwd matmul FLOPs).

    Counts CAUSAL attention (S*d MACs per token, not the dense 2*S*d): the
    flash kernel skips future blocks outright and the einsum path's masked
    upper triangle is waste, not work — counting it would inflate MFU by
    the attention term's share. MFU derived from this is therefore the
    conservative "model FLOPs" convention (remat's extra forward also
    uncounted)."""
    per_token = (
        4 * cfg.d_model * cfg.d_model        # qkv+o projections
        + cfg.d_model * cfg.seq_len          # causal scores + values
        + 2 * cfg.d_model * cfg.d_ff         # mlp
    ) * 2 * cfg.n_layers + 2 * cfg.d_model * cfg.vocab * 2
    return 3.0 * per_token * cfg.batch * cfg.seq_len


def _diff_time(make_chain, arg, n: int, min_diff_s: float = 0.0) -> float:
    """Per-iteration seconds of a chained computation by paired-repeats
    differencing — thin adapter over the shared estimator
    (validator/timing.py, also used by attn_bench) so the methodology
    cannot drift between the two benchmark surfaces."""
    from .timing import paired_time
    return paired_time(make_chain, (arg,), 3, n, min_diff_s=min_diff_s)


# Minimum differenced compute time (seconds) for a trustworthy microbench
# reading on real hardware: the relay's run-to-run jitter is ms-scale, so
# the signal must stand ~100x above it. timing.paired_time grows the chain
# length to reach this.
MICROBENCH_MIN_DIFF_S = 0.25


def _microbench(device, min_diff_s: float = None) -> tuple:
    """Single-chip sanity numbers: bf16 matmul TFLOP/s and memory GB/s.

    Small enough to finish in seconds; meant to catch a chip running at a
    fraction of expected speed (thermal clamp, degraded HBM), not to be a
    rigorous peak benchmark. Uses chained differencing (_diff_time) with a
    minimum-differenced-time floor so neither the relay's fixed sync cost
    nor its jitter can masquerade as (or hide) compute time.
    """
    import jax
    import jax.numpy as jnp
    on_tpu = device.platform == "tpu"
    if min_diff_s is None:
        min_diff_s = MICROBENCH_MIN_DIFF_S if on_tpu else 0.0
    n = 4096 if on_tpu else 512
    # row-stochastic so the chained products stay finite in bf16
    x = jax.device_put(jnp.full((n, n), 1.0 / n, jnp.bfloat16), device)

    def mm_chain(k):
        def run(a):
            out = jax.lax.fori_loop(0, k, lambda i, y: y @ x, a)
            return jnp.sum(out.astype(jnp.float32))
        return jax.jit(run)

    iters = 16 if on_tpu else 2
    mm_s = _diff_time(mm_chain, x, iters, min_diff_s)
    tflops = 2.0 * n ** 3 / mm_s / 1e12 if mm_s > 0 else 0.0

    m = (256 if on_tpu else 16) * 1024 * 1024 // 4
    big = jax.device_put(jnp.ones((m,), jnp.float32), device)

    def add_chain(k):
        # fma, not a pure increment: z+1.0 k times is algebraically z+k
        # and a compiler could in principle collapse the loop
        def run(a):
            out = jax.lax.fori_loop(
                0, k, lambda i, z: z * 1.000001 + 1.0, a)
            return out[0]
        return jax.jit(run)

    add_s = _diff_time(add_chain, big, iters, min_diff_s)
    # one read + one write of m float32 per iteration
    gbps = 2.0 * m * 4 / add_s / 1e9 if add_s > 0 else 0.0
    return tflops, gbps


def validate_slice(
    cfg=None,
    steps: int = 20,
    tp: Optional[int] = None,
    sp: Optional[int] = None,
    pp: Optional[int] = None,
    ep: Optional[int] = None,
    devices=None,
    attention: Optional[str] = None,
    mode: str = "train",
    gpipe_microbatches: int = 0,
) -> SliceReport:
    report = SliceReport(ok=False)
    try:
        import jax
        if devices is None:
            devices = jax.devices()
        report.devices_visible_s = time.monotonic() - _PROCESS_START
        report.platform = devices[0].platform
        report.n_devices = len(devices)
        report.device_kinds = sorted({d.device_kind for d in devices})

        from .mesh import slice_mesh
        from .workload import ModelConfig, build_infer, build_workload
        cfg = cfg or ModelConfig()
        mesh = (slice_mesh(devices, tp=tp, sp=sp, pp=pp, ep=ep)
                if len(devices) > 1 else None)
        if mesh is not None:
            report.mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))

        if mode == "infer":
            # serving path: forward-only latency distribution, no optimizer
            import jax.numpy as jnp
            steps = max(steps, 1)  # percentiles need >=1 sample
            fwd, params, tokens = build_infer(cfg, mesh, attention=attention)
            logits = fwd(params, tokens)
            float(logits.astype(jnp.float32)[0, 0, 0])  # trusted sync
            report.first_step_s = time.monotonic() - _PROCESS_START
            # End-to-end percentiles: submit -> one fetched element. Inside
            # a VMI with local chips this IS serving latency; on a relayed
            # device it includes the relay's fixed sync cost (the
            # differenced step_time below is the pure device time).
            lat = []
            for _ in range(steps):
                t0 = time.monotonic()
                float(fwd(params, tokens).astype(jnp.float32)[0, 0, 0])
                lat.append(time.monotonic() - t0)
            lat.sort()
            report.infer_p50_ms = lat[len(lat) // 2] * 1e3
            report.infer_p99_ms = lat[min(len(lat) - 1,
                                          int(len(lat) * 0.99))] * 1e3

            # pure per-forward device time by chained differencing
            # (_diff_time): each iteration's argmax feeds the next tokens
            def infer_chain(k):
                def run(tok):
                    def body(i, t):
                        lg = fwd(params, t)
                        return jnp.argmax(lg, axis=-1).astype(t.dtype)
                    return jnp.sum(jax.lax.fori_loop(0, k, body, tok))
                return jax.jit(run)
            fwd_s = _diff_time(infer_chain, tokens, max(steps // 2, 4))
            report.step_time_s = fwd_s if fwd_s > 0 else sum(lat) / len(lat)
            report.tokens_per_s = cfg.batch * cfg.seq_len / report.step_time_s
            # a serving slice is usable iff its logits are finite
            report.ok = bool(jax.numpy.isfinite(logits).all())
            if not report.ok:
                report.error = "non-finite logits in serving forward"
        else:
            if gpipe_microbatches:
                # explicit GPipe schedule (pipeline.py); runs einsum
                # attention by construction — the CLI rejects --attention
                # combined with it. Constraints only checkable now that the
                # mesh (hence dp, hence the local batch) is known are
                # config errors, never broken-slice verdicts.
                from .pipeline import build_gpipe
                axis = (dict(zip(mesh.axis_names, mesh.devices.shape))
                        if mesh is not None else {})
                dp = axis.get("dp", 1)
                if cfg.batch % dp or (cfg.batch // dp) % gpipe_microbatches:
                    report.invalid_config = True
                    report.error = (
                        f"invalid configuration: batch {cfg.batch} over "
                        f"dp={dp} gives local batch {cfg.batch // dp}, not "
                        f"divisible by --gpipe-microbatches "
                        f"{gpipe_microbatches}")
                    return report
                try:
                    step, params, momentum, tokens = build_gpipe(
                        cfg, mesh, n_micro=gpipe_microbatches)
                except ValueError as exc:
                    report.invalid_config = True
                    report.error = f"invalid configuration: {exc}"
                    return report
            else:
                step, params, momentum, tokens = build_workload(
                    cfg, mesh, attention=attention)

            params, momentum, loss = step(params, momentum, tokens)
            report.loss_start = float(loss)
            report.first_step_s = time.monotonic() - _PROCESS_START

            # Differenced steady-state step time: time a block of N steps
            # and a block of 2N (each synced by FETCHING the loss — the
            # only sync trusted on relayed devices), divide the difference
            # by N. Cancels the fixed per-fetch cost that would otherwise
            # inflate step_time by sync_cost/steps.
            steps = max(steps, 1)

            def run_block(k):
                nonlocal params, momentum, loss
                t0 = time.monotonic()
                for _ in range(k):
                    params, momentum, loss = step(params, momentum, tokens)
                val = float(loss)
                return time.monotonic() - t0, val

            t_n, _ = run_block(steps)
            t_2n, loss_val = run_block(2 * steps)
            report.loss_end = loss_val
            report.step_time_s = max(t_2n - t_n, 0.0) / steps
            if report.step_time_s > 0:
                report.tflops_per_chip = (
                    _workload_flops(cfg) / report.step_time_s / 1e12
                    / max(report.n_devices, 1))

            # a slice that cannot learn is broken even if it computes
            report.ok = report.loss_end < report.loss_start
            if not report.ok:
                report.error = (f"loss did not decrease "
                                f"({report.loss_start:.4f} -> {report.loss_end:.4f})")

        # Microbench + physics check: runs after the verdict, on a device
        # THIS process can address (in multi-VMI mode jax.devices() spans
        # all guests but only local ones are usable here). A chip slower
        # than peak is diagnostic-only; a chip MEASURING FASTER than its
        # datasheet peak is a broken estimator and vetoes the run
        # (perf_suspect), because every downstream perf claim would
        # otherwise inherit the artifact.
        try:
            local = next((d for d in devices
                          if d.process_index == jax.process_index()),
                         jax.local_devices()[0])
            report.matmul_tflops, report.hbm_gbps = _microbench(local)
            from . import peaks
            peak, suspect, why = peaks.check(
                local.device_kind, report.matmul_tflops, report.hbm_gbps)
            if suspect:
                # one retry at a 4x-taller noise floor before concluding
                # the estimator (not the moment) is broken. A retry that
                # ITSELF fails must keep the suspect verdict — otherwise
                # the impossible first reading would be recorded as ok.
                try:
                    report.matmul_tflops, report.hbm_gbps = _microbench(
                        local, MICROBENCH_MIN_DIFF_S * 4)
                    peak, suspect, why = peaks.check(
                        local.device_kind, report.matmul_tflops,
                        report.hbm_gbps)
                except Exception as exc:
                    why += (f" (retry failed: {type(exc).__name__}: {exc}; "
                            "keeping suspect verdict)")
            if peak is not None:
                report.peak_tflops = peak.bf16_tflops
                report.peak_hbm_gbps = peak.hbm_gbps
                report.microbench_mfu = report.matmul_tflops / peak.bf16_tflops
                report.hbm_frac = report.hbm_gbps / peak.hbm_gbps
                if report.tflops_per_chip:
                    report.mfu = report.tflops_per_chip / peak.bf16_tflops
                    if report.mfu > peaks.SUSPECT_FACTOR:
                        suspect = True
                        why = (f"train MFU {report.mfu:.2f} > "
                               f"{peaks.SUSPECT_FACTOR:g} is impossible; " + why)
            if suspect:
                report.perf_suspect = True
                report.ok = False
                report.error = (report.error + "; " if report.error else "") \
                    + f"perf measurement exceeds datasheet peak: {why}"
        except Exception as exc:
            log_err = f"microbench skipped: {type(exc).__name__}: {exc}"
            if not report.error:
                report.error = log_err
    except Exception as exc:  # report, don't crash the probe harness
        report.error = f"{type(exc).__name__}: {exc}"
    return report


# Named model-size presets for the train/infer workload. "mfu" is the
# sized-up configuration that answers "is it actually fast" (VERDICT r3
# item 2): MXU-shaped dims (d_model 2048, head_dim 128, ffn 4x), a sequence
# past FLASH_MIN_SEQ so auto attention picks the Pallas kernel, and ~46
# model TFLOPs per step — large enough that sustained train MFU on a
# single chip is compute-limited, small enough (402M params, ~3.2 GB f32
# params+momentum) to fit a v5e's 16 GB HBM without remat.
PRESETS = {
    "burnin": {},  # the ModelConfig defaults: tiny, correctness-first
    "mfu": dict(d_model=2048, n_heads=16, d_ff=8192, n_layers=8,
                seq_len=2048, batch=8),
    # ~7x fewer FLOPs/step than "mfu" (halved d_model/d_ff/heads/layers:
    # matmul FLOPs drop 8x but the 4*S^2*d attention term only 4x at the
    # unchanged seq 2048; same MXU-friendly shapes + flash-eligible seq).
    # The relay compiles big models very slowly and a hung full-size
    # compile cannot be killed without wedging the claim (docs/roadmap.md
    # item 1), so the capture protocol runs this first — a valid
    # sustained-MFU number lands even if the full-size run never returns.
    # MFU itself is size-independent (measured/peak); only absolute
    # TFLOP/s differ, so no scale-back-up factor is ever needed.
    "mfu-lite": dict(d_model=1024, n_heads=8, d_ff=4096, n_layers=4,
                     seq_len=2048, batch=8),
}


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="tpu-slice-validator",
        description="Validate a passed-through TPU slice from inside the guest.")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--mode",
                        choices=["train", "infer", "attn-bench", "ring-bench"],
                        default="train",
                        help="train = full step burn-in (loss must decrease); "
                             "infer = forward-only serving latency "
                             "percentiles (p50/p99, tokens/s); attn-bench = "
                             "flash-vs-einsum kernel sweep on one device; "
                             "ring-bench = ring-flash vs einsum-ring under "
                             "shard_map (--sp shards, --seqs GLOBAL lengths)")
    parser.add_argument("--seqs", default="1024,2048,4096",
                        help="attn-bench sequence lengths, comma-separated")
    parser.add_argument("--bwd-blocks", default="",
                        help="attn-bench BACKWARD block sizes (e.g. "
                             "256x256,512x256); empty = same as forward. "
                             "Swept cross-product with --blocks")
    parser.add_argument("--hb", type=int, default=8,
                        help="attn-bench heads*batch (folded leading dim)")
    parser.add_argument("--repeats", type=int, default=1,
                        help="attn-bench: chain this many dependent "
                             "evaluations inside one jit and amortize — "
                             "REQUIRED for truthful numbers on tunneled "
                             "devices whose per-dispatch floor (~40 us) "
                             "exceeds small-kernel compute time")
    parser.add_argument("--blocks", default="128x128",
                        help="attn-bench flash block sizes, e.g. "
                             "'128x128,256x128,128x256'")
    parser.add_argument("--tp", type=int, default=None)
    parser.add_argument("--sp", type=int, default=None)
    parser.add_argument("--pp", type=int, default=None,
                        help="pipeline stages (layer-stacked weights sharded "
                             "over a pp mesh axis; n_layers % pp must be 0)")
    parser.add_argument("--ep", type=int, default=None,
                        help="expert-parallel size (use with --experts)")
    parser.add_argument("--experts", type=int, default=None,
                        help="replace the MLP with a top-1 switch MoE of "
                             "this many experts")
    parser.add_argument("--gpipe-microbatches", type=int, default=0,
                        help="train with the explicit GPipe schedule "
                             "(pipeline.py) using this many microbatches; "
                             "needs --pp > 1 and sp == tp == 1")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialize each layer in the backward "
                             "(jax.checkpoint): O(1) activation memory in "
                             "depth for one extra forward pass")
    parser.add_argument("--seq-len", type=int, default=None)
    parser.add_argument("--preset", choices=sorted(PRESETS), default=None,
                        help="named model size: burnin = tiny defaults "
                             "(correctness), mfu = sized-up config for "
                             "sustained-MFU measurement (d_model 2048, "
                             "seq 2048, 8 layers; auto-selects the flash "
                             "kernel), mfu-lite = ~7x-lighter MFU config "
                             "(d_model 1024, 4 layers) run FIRST on "
                             "hardware as compile-hang insurance. "
                             "--seq-len/--experts/--remat compose on top")
    parser.add_argument("--attention",
                        choices=["auto", "flash", "ring", "einsum"],
                        default="auto",
                        help="auto = ring when sp > 1, Pallas flash kernel "
                             "on TPU when sp == 1, einsum otherwise")
    # multi-VMI slices (e.g. v5p-16 across 2 nodes): each guest runs the
    # validator with the same coordinator; jax.distributed composes the
    # global slice over ICI/DCN and jax.devices() returns ALL chips.
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 for a multi-VMI slice")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--init-timeout", type=int, default=60,
                        help="seconds to wait for the multi-VMI rendezvous "
                             "before reporting failure (default 60)")
    args = parser.parse_args(argv)
    if args.coordinator is not None:
        try:
            import jax
            jax.distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
                initialization_timeout=args.init_timeout)
        except Exception as exc:
            # keep the report-don't-crash contract for catchable failures
            # (bad/missing arguments). NOTE: an unreachable coordinator makes
            # jaxlib's C++ coordination client LOG(FATAL) after the timeout —
            # that path exits the process with a clear stderr message and
            # cannot be converted to a JSON report from inside the process.
            report = SliceReport(
                ok=False, error=f"distributed init: {type(exc).__name__}: {exc}")
            print(report.to_json())
            return 1
    if args.mode == "ring-bench":
        if args.gpipe_microbatches:
            parser.error("--gpipe-microbatches only applies to --mode train")
        from .ring_bench import bench_ring
        try:
            result = bench_ring(
                seq_lens=tuple(int(s) for s in args.seqs.split(",") if s),
                blocks=tuple(
                    tuple(int(x) for x in b.split("x"))
                    for b in args.blocks.split(",") if b),
                sp=args.sp,
                hb=args.hb,
                iters=args.steps,
                repeats=args.repeats,
            )
        except Exception as exc:  # report-don't-crash contract
            print(json.dumps({"ok": False,
                              "error": f"{type(exc).__name__}: {exc}"}))
            return 1
        ok = result["ring_flash_ok"]
        print(json.dumps({"ok": ok, **result}, sort_keys=True))
        return 0 if ok else 1
    if args.mode == "attn-bench":
        if args.gpipe_microbatches:
            parser.error("--gpipe-microbatches only applies to --mode train")
        from .attn_bench import bench_attention
        try:
            bwd = tuple(
                tuple(int(x) for x in b.split("x"))
                for b in args.bwd_blocks.split(",") if b) or (None,)
            result = bench_attention(
                seq_lens=tuple(int(s) for s in args.seqs.split(",") if s),
                blocks=tuple(
                    tuple(int(x) for x in b.split("x"))
                    for b in args.blocks.split(",") if b),
                iters=args.steps,
                hb=args.hb,
                bwd_blocks=bwd,
                repeats=args.repeats,
            )
        except Exception as exc:  # same report-don't-crash contract
            print(json.dumps({"ok": False,
                              "error": f"{type(exc).__name__}: {exc}"}))
            return 1
        ok = result["flash_ok"]
        print(json.dumps({"ok": ok, **result}, sort_keys=True))
        return 0 if ok else 1
    cfg = None
    if (args.preset is not None or args.seq_len is not None
            or args.experts is not None or args.remat):
        from .workload import ModelConfig
        overrides = dict(PRESETS.get(args.preset or "", {}))
        if args.seq_len is not None:
            overrides["seq_len"] = args.seq_len
        if args.experts is not None:
            overrides["n_experts"] = args.experts
        if args.remat:
            overrides["remat"] = True
        cfg = ModelConfig(**overrides)
    # Validate pp/ep against the model BEFORE touching devices: a sharding
    # divisibility error inside validate_slice would be reported as a broken
    # slice, which is exactly what this probe must not false-alarm on.
    from .workload import ModelConfig as _MC
    base = cfg or _MC()
    if args.pp and args.pp > 1 and base.n_layers % args.pp:
        parser.error(f"--pp {args.pp} does not divide n_layers={base.n_layers}")
    if args.ep and args.ep > 1:
        if not base.n_experts:
            parser.error(f"--ep {args.ep} needs --experts (dense model has "
                         "no expert dimension to shard)")
        if base.n_experts % args.ep:
            parser.error(f"--ep {args.ep} does not divide "
                         f"--experts {base.n_experts}")
    if args.gpipe_microbatches:
        if args.mode != "train":
            parser.error("--gpipe-microbatches only applies to --mode train")
        if (args.pp or 0) < 2:
            parser.error("--gpipe-microbatches needs --pp >= 2")
        if (args.tp or 1) != 1 or (args.sp or 1) != 1 or (args.ep or 1) != 1:
            parser.error("--gpipe-microbatches needs tp == sp == ep == 1")
        if args.attention != "auto":
            parser.error("the GPipe schedule runs einsum attention; "
                         "drop --attention")
        if base.batch % args.gpipe_microbatches:
            parser.error(f"batch {base.batch} not divisible by "
                         f"--gpipe-microbatches {args.gpipe_microbatches}")
    attention = None if args.attention == "auto" else args.attention
    report = validate_slice(cfg=cfg, steps=args.steps, tp=args.tp, sp=args.sp,
                            pp=args.pp, ep=args.ep,
                            attention=attention, mode=args.mode,
                            gpipe_microbatches=args.gpipe_microbatches)
    print(report.to_json())
    if report.invalid_config:
        return 2  # caller error, not a broken slice
    return 0 if report.ok else 1
