# Build system for the TPU KubeVirt device plugin
# (role of the reference's Makefile:37-90: build/test/coverage/update-pcidb).

PYTHON ?= python3
CXX ?= g++
CXXFLAGS ?= -O2 -Wall -Wextra -fPIC
IMAGE ?= tpu-device-plugin
VERSION ?= 0.1.0

.PHONY: all native proto test coverage bench bench-discovery bench-health bench-attach bench-attach-path bench-trace bench-trace-fleet bench-fleet bench-fleetsched bench-scale bench-placement bench-fleet-placement bench-broker bench-brokeripc bench-restart bench-transport bench-selfheal test-broker-spawn fleet-soak soak-autopilot clean update-pcidb image push dryrun hash-requirements e2e-kubevirt-local verify-drive chaos chaos-soak chaos-lifecycle lint lint-baseline lockdep-test weave weave-soak

all: native proto

# The one native component: the libtpu liveness shim (NVML-binding analogue).
native: native/libtpuhealth.so

native/libtpuhealth.so: native/tpuhealth.cpp
	$(CXX) $(CXXFLAGS) -shared -o $@ $< -ldl

# Regenerate kubelet protobuf messages (generated files are committed).
proto: proto/deviceplugin_v1beta1.proto proto/dra_v1beta1.proto proto/pluginregistration_v1.proto
	protoc --python_out=tpu_device_plugin/kubeletapi -Iproto \
	  proto/deviceplugin_v1beta1.proto proto/dra_v1beta1.proto \
	  proto/pluginregistration_v1.proto

test:
	$(PYTHON) -m pytest tests/ -q

# Static gates (docs/static-analysis.md): ruff (E/F/B/PLE) + gradual
# strict mypy (allowlist in pyproject.toml) + tsalint, the project
# concurrency analyzer (lock-order graph, blocking-under-hot-lock,
# counter ownership, fault-site registry, thread lifecycle) gated on
# tools/tsalint/baseline.json. ruff/mypy are skipped with a notice where
# not installed (the hermetic test image ships neither; CI installs both)
# — tsalint is stdlib-only and always enforced.
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	    ruff check tpu_device_plugin tools scripts tests bench.py; \
	else echo "lint: ruff not installed; skipped (CI runs it)"; fi
	@if command -v mypy >/dev/null 2>&1; then \
	    mypy --config-file pyproject.toml; \
	else echo "lint: mypy not installed; skipped (CI runs it)"; fi
	$(PYTHON) scripts/lint_concurrency.py

# Re-freeze accepted concurrency-lint debt (reviewable in the diff).
lint-baseline:
	$(PYTHON) scripts/lint_concurrency.py --update-baseline

# Tier-1 as a race detector: every registered lock records acquisition
# order + hold times (tpu_device_plugin/lockdep.py); the session FAILS on
# any observed lock-order inversion, cycle, watched-lock long hold, or
# leaked daemon thread.
lockdep-test:
	TDP_LOCKDEP=1 JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/ -q -m 'not slow'

# Deterministic interleaving checker (docs/static-analysis.md "weave"):
# enumerate thread schedules of the lock-free planes under DPOR +
# bounded preemption, real production code, seed-replayable
# counterexamples (.weave-artifacts/). Runs the 9-scenario quick
# matrix, then the 8 seeded-bug twins (which must FAIL — every
# invariant is mutation-tested). The soak leg multiplies execution
# budgets 25x and raises preemption bounds by 1.
weave:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.weave
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.weave --twins

weave-soak:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.weave --soak
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.weave --twins

# Seeded chaos suite (docs/fault-injection.md): randomized kubelet-restart
# storms, flapping /dev/vfio nodes, apiserver 5xx/timeout bursts — fixed
# seed so failures replay. The long soak variant is @pytest.mark.slow and
# env-gated; `chaos` runs the fast schedule that tier-1 also includes.
CHAOS_SEED ?= 1337
chaos:
	TDP_CHAOS_SEED=$(CHAOS_SEED) JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_chaos.py -q

chaos-soak:
	TDP_CHAOS_SOAK=1 TDP_CHAOS_SEED=$(CHAOS_SEED) JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_chaos.py -q

# Device lifecycle survivability scenarios (docs/design.md "Device
# lifecycle"): hot-unplug of an allocated chip, unplug mid-prepare,
# replug identity swap, migration handoff with source crashes at every
# step, and old→new checkpoint schema upgrade — all deterministic
# (events injected at the FSM/driver seams, no sleeps-as-sync). Runs
# under TDP_LOCKDEP=1 so the FSM's locks are inversion-checked.
chaos-lifecycle:
	TDP_CHAOS_SEED=$(CHAOS_SEED) TDP_LOCKDEP=1 JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_lifecycle_scenarios.py -q

# KubeVirt externalResourceProvider contract, no cluster required: real
# daemon + faithful kubelet sim + simulated virt-controller render
# (scripts/e2e_kubevirt_local.py). The full-cluster stage is
# scripts/e2e_kind.sh KUBEVIRT=1.
e2e-kubevirt-local:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/e2e_kubevirt_local.py

# Canonical build-and-drive check: full daemon against a fake host, driven
# as the kubelet would, asserting the end-to-end health prune/restore loop
# across ListAndWatch AND the published ResourceSlice.
verify-drive:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/verify_drive.py

# Enforced coverage (reference: Makefile:59-61 + golang.yml Coveralls job).
# The image ships no pytest-cov, so the collector is a stdlib sys.monitoring
# harness (scripts/stdlib_coverage.py). Floor = 91: re-measured 91.2%
# (4136/4535 lines) on 2026-07-29 after the DRA driver + ring-flash
# additions (was 92.1% of 3421 lines before them). The 0%-covered __main__
# stubs and all three generated *_pb2 modules are inside that number, not
# excluded.
COV_MIN ?= 92
coverage:
	$(PYTHON) scripts/stdlib_coverage.py --fail-under $(COV_MIN) \
		--json-out coverage.json

bench:
	$(PYTHON) bench.py

# Incremental-discovery + churn bench (docs/perf.md): cold full scan vs
# warm dirty-set rescan read counts at {8,64,256} devices x {0,128}
# partitions, plus the 100-flip ListAndWatch coalescing storm. Writes
# docs/bench_discovery_r06.json.
bench-discovery:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --discovery

# Shared-health-plane bench (docs/perf.md "health plane"): probe-cycle wall
# at {8,64,256} devices with 0/1 injected 1s-slow chips (must be bounded by
# the per-cycle deadline, not the serial sum) + inotify-fd/thread gauges vs
# resource count (one fd per HOST). Writes docs/bench_health_r07.json.
bench-health:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --health

# Attach-path burst bench (docs/perf.md "attach path"): K in {1,8,32}
# concurrent claim prepares at prepare_workers=8 vs the serial single-claim
# estimate, counted checkpoint writes per burst (group commit), and the
# precompiled-fragment plan read ratio. Writes docs/bench_attach_r08.json.
bench-attach:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --attach-burst

# Epoch read-plane attach bench (docs/perf.md "lock-free read plane"):
# daemon-side attach wall broken into sysfs-I/O floor (counted syscalls x
# in-run calibration), daemon overhead, 4-way-contended queue/sync, gRPC
# transport — plus COUNTED registered-lock acquisitions per attach (0; the
# pre-epoch tree measured 11). Writes docs/bench_attach_r09.json, then the
# flight-recorder overhead bench (r10, below). The CI bench-smoke job runs
# this with --quick and the counted honesty guards.
bench-attach-path:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --attach

# Flight-recorder overhead bench (docs/observability.md): per-attach wall
# with tracing enabled vs disabled (interleaved A/B) + COUNTED trace
# records per attach (2 spans, 0 events). Writes docs/bench_attach_r10.json;
# the honesty guard pins the recorded overhead within the documented bound.
bench-trace:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --trace-overhead

# Fleet-scale simulation bench (docs/perf.md "fleet scale"): paced vs
# unpaced boot storms at N={16,64,256} in-process nodes against the
# congestion-modeling fabric (peak in-flight, write p99, exactly-once
# publish audit), plus the 64-node attach storm / flip wave / rolling
# drain-upgrade. Writes docs/bench_fleet_r11.json. CI bench-smoke runs
# the --quick (N=4) variant.
bench-fleet:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --fleet

# Single-daemon scale ceiling bench (docs/perf.md "fleet scale"): 4096
# devices / 1024 partitions — warm-discovery read floor, one-flip epoch
# isolation (counted builds + payload identity), /status //metrics
# scrape assembly accounting, 1024-claim checkpoint burst at the
# group-commit bound with compact-serialization sizing. Writes
# docs/bench_scale_r11.json.
bench-scale:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --scale

# Slice placement bench (docs/perf.md "slice placement"): engine vs
# naive placement quality (4-chip requests on one ICI ring) under
# seeded claim churn at N={4,16} fleetsim nodes, plus the defrag
# advisory applied via migration handoff (unplaceable 2x2 -> placeable)
# — all counted facts, exactly-once audited. Writes
# docs/bench_placement_r12.json. CI bench-smoke runs the --quick (N=4)
# variant.
bench-placement:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --placement

# Fleet placement control plane bench (docs/design.md "Fleet placement
# control plane"): the r12 placement-quality comparison rerun at 256
# simulated nodes THROUGH the cluster scheduler — selector-filtered
# decisions consumed from the watch-stream slice cache, cross-host
# meshes on the pod grid, fragmentation-over-churn curves for the
# engine and the naive first-free baseline, and a global defrag wave
# applied node-by-node via migration handoff — every cell exactly-once
# on the fabric, multiclaim and scheduler commit-log audits. Writes
# docs/bench_fleetplace_r16.json. CI bench-smoke runs the --quick
# (N=16) variant.
bench-fleet-placement:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --fleet-placement

# Sharded fleet scheduler bench (docs/design.md "Sharded
# scheduling"): N=4 optimistic-concurrency scheduler shards over one
# 4096-node fabric vs a single per-claim-commit scheduler on a
# 16k-claim storm — decisions/sec (>=4x pinned), p99 decision
# latency, conflict-abort rate under deliberate contention, every
# cell exactly-once on the multiclaim, write and checkpoint logs.
# Writes docs/bench_fleetsched_r19.json. CI bench-smoke runs the
# --quick (N=2, 64 nodes) variant.
bench-fleetsched:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --fleetsched

# Privilege-separation bench (docs/design.md "Privilege separation"):
# the attach path in BOTH broker modes — counted crossings per attach
# (the <=2 budget tests/test_perf_honesty.py pins) and the spawned
# broker's IPC crossing overhead. Writes docs/bench_broker_r13.json.
# CI bench-smoke runs the --quick variant.
bench-broker:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --broker

# Broker IPC fast-path bench (docs/design.md "Broker fast path"):
# binary-vs-JSON framing byte overhead on the recorded corpus (the
# >=3x pin), counted crossings for the batched multi-group claim
# prefetch and chip_alive probe cycle, and live response-ring hit
# latency against a spawned broker; wall encode/decode recorded
# honestly unpinned. Writes docs/bench_brokeripc_r20.json. CI
# bench-smoke runs the --quick variant.
bench-brokeripc:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --brokeripc

# Restart-to-ready bench (docs/design.md "Boot sequence"): counted
# cold-walk vs persisted-snapshot-warm boots at 64/4096 devices (the
# >=10x reads / >=3x wall pins), the two-wave readiness edges under a
# membership invalidation, corrupt-cache fallback + re-seed, claims
# exactly-once across restarts, and the 256-node rolling-upgrade
# node-seconds-unready wave (>=2x pin). Writes
# docs/bench_restart_r21.json. CI bench-smoke runs the --quick variant.
bench-restart:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --restart

# Attach transport-endgame bench (docs/perf.md "Transport endgame"):
# pre-serialized hot responses — the calibrated attach wall (<200 us
# pin), the isolated serialization A/B (same handlers, byte plane on
# vs off), measured scheduler-wakeup and gRPC no-op RTT floors, and
# the counted bytes-reused/serializations-per-warm-attach guards.
# Writes docs/bench_transport_r15.json. CI bench-smoke runs --quick.
bench-transport:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --transport

# Fleet trace + SLO plane bench (ISSUE 15): a 256-node autopilot soak
# whose migrated claim story reconstructs purely from the fleet trace
# query, a scheduler-placed multi-host slice's full waterfall (decision
# -> per-shard prepare -> broker crossing -> handoff -> destination
# prepare) replayed from ONE /debug/fleet/trace?trace= query, and the
# SLO burn-rate gauge moved by an injected latency fault with its
# exemplar resolving on the same query. Writes
# docs/bench_tracefleet_r17.json. CI bench-smoke runs --quick (N=16).
bench-trace-fleet:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --trace-fleet

# Self-heal closed-loop bench (ISSUE 16): a 256-node autopilot soak
# with a ramped kubeapi delay fault; asserts the full remediation
# chain — burn rise -> breach latch -> policy-approved audited actions
# (pacer backoff + exemplar->node placement bias) -> dilution recovery
# -> knob rollback — all reconstructed from ONE
# /debug/fleet/trace?trace= query. Writes docs/bench_selfheal_r18.json.
# CI bench-smoke runs --quick (N=16).
bench-selfheal:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --selfheal

# Broker + policy suites over the REAL two-process path: every
# seam-facing assertion re-executed with a spawned broker process per
# fixture root (the CI broker-spawn job's body).
test-broker-spawn:
	TDP_BROKER=spawn JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_broker.py tests/test_policy.py -q

# Fleet chaos soak (nightly-shape, gated): 64-node boot storm + flip
# wave + 1024-claim attach + rolling upgrade with chaos faults armed
# (dra.publish refusals, kubeapi transport errors), under runtime
# lockdep. Deterministic seeds; every fleet contract asserted.
fleet-soak:
	TDP_CHAOS_SOAK=1 TDP_LOCKDEP=1 JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_fleetsim.py -q -k soak

# Full-length continuous autopilot soak (ISSUE 12, gated like the other
# soaks): 256 nodes / >= 100k claim events of OVERLAPPING boot storms,
# claim storms, hot-unplugs, migrations, defrag waves and rolling
# upgrades on the watch-stream fabric, with watch chaos + kubeapi.watch
# faults firing throughout and the soak invariants checked continuously
# (fleetsim.fleet_invariants). Writes docs/bench_autopilot_r14.json —
# the artifact the r14 perf-honesty guard pins. The CI smoke leg runs
# the --quick (N=8, ~60 s) shape with TDP_LOCKDEP=1.
soak-autopilot:
	TDP_CHAOS_SOAK=1 JAX_PLATFORMS=cpu $(PYTHON) bench.py --autopilot

# Validate the multi-chip sharding path on a virtual CPU mesh.
dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

# Refresh the bundled PCI id database (network required; the bundled copy is
# a curated subset — see utils/README.md).
update-pcidb:
	curl -fsSL -o utils/pci.ids https://pci-ids.ucw.cz/v2.2/pci.ids
	$(PYTHON) scripts/merge_tpu_pciids.py utils/pci.ids

# Pin sha256 hashes into the image requirements (network required). The
# hashed file is installed by BOTH the image build (cp311, distroless base)
# and the CI unit job (cp312), so download wheels for each target and merge
# every hash per distribution (scripts/hash_requirements.py dedupes).
REQS = deployments/container/requirements.txt
hash-requirements:
	rm -rf build/wheels && mkdir -p build/wheels
	for pyver in 311 312; do \
	    $(PYTHON) -m pip download --no-deps --only-binary :all: \
	        --implementation cp --python-version $$pyver \
	        --platform manylinux2014_x86_64 -d build/wheels -r $(REQS); \
	done
	$(PYTHON) scripts/hash_requirements.py $(REQS) build/wheels

image:
	docker build -f deployments/container/Dockerfile -t $(IMAGE):$(VERSION) .

# Push the built image (reference: README.md:199-206 / container Makefile's
# push target). CI's images.yml does the multi-arch publish; this target is
# the manual single-arch escape hatch.
push: image
	docker push $(IMAGE):$(VERSION)

clean:
	rm -f native/libtpuhealth.so
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
