"""Device-timing helpers that survive relayed/tunneled backends.

Two traps poison naive timing on the build environment's tunneled TPU (and
any remote PJRT relay):

  (a) jax.block_until_ready can return BEFORE the relayed computation
      finishes — observed as "8192-long attention in 1 us". The only sync
      this module trusts is fetching a data-dependent scalar to host.
  (b) a forced-sync fetch carries a FIXED per-call cost (~70 ms observed),
      swamping ms-scale kernels.

The methodology: chain R serially-dependent iterations inside one jit,
reduce to a scalar, time the fetch at R and 2R, and divide the difference
by R — the fixed cost cancels exactly. Shared by attn_bench and probe so
the estimator cannot drift between them.
"""

from __future__ import annotations

import time
from typing import List, Sequence


def median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def time_total(fn, args, iters: int) -> float:
    """Median wall-clock seconds per call, after one warmup/compile call.

    `fn(*args)` must return a scalar depending on the full computation;
    float() fetches it (the trusted sync, see module docstring)."""
    samples: List[float] = []
    float(fn(*args))   # warmup/compile
    for _ in range(max(iters, 1)):
        t0 = time.monotonic()
        float(fn(*args))
        samples.append(time.monotonic() - t0)
    return median(samples)


def paired_time(build, args, iters: int, repeats: int) -> float:
    """Per-iteration seconds via paired-repeats differencing.

    `build(k)` returns a jitted fn of `args` chaining k dependent
    iterations into one scalar. repeats<=1 falls back to plain per-call
    timing — only correct on local devices (tests, interpret mode)."""
    if repeats <= 1:
        return time_total(build(1), args, iters)
    t1 = time_total(build(repeats), args, iters)
    t2 = time_total(build(2 * repeats), args, iters)
    return max((t2 - t1) / repeats, 0.0)
