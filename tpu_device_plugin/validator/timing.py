"""Device-timing helpers that survive relayed/tunneled backends.

Two traps poison naive timing on the build environment's tunneled TPU (and
any remote PJRT relay):

  (a) jax.block_until_ready can return BEFORE the relayed computation
      finishes — observed as "8192-long attention in 1 us". The only sync
      this module trusts is fetching a data-dependent scalar to host.
  (b) a forced-sync fetch carries a FIXED per-call cost (~70 ms observed),
      swamping ms-scale kernels.

The methodology: chain R serially-dependent iterations inside one jit,
reduce to a scalar, time the fetch at R and 2R, and divide the difference
by R — the fixed cost cancels exactly. Two hardenings added after round 3
published a >datasheet-peak number (VERDICT r3 item 1):

  (c) the R and 2R runs are sampled as INTERLEAVED PAIRS and the estimate
      is the median of per-pair differences — a load spike perturbs one
      pair, not the whole estimate, where the old median(t_2R) - median(t_R)
      let uncorrelated noise on two independent medians masquerade as
      (negative or positive) compute time;
  (d) a minimum-differenced-time floor: if the measured (t_2R - t_R) is
      smaller than `min_diff_s`, R grows geometrically until R iterations
      of real compute stand tall enough above the relay's ms-scale jitter
      to be resolvable. Callers on real hardware pass a floor; unit tests
      on CPU keep 0 (no growth, no extra compiles).

Shared by attn_bench and probe so the estimator cannot drift between them.
"""

from __future__ import annotations

import time
from typing import List, Sequence


def median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def time_total(fn, args, iters: int) -> float:
    """Median wall-clock seconds per call, after one warmup/compile call.

    `fn(*args)` must return a scalar depending on the full computation;
    float() fetches it (the trusted sync, see module docstring)."""
    samples: List[float] = []
    float(fn(*args))   # warmup/compile
    for _ in range(max(iters, 1)):
        t0 = time.monotonic()
        float(fn(*args))
        samples.append(time.monotonic() - t0)
    return median(samples)


def _timed(fn, args) -> float:
    t0 = time.monotonic()
    float(fn(*args))
    return time.monotonic() - t0


def paired_time(build, args, iters: int, repeats: int,
                min_diff_s: float = 0.0, max_repeats: int = 65536) -> float:
    """Per-iteration seconds via paired-repeats differencing.

    `build(k)` returns a jitted fn of `args` chaining k dependent
    iterations into one scalar. repeats<=1 (with no floor) falls back to
    plain per-call timing — only correct on local devices (tests,
    interpret mode). With `min_diff_s` > 0 the chain length auto-grows
    until the differenced compute time reaches the floor (hardening (d));
    the estimate is the median of interleaved per-pair differences
    (hardening (c))."""
    if repeats <= 1 and min_diff_s <= 0:
        return time_total(build(1), args, iters)
    repeats = max(repeats, 1)
    while True:
        fn1, fn2 = build(repeats), build(2 * repeats)
        float(fn1(*args))   # compile + warm both chain lengths
        float(fn2(*args))
        if min_diff_s <= 0 or repeats >= max_repeats:
            break
        d = _timed(fn2, args) - _timed(fn1, args)
        if d >= min_diff_s:
            break
        # grow toward the floor in one jump when the probe pair gives a
        # usable signal, else double; bounded growth caps recompiles
        grow = max(2, min(64, int(min_diff_s / d) + 1)) if d > 0 else 2
        repeats = min(max_repeats, repeats * grow)
    diffs: List[float] = []
    for _ in range(max(iters, 1)):
        t1 = _timed(fn1, args)
        t2 = _timed(fn2, args)
        diffs.append((t2 - t1) / repeats)
    return max(median(diffs), 0.0)
