"""HealthHub — the host-level shared health plane.

The reference plugin runs one fsnotify watcher and one NVML event loop per
device type (SURVEY.md §5), and the port inherited that shape: every plugin
server owned a private `health.HealthMonitor` thread with its own inotify
fd, its own periodic existence rescan of the same `/dev/vfio` dirs, and a
strictly serial probe loop — steady-state cost and worst-case health
latency grew with *resource count*, not with what changed. Health sensing
is host-global state: the hub senses it once and fans it out.

One `HealthHub` per host process replaces N monitors with:

- **one inotify fd** watching the union of every subscription's socket and
  device-node directories (`InotifyWatcher`, shared with the legacy
  monitor). If inotify is unavailable (fd/watch limits exhausted) the hub
  degrades to ONE shared existence poller — not one per resource;
- **one periodic existence reconciler**: sysfs (kernfs) emits no inotify
  events at all, and dirs missing at subscribe time (udev still populating
  `/dev/vfio`) get no watch — existence scanning stays the ground truth;
- **a deduped, deadline-bounded probe scheduler**: each physical BDF is
  probed once per cycle even when exposed through multiple
  resources/partitions (all partitions of a chip ride the same
  `/dev/accelN`), probes run on a bounded worker pool, and the cycle
  collects verdicts under a wall-clock deadline — one hung config-space
  read (a dead chip returning all-FF slowly, or a stuck vfio region) is
  scored Unhealthy at the deadline instead of delaying every other chip's
  verdict by the serial sum.

Fault points (docs/fault-injection.md) fire *inside the hub*:
`inotify.poll` in the shared watcher's poll, `native.probe` in the hub's
probe runner — so chaos schedules exercise the one code path production
actually runs.

Subscribers (`HubSubscription`) are per-resource filters: plugin servers
subscribe with their watch-key → node-path / member-BDF maps and health
callbacks; the DRA driver subscribes with just its registration socket.
Callbacks are delivered from the hub thread; per-device ordering is
preserved because each subscription's state transitions are computed and
dispatched sequentially.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures
from typing import Callable, Dict, List, Optional, Tuple

from . import faults
from . import lockdep
from . import trace
from .broker import BrokerUnavailable
from .health import InotifyWatcher, _BACK, _GONE

log = logging.getLogger(__name__)

# main-loop tick: inotify poll timeout / fallback sleep (the legacy
# monitor's cadence, kept so socket-loss detection latency is unchanged)
_TICK_S = 0.2


class HubSubscription:
    """One subscriber's filter + callbacks. Construct and pass to
    `HealthHub.subscribe`; keep the returned object to `unsubscribe`.

    All fields are read-only after subscribe (the hub indexes them):
      name               — display name (logs/stats)
      socket_path        — plugin socket to watch; removal means the
                           kubelet restarted and wiped its socket dir
      on_socket_removed  — called (once per subscription) on removal
      group_paths        — watch key → device node path
      group_bdfs         — watch key → member BDFs (probe fan-in: a key is
                           healthy iff every member BDF probes alive)
      on_device_health   — (key, healthy, source) with source "fs"/"probe"
      probe              — (bdf, node_path) → bool; the hub dedups BDFs
                           across subscriptions and adds the
                           `native.probe` fault point around it
    """

    def __init__(
        self,
        name: str,
        socket_path: Optional[str] = None,
        on_socket_removed: Optional[Callable[[], None]] = None,
        group_paths: Optional[Dict[str, str]] = None,
        group_bdfs: Optional[Dict[str, List[str]]] = None,
        on_device_health: Optional[Callable[[str, bool, str], None]] = None,
        probe: Optional[Callable[[str, Optional[str]], bool]] = None,
    ) -> None:
        self.name = name
        self.socket_path = socket_path
        self.on_socket_removed = on_socket_removed
        self.group_paths = dict(group_paths or {})
        self.group_bdfs = {k: list(v) for k, v in (group_bdfs or {}).items()}
        self.on_device_health = on_device_health
        self.probe = probe
        # mutable state, owned by the hub. _state_lock serializes every
        # check-then-set + delivery on this subscription (the subscribe-time
        # initial scan runs on the caller's thread and must not interleave
        # with the hub thread's scans/events over the same state — without
        # it a transition could be delivered twice or land out of order)
        self._state_lock = lockdep.instrument(
            "healthhub.HubSubscription._state_lock", threading.Lock())
        self._active = False
        self._socket_reported = False
        self._fs_state: Dict[str, bool] = {}
        self._probe_state: Dict[str, bool] = {}


class HealthHub:
    """Shared watcher + reconciler + probe scheduler (module docstring)."""

    def __init__(self, poll_interval_s: float = 5.0, probe_workers: int = 4,
                 probe_deadline_s: float = 1.0) -> None:
        # fail-loud arm-time validation, matching server.py's debounce rule:
        # a zero/negative pool serializes nothing and a non-finite deadline
        # makes every timeout comparison silently false
        if not isinstance(probe_workers, int) or probe_workers < 1:
            raise ValueError(
                f"probe_workers must be an int >= 1, got {probe_workers!r}")
        if not (isinstance(probe_deadline_s, (int, float))
                and probe_deadline_s == probe_deadline_s
                and 0 < probe_deadline_s < float("inf")):
            raise ValueError(
                f"probe_deadline_s must be a finite number > 0, got "
                f"{probe_deadline_s!r}")
        self.poll_interval_s = poll_interval_s
        self.probe_workers = probe_workers
        self.probe_deadline_s = probe_deadline_s
        self._lock = lockdep.instrument(
            "healthhub.HealthHub._lock", threading.RLock())
        self._subs: List[HubSubscription] = []
        # reverse indexes, rebuilt on (un)subscribe: node events and
        # existence scans resolve in O(paths touched), not O(subs × keys)
        self._node_index: Dict[str, List[Tuple[HubSubscription, str]]] = {}
        self._socket_index: Dict[str, HubSubscription] = {}
        self._watcher: Optional[InotifyWatcher] = None
        self._watcher_failed = False
        self._watched_dirs: set = set()
        # dirs a subscription wants watched that did not exist (or failed
        # to watch) at subscribe time — e.g. a hot-unplugged device's
        # node dir. The periodic existence scan retries them, so a replug
        # regains inotify latency instead of staying on scan cadence
        # forever. Guarded by _lock.
        self._pending_dirs: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pool: Optional[futures.ThreadPoolExecutor] = None
        # one probe cycle at a time (the loop and bench/test callers of
        # probe_cycle() must not interleave verdict collection)
        self._cycle_lock = lockdep.instrument(
            "healthhub.HealthHub._cycle_lock", threading.Lock())
        # BDF -> future still running past its deadline: a genuinely hung
        # probe (blocked syscall — uncancellable) must NOT be resubmitted
        # every cycle, or each cycle strands one more pool worker until the
        # shared pool is exhausted and EVERY chip on the host times out.
        # While stuck the chip keeps its dead verdict; when the read finally
        # returns the entry clears and the next cycle probes it fresh.
        self._stuck: Dict[str, futures.Future] = {}
        # counters (read under _lock via stats())
        self._probe_cycles = 0
        self._probes_last_cycle = 0
        self._probes_deduped_last_cycle = 0
        self._probe_timeouts = 0
        self._probe_errors = 0
        # probes that failed because the privileged broker was gone
        # (broker.BrokerUnavailable): counted apart from generic probe
        # errors so a broker outage reads as ITSELF on /status — the
        # chip's dead verdict is a degradation artifact, not silicon
        self._probe_broker_unavailable = 0
        self._existence_scans = 0
        self._last_cycle_s = 0.0

    # ------------------------------------------------------------ lifecycle

    def ensure_started(self) -> None:
        """Idempotent lazy start (also restarts a stopped hub): watcher,
        probe pool, and the single hub thread come up on first use so a
        constructed-but-unused hub costs nothing."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            if self._watcher is None and not self._watcher_failed:
                try:
                    self._watcher = InotifyWatcher()
                except OSError as exc:
                    self._watcher_failed = True
                    log.error("health hub: inotify unavailable (%s); "
                              "degrading to ONE shared existence poller",
                              exc)
                else:
                    # re-register dirs across a restart
                    dirs, self._watched_dirs = self._watched_dirs, set()
                    for d in dirs:
                        self._watch_dir(d)
            if self._pool is None:
                self._pool = futures.ThreadPoolExecutor(
                    max_workers=self.probe_workers,
                    thread_name_prefix="healthhub-probe")
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="healthhub")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            pool, self._pool = self._pool, None
            watcher, self._watcher = self._watcher, None
            self._stop.set()
        if thread is not None:
            thread.join(timeout=2)
        if pool is not None:
            # cancel_futures: a genuinely hung probe should not block
            # process shutdown behind the executor's atexit join
            pool.shutdown(wait=False, cancel_futures=True)
        if watcher is not None:
            watcher.close()

    # --------------------------------------------------------- subscription

    def subscribe(self, sub: HubSubscription) -> HubSubscription:
        """Register + watch dirs + initial existence reconcile.

        Watches are added before the initial scan so an event arriving
        immediately after subscribe (e.g. the kubelet wiping its socket dir
        during registration) cannot be lost to setup latency — the same
        ordering the per-plugin monitor guaranteed in start()."""
        self.ensure_started()
        with self._lock:
            sub._active = True
            sub._fs_state = {k: True for k in sub.group_paths}
            sub._probe_state = {}
            self._subs.append(sub)
            dirs = set()
            if sub.socket_path:
                dirs.add(os.path.dirname(sub.socket_path) or ".")
            for path in sub.group_paths.values():
                dirs.add(os.path.dirname(path) or ".")
            for d in dirs:
                if os.path.isdir(d):
                    self._watch_dir(d)
                else:
                    # not there yet (udev still populating, or the device
                    # is unplugged): the existence scan retries the watch
                    # when the dir appears
                    self._pending_dirs.add(d)
            self._rebuild_indexes_locked()
        # initial reconcile outside the lock (callbacks may take plugin
        # locks): inotify only reports *future* events, so a node already
        # missing at subscribe time must be flagged here
        self._scan_subscription(sub)
        return sub

    def unsubscribe(self, sub: HubSubscription) -> None:
        """Drop a subscription. Watches on its dirs are kept (the dir set
        is tiny and shared; inotify dedups adds) — events with no matching
        subscription are simply ignored."""
        with self._lock:
            sub._active = False
            if sub in self._subs:
                self._subs.remove(sub)
            self._rebuild_indexes_locked()

    def _rebuild_indexes_locked(self) -> None:
        node_index: Dict[str, List[Tuple[HubSubscription, str]]] = {}
        socket_index: Dict[str, HubSubscription] = {}
        for sub in self._subs:
            if sub.socket_path:
                socket_index[sub.socket_path] = sub
            for key, path in sub.group_paths.items():
                node_index.setdefault(path, []).append((sub, key))
        self._node_index = node_index
        self._socket_index = socket_index

    def _watch_dir(self, path: str) -> None:
        if self._watcher is None or path in self._watched_dirs:
            return
        try:
            self._watcher.watch_dir(path)
            self._watched_dirs.add(path)
        except OSError as exc:
            # watch-limit exhaustion on one dir degrades that dir to the
            # existence scan, not the whole hub to polling
            log.error("health hub: inotify_add_watch(%s) failed (%s); "
                      "existence scan covers it", path, exc)

    # ------------------------------------------------------------ main loop

    def _run(self) -> None:
        stop = self._stop
        # pace from loop start: the subscribe-time initial scan covers the
        # fs ground truth, so the first periodic scan/probe lands one full
        # interval later (0.0 here would read as "interval already elapsed"
        # on any host with uptime and fire a spurious immediate cycle)
        last_scan = time.monotonic()
        last_probe = last_scan
        while not stop.is_set():
            watcher = self._watcher
            if watcher is not None:
                # fault point "inotify.poll" fires inside watcher.poll —
                # the hub IS the consumer now (docs/fault-injection.md)
                try:
                    events = watcher.poll(_TICK_S)
                except OSError as exc:
                    if stop.is_set():
                        break
                    # a broken fd would return instantly forever (no select
                    # timeout to pace the loop) — drop the watcher and
                    # degrade to the shared existence poller instead of
                    # spinning a core on the dead fd
                    log.error("health hub: inotify poll failed (%s); "
                              "degrading to the shared existence poller",
                              exc)
                    with self._lock:
                        if self._watcher is watcher:
                            self._watcher = None
                            self._watcher_failed = True
                    try:
                        watcher.close()
                    except OSError:
                        pass  # the fd may be the broken thing being dropped
                    continue
                for directory, name, mask in events:
                    self._dispatch_event(os.path.join(directory, name), mask)
            else:
                stop.wait(_TICK_S)
            now = time.monotonic()
            if now - last_scan >= (self.poll_interval_s
                                   if watcher is not None else _TICK_S):
                # with inotify this is the periodic reconciler; without it,
                # it IS the event source — one shared poller either way
                last_scan = now
                self._scan_all()
            if now - last_probe >= self.poll_interval_s:
                last_probe = now
                self.probe_cycle()

    def _dispatch_event(self, path: str, mask: int) -> None:
        with self._lock:
            sock_sub = self._socket_index.get(path)
            node_hits = list(self._node_index.get(path, ()))
        if sock_sub is not None and mask & _GONE:
            self._report_socket_gone(sock_sub)
        for sub, key in node_hits:
            if not sub._active:
                continue
            if mask & _GONE:
                self._fs_transition(sub, key, False,
                                    "device node %s removed", path)
            elif mask & _BACK:
                self._fs_transition(sub, key, True,
                                    "device node %s (re)created", path)

    def _fs_transition(self, sub: HubSubscription, key: str, exists: bool,
                       msg: str, path: str) -> None:
        """Check-then-set + delivery for one fs verdict, serialized per
        subscription (_state_lock): the subscribe-time initial scan runs on
        the caller's thread and must not interleave with the hub thread's
        events/scans — an unsynchronized race could deliver a transition
        twice or leave the stored state contradicting the last delivery."""
        with sub._state_lock:
            if sub._fs_state.get(key) == exists:
                return
            sub._fs_state[key] = exists
            if exists:
                log.info(msg, path)
            else:
                log.warning(msg, path)
            trace.event("health.fs_transition", device=key,
                        subscriber=sub.name, healthy=exists)
            self._deliver(sub, key, exists, "fs")

    def _report_socket_gone(self, sub: HubSubscription) -> None:
        if not sub._active or sub.on_socket_removed is None:
            return
        with sub._state_lock:
            if sub._socket_reported:
                return
            sub._socket_reported = True
        log.info("%s: socket %s removed — kubelet restart", sub.name,
                 sub.socket_path)
        try:
            sub.on_socket_removed()
        except Exception as exc:
            log.error("%s: on_socket_removed failed: %s", sub.name, exc)

    def _deliver(self, sub: HubSubscription, key: str, healthy: bool,
                 source: str) -> None:
        if sub.on_device_health is None:
            return
        try:
            sub.on_device_health(key, healthy, source)
        except Exception as exc:
            log.error("%s: health callback (%s, %s) failed: %s",
                      sub.name, key, source, exc)

    # ----------------------------------------------------- existence scan

    def _scan_all(self) -> None:
        with self._lock:
            subs = list(self._subs)
            self._existence_scans += 1
            # retry watches on dirs that were absent at subscribe time
            # (hot-unplug/replug): once the dir is back, events flow at
            # inotify latency again instead of scan cadence
            pending = [d for d in self._pending_dirs if os.path.isdir(d)]
            for d in pending:
                self._pending_dirs.discard(d)
                self._watch_dir(d)
        for sub in subs:
            self._scan_subscription(sub)

    def _scan_subscription(self, sub: HubSubscription) -> None:
        if not sub._active:
            return
        for key, path in list(sub.group_paths.items()):
            exists = os.path.exists(path)
            if sub._fs_state.get(key) != exists:
                self._fs_transition(sub, key, exists,
                                    "device node %s (re)created" if exists
                                    else "device node %s missing", path)
        if sub.socket_path and not os.path.exists(sub.socket_path):
            # covers both the subscribe-time race (unlink between the grpc
            # bind and the watch add) and inotify event drops
            self._report_socket_gone(sub)

    # ------------------------------------------------------- probe cycle

    def probe_cycle(self) -> Dict[str, bool]:
        """One deduped, deadline-bounded probe pass; returns {bdf: alive}.

        Called by the hub loop every poll_interval_s; also callable
        directly (bench/tests) — serialized by _cycle_lock either way.
        Every unique BDF across all subscriptions is probed ONCE on the
        worker pool; verdicts are collected until `probe_deadline_s` after
        cycle start, and a probe that has not answered by then is scored
        dead (and counted) instead of stalling the cycle — the next cycle
        re-probes it, so a transiently slow chip self-heals.
        """
        with self._cycle_lock, \
                trace.span("health.probe_cycle",
                           histogram="tdp_probe_cycle_ms") as cycle_span:
            t0 = time.monotonic()
            with self._lock:
                subs = [s for s in self._subs
                        if s._active and s.probe is not None and s.group_bdfs]
                pool = self._pool
            if pool is None:
                return {}
            # dedup: first subscription to mention a BDF supplies its probe
            # + representative node (all exposures of a chip share the same
            # physical config space, so any subscriber's probe is valid)
            requested = 0
            bdf_map: Dict[str, Tuple[Callable, Optional[str]]] = {}
            for sub in subs:
                for key, bdfs in sub.group_bdfs.items():
                    node = sub.group_paths.get(key)
                    for bdf in bdfs:
                        requested += 1
                        bdf_map.setdefault(bdf, (sub.probe, node))
            # drop stuck entries whose worker finally returned; a BDF whose
            # previous probe is STILL running keeps its dead verdict without
            # a resubmission (see _stuck above — one hung chip must cost one
            # worker, not one worker per cycle). _stuck is read/written
            # under _lock: stats() iterates it from HTTP threads
            with self._lock:
                self._stuck = {b: f for b, f in self._stuck.items()
                               if not f.done()}
                still_stuck = set(self._stuck)
            verdicts: Dict[str, bool] = {}
            futs: Dict[str, futures.Future] = {}
            # partition into BATCHED groups and singles (round 20): a
            # spawn-mode probe closure carries a `.batch` callable (one
            # broker crossing for the whole group — see BrokeredHealth.
            # chip_alive_batch) and a `.batch_key` identifying which
            # closures may share a crossing; everything else keeps the
            # one-submission-per-bdf path unchanged
            batch_groups: Dict[object, Tuple[
                Callable, List[Tuple[str, Optional[str]]]]] = {}
            singles: Dict[str, Tuple[Callable, Optional[str]]] = {}
            for bdf, (probe, node) in bdf_map.items():
                if bdf in still_stuck:
                    verdicts[bdf] = False
                    continue
                batch_fn = getattr(probe, "batch", None)
                if batch_fn is not None:
                    gkey = getattr(probe, "batch_key", id(probe))
                    _fn, items = batch_groups.setdefault(
                        gkey, (batch_fn, []))
                    items.append((bdf, node))
                else:
                    singles[bdf] = (probe, node)
            batch_futs: List[Tuple[
                List[Tuple[str, Optional[str]]], futures.Future]] = []
            batched = sum(len(items)
                          for _fn, items in batch_groups.values())
            try:
                for bdf, (probe, node) in singles.items():
                    futs[bdf] = pool.submit(self._probe_one, probe, bdf,
                                            node)
                for batch_fn, items in batch_groups.values():
                    batch_futs.append(
                        (items, pool.submit(self._probe_batch, batch_fn,
                                            items)))
            except RuntimeError:
                return {}  # pool shut down under us (hub.stop mid-cycle)
            deadline = t0 + self.probe_deadline_s
            timeouts = 0
            for bdf, fut in futs.items():
                try:
                    verdicts[bdf] = fut.result(
                        timeout=max(0.0, deadline - time.monotonic()))
                except futures.CancelledError:
                    # hub stopped mid-cycle (shutdown cancelled the queue):
                    # score conservatively, nothing to count
                    verdicts[bdf] = False
                except futures.TimeoutError:
                    # the worker may still be stuck in the read; score the
                    # chip dead NOW (a dead chip returning all-FF slowly is
                    # the common cause). cancel() handles the queued-not-
                    # started case; a running one is remembered in _stuck
                    if not fut.cancel():
                        with self._lock:
                            self._stuck[bdf] = fut
                    verdicts[bdf] = False
                    timeouts += 1
                    log.warning("liveness probe for %s exceeded the %.2fs "
                                "deadline; scoring dead", bdf,
                                self.probe_deadline_s)
            for items, fut in batch_futs:
                try:
                    got = fut.result(
                        timeout=max(0.0, deadline - time.monotonic()))
                    for bdf, _node in items:
                        verdicts[bdf] = bool(got.get(bdf, False))
                except futures.CancelledError:
                    for bdf, _node in items:
                        verdicts[bdf] = False
                except futures.TimeoutError:
                    # the whole group shares one worker, so a stuck batch
                    # costs one worker and every member keeps its dead
                    # verdict without resubmission until it returns
                    if not fut.cancel():
                        with self._lock:
                            for bdf, _node in items:
                                self._stuck[bdf] = fut
                    for bdf, _node in items:
                        verdicts[bdf] = False
                    timeouts += 1
                    log.warning("batched liveness probe of %d chips "
                                "exceeded the %.2fs deadline; scoring "
                                "dead", len(items), self.probe_deadline_s)
            wall = time.monotonic() - t0
            cycle_span.set(probes=len(bdf_map),
                           deduped=requested - len(bdf_map),
                           batched=batched,
                           timeouts=timeouts)
            with self._lock:
                self._probe_cycles += 1
                self._probes_last_cycle = len(bdf_map)
                self._probes_deduped_last_cycle = requested - len(bdf_map)
                self._probe_timeouts += timeouts
                self._last_cycle_s = wall
            # fan verdicts back out through each subscription's filter
            for sub in subs:
                if not sub._active:
                    continue
                for key, bdfs in sub.group_bdfs.items():
                    healthy = all(verdicts.get(b, False) for b in bdfs)
                    with sub._state_lock:
                        if sub._probe_state.get(key) == healthy:
                            continue
                        sub._probe_state[key] = healthy
                        if not healthy:
                            log.warning(
                                "%s: liveness probe failed for %s (%s)",
                                sub.name, key, ",".join(bdfs))
                        self._deliver(sub, key, healthy, "probe")
            return verdicts

    def _probe_one(self, probe: Callable, bdf: str,
                   node: Optional[str]) -> bool:
        # fault point "native.probe" (value kind): a fired fault reports
        # the chip dead, exercising the Unhealthy -> recovery path — fires
        # in the hub so every subscriber sees the same injected verdict.
        # The per-BDF verdict span carries the bdf, so the fault event
        # faults.fire emits inherits it on the flight recorder.
        with trace.span("health.probe", bdf=bdf) as sp:
            try:
                if faults.fire("native.probe", bdf=bdf):
                    sp.set(alive=False, injected=True)
                    return False
                alive = bool(probe(bdf, node))
                sp.set(alive=alive)
                return alive
            except BrokerUnavailable as exc:
                # spawn mode, broker gone: the probe cannot answer, so
                # the chip scores dead (safe direction) — but the counter
                # and span attribute say WHY, and a broker respawn
                # recovers the verdict on the next cycle
                with self._lock:
                    self._probe_broker_unavailable += 1
                log.error("liveness probe for %s degraded (%s); scoring "
                          "dead until the broker returns", bdf, exc)
                sp.set(alive=False, broker_unavailable=True)
                return False
            except Exception as exc:
                # a raising probe must never kill the worker silently
                # healthy: score the chip dead and count it
                # (tdp_probe_errors_total)
                with self._lock:
                    self._probe_errors += 1
                log.error("liveness probe for %s raised (%s); scoring dead",
                          bdf, exc)
                sp.set(alive=False, probe_error=str(exc))
                return False

    def _probe_batch(self, batch_fn: Callable,
                     items: List[Tuple[str, Optional[str]]],
                     ) -> Dict[str, bool]:
        """One batched crossing for a whole probe group (spawn mode).
        Fault injection still applies PER BDF — an armed "native.probe"
        scores that chip dead without probing it, and the rest of the
        group still crosses — and a dead broker degrades every member
        exactly as the singular path would (counted per member, scored
        dead until the broker returns)."""
        out: Dict[str, bool] = {}
        live: List[Tuple[str, Optional[str]]] = []
        with trace.span("health.probe_batch", probes=len(items)) as sp:
            for bdf, node in items:
                if faults.fire("native.probe", bdf=bdf):
                    out[bdf] = False
                else:
                    live.append((bdf, node))
            if not live:
                sp.set(injected=len(items))
                return out
            try:
                got = batch_fn(live)
                for bdf, _node in live:
                    out[bdf] = bool(got.get(bdf, False))
            except BrokerUnavailable as exc:
                with self._lock:
                    self._probe_broker_unavailable += len(live)
                log.error("batched liveness probe of %d chips degraded "
                          "(%s); scoring dead until the broker returns",
                          len(live), exc)
                sp.set(broker_unavailable=True)
                for bdf, _node in live:
                    out[bdf] = False
            except Exception as exc:
                with self._lock:
                    self._probe_errors += len(live)
                log.error("batched liveness probe raised (%s); scoring "
                          "%d chips dead", exc, len(live))
                sp.set(probe_error=str(exc))
                for bdf, _node in live:
                    out[bdf] = False
        return out

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Counters + gauges for /status, /metrics and the perf guards.

        LOCK-FREE read side (the /status lockdep gate): every value is a
        GIL-atomic attribute/int read, `len()` on a live container, or a
        C-atomic `list(dict.values())` copy — a /status scrape can never
        queue behind a probe cycle holding the hub lock. Counters are
        written only under `_lock` (tsalint counter ownership), so reads
        here see a value at most one mutation stale."""
        prefixes = ("healthhub", "healthhub-probe")
        threads = sum(1 for t in threading.enumerate()
                      if t.name.startswith(prefixes))
        return {
            "inotify_fds": 1 if self._watcher is not None else 0,
            "fallback_polling": self._watcher is None
                                and self._watcher_failed,
            "watched_dirs": len(self._watched_dirs),
            # dirs awaiting their first successful inotify watch (absent
            # at subscribe time; retried by the existence scan)
            "pending_watch_dirs": len(self._pending_dirs),
            "subscriptions": len(self._subs),
            "probe_workers": self.probe_workers,
            "probe_deadline_s": self.probe_deadline_s,
            "threads": threads,
            "probe_cycles_total": self._probe_cycles,
            "probes_last_cycle": self._probes_last_cycle,
            "probes_deduped_last_cycle": self._probes_deduped_last_cycle,
            "probe_timeouts_total": self._probe_timeouts,
            "probe_errors_total": self._probe_errors,
            "probe_broker_unavailable_total":
                self._probe_broker_unavailable,
            # probes still blocked past their deadline right now: each
            # pins one pool worker until its read returns (the chip
            # keeps its dead verdict without resubmission meanwhile)
            "stuck_probes": sum(1 for f in list(self._stuck.values())
                                if not f.done()),
            "existence_scans_total": self._existence_scans,
            "last_cycle_ms": round(self._last_cycle_s * 1e3, 3),
        }
