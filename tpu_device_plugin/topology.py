"""ICI topology model and topology-aware preferred allocation.

This is the TPU-native replacement for the reference's NUMA-only
`GetPreferredAllocation` (reference: pkg/device_plugin/generic_device_plugin.go:470-608)
and the domain analogue of "parallelism strategy" (SURVEY.md §2 #18): the
scale dimension of a device plugin is *slice shape*. Chips on one host sit at
coordinates of a small ICI torus (3D for v4/v5p, 2D for v5e/v6e); a VMI that
receives an axis-aligned contiguous sub-slice can run XLA collectives over
ICI, while a ragged set falls back to PCIe/DCN. Preference order:

1. smallest axis-aligned ICI sub-box that covers the request,
2. single NUMA node (reference behavior),
3. kubelet-provided order (reference fallback).
"""

from __future__ import annotations

import functools
import itertools
import json
import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .naming import GenerationInfo

log = logging.getLogger(__name__)

Coords = Tuple[int, ...]


def load_topology_hints(path: Optional[str]) -> Dict[str, Coords]:
    """Optional JSON map BDF → [x, y, ...] torus coordinates."""
    if not path:
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        if not isinstance(raw, dict):
            raise ValueError("top level must be an object of bdf -> [coords]")
        return {bdf: tuple(int(c) for c in coords) for bdf, coords in raw.items()}
    except (OSError, ValueError, TypeError, AttributeError) as exc:
        log.warning("topology hints %s unreadable (%s); ignoring", path, exc)
        return {}


def assign_coords(
    bdfs: Sequence[str],
    info: Optional[GenerationInfo],
    hints: Optional[Dict[str, Coords]] = None,
    pcie_paths: Optional[Dict[str, str]] = None,
) -> Dict[str, Optional[Coords]]:
    """Place each BDF on the host-local torus.

    Explicit hints win. Otherwise chips are laid out along lexicographic
    torus coordinates in resolved-PCIe-path order: co-packaged chips share
    a hierarchy prefix (a switch's upstream port) at any nesting depth, so
    they fill CONSECUTIVE grid slots (SURVEY §7 hard part (a): host-side
    ICI adjacency without guest context). Consecutive slots are physically
    adjacent when group sizes align with the innermost torus axis — the
    common case for paired/quad trays; fleets where that heuristic (or
    hint-perturbed slot packing) is wrong supply explicit hints
    (Config.topology_hints_path), which always win. Without path info this
    degrades to sorted-BDF order — PCIe enumeration order tracks physical
    chip order. BDFs beyond the torus capacity get no coordinates (and
    therefore only NUMA-level preference).
    """
    hints = hints or {}
    pcie_paths = pcie_paths or {}
    out: Dict[str, Optional[Coords]] = {}
    if info is None:
        return {bdf: hints.get(bdf) for bdf in bdfs}
    dims = info.host_topology
    # Drop malformed hints (wrong arity / out of range) rather than letting a
    # typo'd hints file poison sub-box scoring downstream.
    bad = {b: c for b, c in hints.items()
           if len(c) != len(dims) or any(not 0 <= x < d for x, d in zip(c, dims))}
    for b, c in bad.items():
        log.warning("topology hint %s=%s invalid for torus %s; ignoring", b, c, dims)
    hints = {b: c for b, c in hints.items() if b not in bad}
    # Duplicate coordinates across hint entries: two chips on ONE torus
    # slot would poison every sub-box score downstream (a "2-chip box"
    # that is physically one chip). Reject the WHOLE colliding group —
    # picking a winner would silently mislabel the loser's physical slot
    # — and let the path/BDF layout place them like any unhinted chip.
    by_coord: Dict[Coords, List[str]] = {}
    for b, c in hints.items():
        by_coord.setdefault(c, []).append(b)
    colliding = {b for group in by_coord.values() if len(group) > 1
                 for b in group}
    for b in sorted(colliding):
        log.warning("topology hint %s=%s duplicates another hint's "
                    "coordinates on torus %s; ignoring the colliding "
                    "hints", b, hints[b], dims)
    hints = {b: c for b, c in hints.items() if b not in colliding}
    grid = list(itertools.product(*[range(d) for d in dims]))
    unhinted = [b for b in sorted(bdfs,
                                  key=lambda b: (pcie_paths.get(b, b), b))
                if b not in hints]
    taken = set(hints.values())
    free_slots = [c for c in grid if c not in taken]
    for bdf in bdfs:
        if bdf in hints:
            out[bdf] = hints[bdf]
    for bdf, coords in zip(unhinted, free_slots):
        out[bdf] = coords
    for bdf in bdfs:
        if bdf not in out:
            log.warning("chip %s exceeds %s host torus %s; no ICI coords",
                        bdf, info.name, info.host_topology)
            out[bdf] = None
    return out


@dataclass(frozen=True)
class AllocatableDevice:
    """What the allocator needs to know about one advertised device."""

    device_id: str            # kubelet device ID (BDF or partition uuid)
    numa_node: int
    coords: Optional[Coords] = None


class MustIncludeTooLarge(ValueError):
    """MustIncludeDeviceIDs exceeds AllocationSize (reference errors too, :535-538)."""


@functools.lru_cache(maxsize=64)
def _boxes(dims: Coords) -> Tuple[Tuple[int, Tuple[Tuple[int, int], ...],
                                        frozenset], ...]:
    """All axis-aligned sub-boxes as (volume, per-axis (start, length),
    covered-coordinate set), smallest volume first (so the scan can stop at
    the first feasible tier). The precomputed coordinate set turns the
    per-device containment test into one hash lookup on the Allocate/
    GetPreferredAllocation hot path.

    Non-wrapping: a host's chips are a *slice* of the pod torus, so partial
    axes have no wraparound ICI link — a "wrapped" pair would really be
    several hops apart. Full-axis boxes (length == dim) cover the wrap case.
    """
    per_axis = [
        [(s, l) for l in range(1, d + 1) for s in range(d) if s + l <= d]
        for d in dims
    ]
    def volume(box):
        v = 1
        for _, length in box:
            v *= length
        return v
    def coordset(box):
        return frozenset(itertools.product(
            *[range(start, start + length) for start, length in box]))
    return tuple(sorted(((volume(b), b, coordset(b))
                         for b in itertools.product(*per_axis)),
                        key=lambda vb: vb[0]))


class AllocationIndex:
    """Precomputed indexes for preferred_allocation over an immutable
    device set.

    The advertised device set is fixed for a plugin server's lifetime
    (rediscovery rebuilds the server), but the availability list changes
    with every kubelet call — so everything derivable from (devices,
    torus_dims) alone is computed once here, and `preferred()` does only
    the per-availability work: id→device/coords lookups become prebuilt
    dicts, and each box's member-id set replaces the per-call
    coords-in-boxset hashing. Measured on the bench host: cold
    GetPreferredAllocation ~27 → ~17 µs.
    """

    def __init__(self, devices: Sequence[AllocatableDevice],
                 torus_dims: Optional[Coords] = None) -> None:
        self.devices = tuple(devices)
        self.torus_dims = tuple(torus_dims) if torus_dims else None
        self.by_id = {d.device_id: d for d in self.devices}
        if self.torus_dims:
            ndims = len(self.torus_dims)
            self.coords_of = {
                i: d.coords for i, d in self.by_id.items()
                if d.coords is not None and len(d.coords) == ndims
            }
            # (volume, ids-in-box) per sub-box, volume-sorted like _boxes
            self.box_members: Tuple[Tuple[int, frozenset], ...] = tuple(
                (volume,
                 frozenset(i for i, c in self.coords_of.items()
                           if c in boxset))
                for volume, _box, boxset in _boxes(self.torus_dims))
        else:
            self.coords_of = {}
            self.box_members = ()

    def preferred(self, available_ids: Sequence[str],
                  must_include_ids: Sequence[str], size: int) -> List[str]:
        """Pick `size` device IDs, preferring contiguous ICI, then one
        NUMA node.

        `available_ids` order is the kubelet's and is preserved within
        each preference tier (reference preserves it the same way,
        :493-504).
        """
        if len(must_include_ids) > size:
            raise MustIncludeTooLarge(
                f"{len(must_include_ids)} must-include devices > "
                f"allocation size {size}")
        by_id = self.by_id
        avail = [i for i in available_ids if i in by_id]
        must = list(must_include_ids)
        need = size - len(must)
        must_set = set(must)
        fill_pool = [i for i in avail if i not in must_set]

        # Tier 1: smallest ICI sub-box covering must-include with enough
        # chips.
        coords_of = self.coords_of
        if self.torus_dims and all(i in coords_of for i in must):
            placed_pool = [i for i in fill_pool if i in coords_of]
            best: Optional[Tuple[Tuple[int, int], List[str]]] = None
            for volume, members in self.box_members:
                if best is not None and volume > best[0][0]:
                    break  # volume-sorted; no better score ahead
                if volume < size:
                    continue
                if not must_set <= members:
                    continue
                in_box = [i for i in placed_pool if i in members]
                if len(in_box) < need:
                    continue
                chosen = must + in_box[:need]
                numa_span = len({by_id[i].numa_node for i in chosen})
                score = (volume, numa_span)
                if best is None or score < best[0]:
                    best = (score, chosen)
            if best is not None:
                log.info("preferred allocation: ICI sub-box %s", best[1])
                return best[1]

        # Tier 2: a single NUMA node that can satisfy the request.
        nodes: Dict[int, List[str]] = {}
        for i in fill_pool:
            nodes.setdefault(by_id[i].numa_node, []).append(i)
        must_nodes = {by_id[i].numa_node for i in must if i in by_id}
        for node, ids in sorted(nodes.items()):
            if must_nodes and must_nodes != {node}:
                continue
            if len(ids) >= need:
                chosen = must + ids[:need]
                log.info("preferred allocation: NUMA node %d %s",
                         node, chosen)
                return chosen

        # Tier 3: kubelet order.
        chosen = must + fill_pool[:need]
        log.info("preferred allocation: kubelet-order fallback %s", chosen)
        return chosen


def preferred_allocation(
    devices: Sequence[AllocatableDevice],
    available_ids: Sequence[str],
    must_include_ids: Sequence[str],
    size: int,
    torus_dims: Optional[Coords] = None,
) -> List[str]:
    """One-shot form of AllocationIndex.preferred (tests, ad-hoc callers).

    Long-lived callers (the plugin servers) hold an AllocationIndex so the
    per-device-set precomputation is paid once, not per RPC.
    """
    return AllocationIndex(devices, torus_dims).preferred(
        available_ids, must_include_ids, size)
