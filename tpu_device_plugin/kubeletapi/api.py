"""Hand-rolled gRPC service/stub wiring for the kubelet v1beta1 API.

grpcio's generic handler API lets us register method handlers without
generated service stubs. Method paths (`/v1beta1.DevicePlugin/...`) and the
constants below are part of the kubelet contract (reference:
vendor/k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/constants.go:19-46).
"""

from __future__ import annotations

import grpc

from . import deviceplugin_v1beta1_pb2 as pb

# -- kubelet contract constants ------------------------------------------------
API_VERSION = "v1beta1"
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"
KUBELET_SOCKET = DEVICE_PLUGIN_PATH + "kubelet.sock"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

_DEVICE_PLUGIN_SERVICE = "v1beta1.DevicePlugin"
_REGISTRATION_SERVICE = "v1beta1.Registration"


# -- pre-serialized response passthrough (round 15) ----------------------------
# The hot handlers (ListAndWatch sends, Allocate, GetPreferredAllocation,
# DRA prepare acks) assemble responses from pre-serialized epoch-keyed
# byte segments. On the gRPC path those bytes must reach the wire
# WITHOUT a parse + re-serialize round-trip, so the response serializers
# below pass a RawResponse payload through untouched; any other return
# value serializes normally (message-path fallbacks, every other RPC).

class RawResponse:
    """Pre-serialized response bytes for the passthrough serializers."""

    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        self.data = data


class _RawContextSentinel:
    """Marker context for bench/tests: handlers given this context return
    their RawResponse exactly as the transport serializer would see it
    (a real gRPC ServicerContext triggers the same path in production)."""

    def abort(self, code, details):
        raise RuntimeError(f"handler aborted under RAW_CONTEXT: "
                           f"{code} {details}")


RAW_CONTEXT = _RawContextSentinel()


def wants_raw(context) -> bool:
    """True when the handler's return feeds a passthrough serializer
    (real gRPC transport) or the caller explicitly asked for wire bytes
    (RAW_CONTEXT); direct in-process callers (tests, bench handler-
    compute loops, fleetsim) get parsed messages instead."""
    return context is RAW_CONTEXT or isinstance(context, grpc.ServicerContext)


def raw_or(serialize):
    """Wrap a protobuf SerializeToString into a RawResponse-passthrough
    response serializer."""

    def _serialize(msg):
        if type(msg) is RawResponse:
            return msg.data
        return serialize(msg)

    return _serialize


class DevicePluginServicer:
    """Server-side interface for the DevicePlugin service (5 RPCs)."""

    def GetDevicePluginOptions(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetDevicePluginOptions")

    def ListAndWatch(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "ListAndWatch")

    def GetPreferredAllocation(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetPreferredAllocation")

    def Allocate(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Allocate")

    def PreStartContainer(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "PreStartContainer")


def add_device_plugin_servicer(server: grpc.Server, servicer: DevicePluginServicer) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=raw_or(
                pb.ListAndWatchResponse.SerializeToString),
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=raw_or(
                pb.PreferredAllocationResponse.SerializeToString),
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=raw_or(pb.AllocateResponse.SerializeToString),
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_DEVICE_PLUGIN_SERVICE, handlers),)
    )


class DevicePluginStub:
    """Client stub for the DevicePlugin service (what the kubelet dials)."""

    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{_DEVICE_PLUGIN_SERVICE}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{_DEVICE_PLUGIN_SERVICE}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


class RegistrationServicer:
    """Server-side interface for the Registration service (fake kubelet in tests)."""

    def Register(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Register")


def add_registration_servicer(server: grpc.Server, servicer: RegistrationServicer) -> None:
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_REGISTRATION_SERVICE, handlers),)
    )


class RegistrationStub:
    """Client stub for the kubelet Registration service (the plugin dials this)."""

    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{_REGISTRATION_SERVICE}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )
