"""DRA driver tests: ResourceSlice publishing, kubelet registration
handshake, NodePrepareResources/NodeUnprepareResources over real gRPC,
CDI spec lifecycle, checkpoint restart recovery.

The API server is a stdlib HTTP server faking exactly the endpoints the
driver touches (nodes GET, resourceslices CRUD, resourceclaims GET); the
kubelet side is a real gRPC client dialing the driver's sockets the way
kubelet's pluginwatcher + DRA manager do.
"""

import json
import os
import shutil
import tempfile
import threading
import time as time_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover
from tpu_device_plugin.dra import DraDriver, slice_device_name
from tpu_device_plugin.kubeapi import ApiClient
from tpu_device_plugin.kubeletapi import draapi, drapb, regpb


class FakeApiServer:
    """Just enough of the kube-apiserver for the DRA driver."""

    def __init__(self, versions=("v1beta1",)):
        self.slices = {}      # name -> object (with resourceVersion)
        self.claims = {}      # (ns, name) -> object
        self.requests = []    # (method, path) log
        self.connections = 0  # distinct TCP connections accepted
        self.versions = list(versions)  # served resource.k8s.io versions
        # per-request latency injected before answering (bench.py
        # --attach-burst: a loopback fake has no network, so the RTT a
        # real in-cluster apiserver costs — the wait the parallel prepare
        # pool overlaps — is modeled explicitly, like the health bench's
        # injected slow chip). time.sleep releases the GIL, so concurrent
        # requests genuinely overlap the way real socket waits do.
        self.latency_s = 0.0
        self._rv = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 like a real apiserver, so the ApiClient's
            # keep-alive pool is actually exercised (Content-Length is
            # always sent by _send, which 1.1 keep-alive requires).
            # Buffered writes + no Nagle: BaseHTTPRequestHandler's default
            # unbuffered wfile emits each header line as its own packet,
            # which on a reused connection interacts with delayed ACK into
            # ~40 ms per-request stalls.
            protocol_version = "HTTP/1.1"
            wbufsize = 65536
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def setup(self):
                outer.connections += 1
                super().setup()

            def _send(self, code, obj=None):
                body = json.dumps(obj or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                outer.requests.append(("GET", self.path))
                if outer.latency_s:
                    time_mod.sleep(outer.latency_s)
                if self.path.rstrip("/") == "/apis/resource.k8s.io":
                    return self._send(200, {
                        "kind": "APIGroup", "name": "resource.k8s.io",
                        "versions": [{"groupVersion": f"resource.k8s.io/{v}",
                                      "version": v}
                                     for v in outer.versions]})
                if self.path.startswith("/api/v1/nodes/"):
                    name = self.path.rsplit("/", 1)[-1]
                    return self._send(200, {"metadata": {
                        "name": name, "uid": f"uid-{name}"}})
                if "watch=" in self.path:
                    # no watch support here: a reflector pointed at this
                    # fake must take its typed degraded-polling ladder
                    return self._send(400, {"reason": "watch unsupported"})
                if self.path.split("?", 1)[0].rstrip("/").endswith(
                        "/resourceslices"):
                    # collection LIST (the watch reconciler's relist; this
                    # fake serves no watch streams, so a reflector pointed
                    # here exercises the typed degraded-polling ladder)
                    return self._send(200, {
                        "kind": "ResourceSliceList",
                        "metadata": {"resourceVersion": str(outer._rv)},
                        "items": list(outer.slices.values())})
                if "/resourceslices/" in self.path:
                    name = self.path.rsplit("/", 1)[-1]
                    if name in outer.slices:
                        return self._send(200, outer.slices[name])
                    return self._send(404, {"reason": "NotFound"})
                if "/resourceclaims/" in self.path:
                    parts = self.path.split("/")
                    ns, name = parts[-3], parts[-1]
                    obj = outer.claims.get((ns, name))
                    if obj is not None:
                        return self._send(200, obj)
                    return self._send(404, {"reason": "NotFound"})
                return self._send(404, {})

            def do_POST(self):
                outer.requests.append(("POST", self.path))
                obj = self._body()
                name = obj["metadata"]["name"]
                outer._rv += 1
                obj["metadata"]["resourceVersion"] = str(outer._rv)
                outer.slices[name] = obj
                return self._send(201, obj)

            def do_PUT(self):
                outer.requests.append(("PUT", self.path))
                name = self.path.rsplit("/", 1)[-1]
                obj = self._body()
                live = outer.slices.get(name)
                if live is None:
                    return self._send(404, {})
                if (obj["metadata"].get("resourceVersion")
                        != live["metadata"]["resourceVersion"]):
                    return self._send(409, {"reason": "Conflict"})
                outer._rv += 1
                obj["metadata"]["resourceVersion"] = str(outer._rv)
                outer.slices[name] = obj
                return self._send(200, obj)

            def do_DELETE(self):
                outer.requests.append(("DELETE", self.path))
                name = self.path.rsplit("/", 1)[-1]
                if outer.slices.pop(name, None) is None:
                    return self._send(404, {})
                return self._send(200, {})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def add_claim(self, ns, name, uid, driver, results, generation=None):
        meta = {"namespace": ns, "name": name, "uid": uid}
        if generation is not None:
            meta["generation"] = generation
        self.claims[(ns, name)] = {
            "metadata": meta,
            "status": {"allocation": {"devices": {"results": [
                {"request": r.get("request", "tpu"), "driver": driver,
                 "pool": r.get("pool", "node-a"), "device": r["device"]}
                for r in results
            ]}}},
        }

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def apiserver():
    s = FakeApiServer()
    yield s
    s.stop()


@pytest.fixture()
def host():
    # short root: unix socket paths cap at ~107 chars and pytest's tmp_path
    # nesting blows past it for the plugins_registry socket
    root = tempfile.mkdtemp(prefix="tdpdra-")
    h = FakeHost(root)
    for i in range(4):
        h.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                            iommu_group=str(11 + i), numa_node=i // 2))
    cfg = Config().with_root(root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    yield h, cfg
    shutil.rmtree(root, ignore_errors=True)


def make_driver(cfg, apiserver, node="node-a"):
    registry, generations = discover(cfg)
    api = (ApiClient(apiserver.url, token_path="/nonexistent-token")
           if apiserver is not None else None)
    return DraDriver(cfg, registry, generations, node_name=node, api=api)


def chip_name(i):
    return slice_device_name(f"0000:00:{4 + i:02x}.0")


# --------------------------------------------------------------- slices


def test_publish_resource_slice(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    assert len(apiserver.slices) == 1
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["driver"] == "cloud-tpus.google.com"
    assert obj["spec"]["nodeName"] == "node-a"
    assert obj["spec"]["pool"]["generation"] == 1
    devices = obj["spec"]["devices"]
    assert len(devices) == 4
    by_name = {d["name"]: d for d in devices}
    attrs = by_name[chip_name(0)]["basic"]["attributes"]
    assert attrs["generation"] == {"string": "v5e"}
    assert attrs["bdf"] == {"string": "0000:00:04.0"}
    assert attrs["iommuGroup"] == {"string": "11"}
    assert attrs["numaNode"] == {"int": 0}
    assert attrs["type"] == {"string": "passthrough"}
    # ICI coordinates are published for CEL selectors
    assert "iciX" in attrs and "iciY" in attrs
    # garbage-collection anchor on the Node object
    owner = obj["metadata"]["ownerReferences"][0]
    assert owner["kind"] == "Node" and owner["uid"] == "uid-node-a"


def test_republish_unchanged_keeps_generation(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 1
    # no PUT happened for the unchanged republish
    assert [m for m, _ in apiserver.requests].count("PUT") == 0


def test_republish_changed_inventory_bumps_generation(host, apiserver, tmp_path):
    h, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    h.add_chip(FakeChip("0000:00:09.0", device_id="0063",
                        iommu_group="19", numa_node=1))
    registry, generations = discover(cfg)
    driver.set_inventory(registry, generations)
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 2
    assert len(obj["spec"]["devices"]) == 5


def test_apply_gone_drops_device_from_slice_and_inventory(host, apiserver):
    """Regression (ISSUE 7 satellite): a device that DISAPPEARED (hot-
    unplug) must leave the published inventory entirely — removed from
    by_name so prepares fail with a typed 'departed' error — not ride the
    unhealthy prune while still being plannable."""
    from tpu_device_plugin.discovery import discover as rediscover

    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    ep0 = driver._inventory_snapshot()
    assert driver.apply_gone(["0000:00:04.0"]) is True
    # unknown/repeat raws publish nothing
    assert driver.apply_gone(["0000:00:04.0"]) is False
    assert driver.apply_gone(["no-such-device"]) is False
    ep1 = driver._inventory_snapshot()
    assert ep1.epoch_id == ep0.epoch_id + 1
    assert chip_name(0) not in ep1.by_name          # gone, not just pruned
    assert chip_name(0) in ep1.departed
    assert driver.departed_devices() == ["0000:00:04.0"]
    obj = next(iter(apiserver.slices.values()))
    names = {d["name"] for d in obj["spec"]["devices"]}
    assert chip_name(0) not in names and len(names) == 3
    assert obj["spec"]["pool"]["generation"] == 2
    # contrast: an UNHEALTHY device stays in by_name (it may recover in
    # place), it is merely pruned from the slice body
    assert driver.apply_health({"0000:00:05.0": False}) is True
    assert chip_name(1) in driver._by_name
    # a prepare against the departed device fails with the typed error
    apiserver.add_claim("ns1", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="c1",
                                       uid="uid-1"))
    assert "departed" in resp.claims["uid-1"].error
    # replug + rediscovery readmits: departed mark clears, name returns
    driver.set_inventory(*rediscover(cfg))
    assert driver.departed_devices() == []
    assert chip_name(0) in driver._by_name


def test_empty_inventory_withdraws_slice(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    assert apiserver.slices
    from tpu_device_plugin.registry import Registry
    driver.set_inventory(Registry(), {})
    assert driver.publish_resource_slices()
    assert not apiserver.slices


# --------------------------------------------------- registration handshake


def test_registration_handshake(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    driver.start()
    try:
        with grpc.insecure_channel(
                f"unix://{driver.registration_socket_path}") as ch:
            stub = draapi.PluginRegistrationStub(ch)
            info = stub.GetInfo(regpb.InfoRequest(), timeout=5)
            assert info.type == "DRAPlugin"
            assert info.name == "cloud-tpus.google.com"
            assert info.endpoint == driver.dra_socket_path
            assert list(info.supported_versions) == ["v1", "v1beta1"]
            stub.NotifyRegistrationStatus(
                regpb.RegistrationStatus(plugin_registered=True), timeout=5)
        assert driver.registered.wait(2)
        assert driver.registration_error is None
    finally:
        driver.stop()


def test_registration_rejection_recorded(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    driver.start()
    try:
        with grpc.insecure_channel(
                f"unix://{driver.registration_socket_path}") as ch:
            stub = draapi.PluginRegistrationStub(ch)
            stub.NotifyRegistrationStatus(
                regpb.RegistrationStatus(plugin_registered=False,
                                         error="version mismatch"), timeout=5)
        assert driver.registered.wait(2)
        assert driver.registration_error == "version mismatch"
    finally:
        driver.stop()


def test_registration_socket_recovery_via_health_hub(host, apiserver):
    """A wiped plugins_registry socket (kubelet restart) must be noticed by
    the shared health hub and both sockets re-served — the old shape left
    the gRPC server bound to a dangling inode the kubelet can never find."""
    import time as time_mod

    from tpu_device_plugin.healthhub import HealthHub

    _, cfg = host
    hub = HealthHub(poll_interval_s=0.1, probe_workers=1)
    driver = make_driver(cfg, apiserver)
    driver.attach_health_hub(hub)
    driver.start()
    try:
        assert os.path.exists(driver.registration_socket_path)
        os.unlink(driver.registration_socket_path)
        deadline = time_mod.monotonic() + 10
        while not os.path.exists(driver.registration_socket_path) \
                and time_mod.monotonic() < deadline:
            time_mod.sleep(0.05)
        assert os.path.exists(driver.registration_socket_path), \
            "registration socket never re-served after the wipe"
        # the re-served socket answers GetInfo
        with grpc.insecure_channel(
                f"unix://{driver.registration_socket_path}") as ch:
            info = draapi.PluginRegistrationStub(ch).GetInfo(
                regpb.InfoRequest(), timeout=5)
            assert info.type == "DRAPlugin"
    finally:
        driver.stop()
        hub.stop()
    # stop() unsubscribed: recreating then unlinking the socket path fires
    # nothing (the driver is gone, not restarting)
    assert driver._health_sub is None


# ------------------------------------------------------ prepare/unprepare


def prepare(driver, claim):
    return driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=[claim]), None)


def test_prepare_and_unprepare_claim(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}, {"device": chip_name(1)}])
    claim = drapb.Claim(namespace="ns1", name="claim1", uid="uid-1")
    resp = prepare(driver, claim)
    out = resp.claims["uid-1"]
    assert out.error == ""
    assert len(out.devices) == 2
    assert out.devices[0].device_name == chip_name(0)
    assert out.devices[0].pool_name == "node-a"
    assert list(out.devices[0].request_names) == ["tpu"]
    # the composite claim CDI id rides on EVERY device entry so containers
    # referencing any request of the claim get the nodes (kubelet filters
    # prepared devices by request, then set-aggregates the ids)
    for d in out.devices:
        assert list(d.cdi_device_ids) == ["cloud-tpus.google.com/claim=uid-1"]

    # the CDI spec must carry the vfio nodes + the KubeVirt env contract
    spec_path = driver._claim_spec_path("uid-1")
    with open(spec_path) as f:
        spec = json.load(f)
    assert spec["kind"] == "cloud-tpus.google.com/claim"
    dev = spec["devices"][0]
    assert dev["name"] == "uid-1"
    paths = [n["path"] for n in dev["containerEdits"]["deviceNodes"]]
    assert "/dev/vfio/vfio" in paths
    assert "/dev/vfio/11" in paths and "/dev/vfio/12" in paths
    env = dev["containerEdits"]["env"]
    assert env == [
        "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V5E=0000:00:04.0,0000:00:05.0"]

    # unprepare removes spec + checkpoint
    resp = driver.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[claim]), None)
    assert resp.claims["uid-1"].error == ""
    assert not os.path.exists(spec_path)
    assert driver._checkpoint == {}


def test_prepare_is_idempotent(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(2)}])
    claim = drapb.Claim(namespace="ns1", name="claim1", uid="uid-1")
    first = prepare(driver, claim)
    n_gets = len(apiserver.requests)
    second = prepare(driver, claim)
    assert second.claims["uid-1"].devices == first.claims["uid-1"].devices
    # checkpoint hit: no second ResourceClaim GET
    assert len(apiserver.requests) == n_gets


def test_prepare_uid_mismatch_errors(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-NEW", driver.driver_name,
                        [{"device": chip_name(0)}])
    resp = prepare(driver, drapb.Claim(
        namespace="ns1", name="claim1", uid="uid-OLD"))
    assert "UID mismatch" in resp.claims["uid-OLD"].error
    assert not os.path.exists(driver._claim_spec_path("uid-OLD"))


def test_prepare_unknown_device_errors(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": "no-such-device"}])
    resp = prepare(driver, drapb.Claim(
        namespace="ns1", name="claim1", uid="uid-1"))
    assert "not in this node's inventory" in resp.claims["uid-1"].error


def test_unprepare_unknown_claim_is_ok(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    resp = driver.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[
            drapb.Claim(namespace="x", name="y", uid="never-prepared")]),
        None)
    assert resp.claims["never-prepared"].error == ""


def test_checkpoint_survives_driver_restart(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    claim = drapb.Claim(namespace="ns1", name="claim1", uid="uid-1")
    first = prepare(driver, claim)

    # new process: fresh driver over the same filesystem state
    driver2 = make_driver(cfg, apiserver)
    resp = prepare(driver2, claim)
    assert resp.claims["uid-1"].devices == first.claims["uid-1"].devices
    resp = driver2.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[claim]), None)
    assert resp.claims["uid-1"].error == ""
    assert not os.path.exists(driver2._claim_spec_path("uid-1"))


def test_prepare_rewrites_lost_cdi_spec(host, apiserver):
    """Reboot wipes /var/run: an idempotent re-prepare must re-materialize
    the CDI spec file, not just echo the checkpoint."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    claim = drapb.Claim(namespace="ns1", name="claim1", uid="uid-1")
    prepare(driver, claim)
    os.unlink(driver._claim_spec_path("uid-1"))
    resp = prepare(driver, claim)
    assert resp.claims["uid-1"].error == ""
    assert os.path.exists(driver._claim_spec_path("uid-1"))


def test_prepare_partitions_mdev_and_logical(host, apiserver, tmp_path):
    h, cfg = host
    h.add_mdev("uuid-mdev-1", "TPU vhalf", "0000:00:04.0", iommu_group="31")
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim(
        "ns1", "claim1", "uid-1", driver.driver_name,
        [{"device": slice_device_name("uuid-mdev-1"), "request": "vtpu"}])
    resp = prepare(driver, drapb.Claim(
        namespace="ns1", name="claim1", uid="uid-1"))
    out = resp.claims["uid-1"]
    assert out.error == ""
    with open(driver._claim_spec_path("uid-1")) as f:
        spec = json.load(f)
    edits = spec["devices"][0]["containerEdits"]
    paths = [n["path"] for n in edits["deviceNodes"]]
    assert "/dev/vfio/vfio" in paths and "/dev/vfio/31" in paths
    env = dict(e.split("=", 1) for e in edits["env"])
    assert env["MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_TPU_VHALF"] == \
        "uuid-mdev-1"


def test_prepare_mdev_retyped_errors(host, apiserver):
    """vtpu.py parity: a live mdev whose type changed since discovery must
    fail prepare (TOCTOU), not hand the VMI a different partition type."""
    h, cfg = host
    h.add_mdev("uuid-mdev-2", "TPU vhalf", "0000:00:05.0", iommu_group="32")
    driver = make_driver(cfg, apiserver)
    name_path = os.path.join(cfg.mdev_base_path, "uuid-mdev-2",
                             "mdev_type", "name")
    with open(name_path, "w") as f:
        f.write("TPU vquarter\n")
    apiserver.add_claim(
        "ns1", "claim1", "uid-1", driver.driver_name,
        [{"device": slice_device_name("uuid-mdev-2")}])
    resp = prepare(driver, drapb.Claim(
        namespace="ns1", name="claim1", uid="uid-1"))
    assert "live type" in resp.claims["uid-1"].error


def test_prepare_over_grpc_socket(host, apiserver):
    """Full wire path: kubelet-side stub against the served dra.sock."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(3)}])
    driver.start()
    try:
        with grpc.insecure_channel(
                f"unix://{driver.dra_socket_path}") as ch:
            stub = draapi.DraPluginStub(ch)
            resp = stub.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns1", name="claim1",
                                uid="uid-1")]), timeout=5)
            assert resp.claims["uid-1"].error == ""
            assert resp.claims["uid-1"].devices[0].device_name == chip_name(3)
            resp = stub.NodeUnprepareResources(
                drapb.NodeUnprepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns1", name="claim1",
                                uid="uid-1")]), timeout=5)
            assert resp.claims["uid-1"].error == ""
    finally:
        driver.stop()


def test_status_surfaces_dra(host, apiserver):
    """/status and /metrics carry DRA registration + prepared-claim facts."""
    from tpu_device_plugin.status import StatusServer

    class FakeManager:
        plugins = []
        pending = []
        native_info = {}
        draining = False

    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    prepare(driver, drapb.Claim(namespace="ns1", name="claim1", uid="uid-1"))
    status = StatusServer(FakeManager(), dra_driver=driver)
    s = status.status()
    assert s["dra"]["driver"] == "cloud-tpus.google.com"
    assert s["dra"]["prepared_claims"] == 1
    assert s["dra"]["serving"] is False          # not started in this test
    assert s["dra"]["kubelet_registered"] is False
    metrics = status.metrics()
    assert "tpu_plugin_dra_prepared_claims 1" in metrics
    assert "tpu_plugin_dra_registered 0" in metrics


# ------------------------------------------------ failure / degraded paths


def test_publish_without_api_client(host):
    _, cfg = host
    registry, generations = discover(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="n", api=None)
    assert driver.publish_resource_slices() is False


def test_publish_api_unreachable(host):
    """Transport-level API failure: publish reports False (run loop retries)."""
    _, cfg = host
    registry, generations = discover(cfg)
    api = ApiClient("http://127.0.0.1:1", timeout_s=0.3)   # closed port
    driver = DraDriver(cfg, registry, generations, node_name="n", api=api)
    assert driver.publish_resource_slices() is False


def test_prepare_api_unreachable_errors(host):
    _, cfg = host
    registry, generations = discover(cfg)
    api = ApiClient("http://127.0.0.1:1", timeout_s=0.3)
    driver = DraDriver(cfg, registry, generations, node_name="n", api=api)
    resp = prepare(driver, drapb.Claim(namespace="x", name="y", uid="u"))
    assert "ResourceClaim GET failed" in resp.claims["u"].error


def test_prepare_claim_not_found_errors(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="ghost",
                                       uid="u"))
    assert "ResourceClaim GET failed" in resp.claims["u"].error


def test_prepare_foreign_driver_results_prepare_nothing(host, apiserver):
    """A claim whose allocation names only ANOTHER driver's devices prepares
    zero devices without error (the kubelet calls every driver the claim's
    allocation mentions; ours may legitimately have no share)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", "gpu.example.com",
                        [{"device": "some-gpu"}])
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="claim1",
                                       uid="uid-1"))
    out = resp.claims["uid-1"]
    assert out.error == ""
    assert len(out.devices) == 0


def test_corrupt_checkpoint_degrades_to_empty(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    os.makedirs(os.path.dirname(driver.checkpoint_path), exist_ok=True)
    with open(driver.checkpoint_path, "w") as f:
        f.write("{not json")
    driver2 = make_driver(cfg, apiserver)
    assert driver2.prepared_claim_count() == 0


def test_stop_with_withdraw_deletes_slice(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    assert apiserver.slices
    driver.start()
    driver.stop(withdraw_slice=True)
    assert not apiserver.slices


def test_node_owner_ref_degrades_without_rbac(host, apiserver):
    """Node GET failing (no `get nodes` RBAC) publishes an un-owned slice
    rather than failing the publish."""
    _, cfg = host
    registry, generations = discover(cfg)

    class NoNodesClient(ApiClient):
        def get_json(self, path):
            if path.startswith("/api/v1/nodes/"):
                from tpu_device_plugin.kubeapi import ApiError
                raise ApiError("forbidden", code=403)
            return super().get_json(path)

    api = NoNodesClient(apiserver.url)
    driver = DraDriver(cfg, registry, generations, node_name="n", api=api)
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert "ownerReferences" not in obj["metadata"]


def test_prepare_logical_partitions_accel_and_vfio_parent(host, apiserver,
                                                          tmp_path):
    """vtpu.py parity for the logical providers: accel-backed partitions get
    the accel node under the operator's permission policy; a vfio-parent
    partition rides the parent planner (group expansion + PCI env)."""
    from dataclasses import replace
    h, cfg = host
    # chip 4 is vfio-bound (from the fixture); add an accel-owned chip
    h.add_chip(FakeChip("0000:00:09.0", device_id="0063", iommu_group="19",
                        driver="google-tpu", accel_index=3))
    pc = tmp_path / "partitions.json"
    # per_core splits the accel-owned chip; the explicit entry declares one
    # partition on a vfio-bound parent (the parent-planner prepare path)
    pc.write_text(json.dumps({
        "per_core": True,
        "partitions": [{"uuid": "lp-vfio-0", "type": "v5e_half",
                        "parent_bdf": "0000:00:04.0"}],
    }))
    cfg = replace(cfg, partition_config_path=str(pc),
                  partition_node_permissions="r")
    driver = make_driver(cfg, apiserver)
    part_names = [n for n, (kind, _, _) in driver._by_name.items()
                  if kind == "partition"]
    accel_parts = [n for n in part_names if "-09-0" in n]
    vfio_parts = [n for n in part_names if n == slice_device_name("lp-vfio-0")]
    assert accel_parts and vfio_parts
    apiserver.add_claim(
        "ns1", "claim1", "uid-1", driver.driver_name,
        [{"device": accel_parts[0]}, {"device": vfio_parts[0]}])
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="claim1",
                                       uid="uid-1"))
    assert resp.claims["uid-1"].error == ""
    with open(driver._claim_spec_path("uid-1")) as f:
        spec = json.load(f)
    edits = spec["devices"][0]["containerEdits"]
    nodes = {n["path"]: n["permissions"] for n in edits["deviceNodes"]}
    assert nodes["/dev/accel3"] == "r"       # policy carried into CDI
    assert "/dev/vfio/11" in nodes           # parent group of chip 04
    env = dict(e.split("=", 1) for e in edits["env"])
    # vfio-parent partitions attach as PCI passthrough of the parent
    assert env["PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V5E_HALF"] == \
        "0000:00:04.0"


def test_prepare_mdev_without_group_falls_back_to_wide_mount(host, apiserver):
    """vtpu.py:169-172 parity: an mdev whose iommu_group link is not
    visible degrades to the reference-compatible wide /dev/vfio mount
    instead of failing the prepare."""
    h, cfg = host
    h.add_mdev("uuid-wide", "TPU vhalf", "0000:00:06.0")   # no group link
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim(
        "ns1", "claim1", "uid-1", driver.driver_name,
        [{"device": slice_device_name("uuid-wide")}])
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="claim1",
                                       uid="uid-1"))
    assert resp.claims["uid-1"].error == ""
    with open(driver._claim_spec_path("uid-1")) as f:
        spec = json.load(f)
    paths = [n["path"] for n in
             spec["devices"][0]["containerEdits"]["deviceNodes"]]
    assert "/dev/vfio" in paths


# ------------------------------------------------------------ health loop


def test_health_transition_prunes_device_and_bumps_generation(host, apiserver):
    """VERDICT r3 item 3: a chip failing the liveness probe must leave the
    published ResourceSlice on the SAME transition that marks it Unhealthy
    on ListAndWatch — in DRA-only mode the scheduler would otherwise keep
    allocating dead hardware forever."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()

    assert driver.apply_health({"0000:00:04.0": False}) is True
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 2
    names = [d["name"] for d in obj["spec"]["devices"]]
    assert chip_name(0) not in names and len(names) == 3
    assert driver.unhealthy_devices() == ["0000:00:04.0"]

    # recovery republishes the device with another generation bump
    assert driver.apply_health({"0000:00:04.0": True}) is True
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 3
    assert chip_name(0) in [d["name"] for d in obj["spec"]["devices"]]
    assert driver.unhealthy_devices() == []


def test_health_republish_is_one_guarded_put_no_get(host, apiserver):
    """Generation-keyed delta: a health-only change publishes as ONE PUT
    under the cached resourceVersion — no read-modify-write GET."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    before = len(apiserver.requests)
    assert driver.apply_health({"0000:00:04.0": False}) is True
    new = apiserver.requests[before:]
    assert [m for m, _ in new] == ["PUT"], new
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 2
    assert driver.publish_stats["delta"] == 1
    assert driver.publish_stats["delta_conflicts"] == 0


def test_delta_conflict_falls_back_to_read_modify_write(host, apiserver):
    """An interleaved writer moves the slice's resourceVersion: the delta
    PUT 409s, and the classic GET+PUT reconciles without losing the
    health prune (exactly-once: no duplicate write of the same state)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    # another writer bumps the rv behind the driver's back
    name = next(iter(apiserver.slices))
    apiserver._rv += 1
    apiserver.slices[name]["metadata"]["resourceVersion"] = \
        str(apiserver._rv)
    assert driver.apply_health({"0000:00:04.0": False}) is True
    assert driver.publish_stats["delta_conflicts"] == 1
    obj = apiserver.slices[name]
    assert obj["spec"]["pool"]["generation"] == 2
    assert chip_name(0) not in [d["name"] for d in obj["spec"]["devices"]]
    # cache re-primed by the fallback: the next flip deltas again
    assert driver.apply_health({"0000:00:04.0": True}) is True
    assert driver.publish_stats["delta"] == 1
    assert apiserver.slices[name]["spec"]["pool"]["generation"] == 3


def test_delta_after_slice_deleted_behind_driver_restores_it(host,
                                                             apiserver):
    """A slice wiped externally (operator/GC) turns the delta PUT into a
    404; the fallback POST must restore it rather than dropping the
    publish."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    apiserver.slices.clear()
    assert driver.apply_health({"0000:00:04.0": False}) is True
    obj = next(iter(apiserver.slices.values()))
    assert chip_name(0) not in [d["name"] for d in obj["spec"]["devices"]]


def test_change_free_republish_still_heals_deleted_slice(host, apiserver):
    """The delta fast path must not skip the liveness GET on a change-free
    republish: a slice wiped externally between publishes is recreated
    even when nothing this driver owns changed (pre-delta behavior)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    apiserver.slices.clear()
    assert driver.publish_resource_slices()
    assert apiserver.slices, "deleted slice not recreated by no-op republish"


def test_foreign_low_generation_recreate_never_regresses_sequence(
        host, apiserver):
    """A foreign delete + recreate resets pool.generation to 1. The next
    publish must continue THIS driver's sequence (max(live, last) + 1),
    never replay 2..N — old allocations would look newer than the live
    pool and the fabric's exactly-once audit would see regressed
    generations. A matching-projection recreate is divergence too: it is
    not adopted as the delta baseline, and the guarded PUT restores the
    advertised generation."""
    import copy
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()              # generation 1
    assert driver.apply_health({"0000:00:04.0": False})  # 2
    assert driver.apply_health({"0000:00:04.0": True})   # 3
    name = next(iter(apiserver.slices))
    assert apiserver.slices[name]["spec"]["pool"]["generation"] == 3

    def foreign_recreate(mutate=None):
        obj = copy.deepcopy(apiserver.slices[name])
        obj["spec"]["pool"]["generation"] = 1
        apiserver._rv += 1
        obj["metadata"]["resourceVersion"] = str(apiserver._rv)
        if mutate:
            mutate(obj)
        apiserver.slices[name] = obj

    # DIVERGED projection: the recreate dropped a device; the repair
    # publish continues the sequence (4), never replays 2
    foreign_recreate(lambda o: o["spec"]["devices"].pop())
    driver._last_publish = None            # what a watch repair does
    assert driver.publish_resource_slices()
    assert apiserver.slices[name]["spec"]["pool"]["generation"] == 4

    # MATCHING projection at a REGRESSED generation: flagged diverged
    # (the watch reconciler would repair it), never adopted as the
    # delta baseline — the guarded PUT restores the generation (5)
    foreign_recreate()
    assert driver._slice_diverged(apiserver.slices[name])
    driver._last_publish = None
    assert driver.publish_resource_slices()
    assert apiserver.slices[name]["spec"]["pool"]["generation"] == 5
    driver.stop()


def test_apply_health_noop_transitions_do_not_publish(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    puts_before = [m for m, _ in apiserver.requests].count("PUT")
    # unknown device and already-healthy verdicts change nothing
    assert driver.apply_health({"0000:00:ff.0": False}) is False
    assert driver.apply_health({"0000:00:04.0": True}) is False
    assert [m for m, _ in apiserver.requests].count("PUT") == puts_before
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 1


def test_unhealthy_state_survives_inventory_swap(host, apiserver):
    """Rediscovery must not resurrect a dead chip in the slice; devices
    that left the inventory drop their health state."""
    h, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    assert driver.apply_health({"0000:00:05.0": False})

    h.add_chip(FakeChip("0000:00:09.0", device_id="0063",
                        iommu_group="19", numa_node=1))
    registry, generations = discover(cfg)
    driver.set_inventory(registry, generations)
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    names = [d["name"] for d in obj["spec"]["devices"]]
    assert chip_name(1) not in names            # still pruned
    assert len(names) == 4                      # 5 chips - 1 dead

    # the dead chip leaving the inventory clears its health entry
    shutil.rmtree(os.path.join(h.pci, "0000:00:05.0"))
    driver.set_inventory(*discover(cfg))
    assert driver.unhealthy_devices() == []


def test_plugin_server_health_listener_reaches_dra(host, apiserver):
    """End-to-end transition: the plugin server's ANDed verdict (probe
    source) must reach the DRA driver through the health_listener seam."""
    _, cfg = host
    registry, generations = discover(cfg)
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()

    from tpu_device_plugin.server import TpuDevicePlugin
    devs = next(iter(registry.devices_by_model.values()))
    plugin = TpuDevicePlugin(cfg, "v5e", registry, devs,
                             health_listener=driver.apply_health)
    plugin.set_devices_health(["0000:00:06.0"], False, "probe")
    obj = next(iter(apiserver.slices.values()))
    assert chip_name(2) not in [d["name"] for d in obj["spec"]["devices"]]
    # second verdict from another source is ANDed, no duplicate publish
    puts = [m for m, _ in apiserver.requests].count("PUT")
    plugin.set_devices_health(["0000:00:06.0"], False, "fs")
    assert [m for m, _ in apiserver.requests].count("PUT") == puts
    # recovery requires BOTH sources healthy again
    plugin.set_devices_health(["0000:00:06.0"], True, "probe")
    assert driver.unhealthy_devices() == ["0000:00:06.0"]
    plugin.set_devices_health(["0000:00:06.0"], True, "fs")
    assert driver.unhealthy_devices() == []
    obj = next(iter(apiserver.slices.values()))
    assert chip_name(2) in [d["name"] for d in obj["spec"]["devices"]]


# ------------------------------------------------- advisor r3 regressions


def test_server_side_defaulting_does_not_churn_generation(host, apiserver):
    """ADVICE r3 (dra.py:274): apiserver-added spec fields must not make
    every republish look like a change (PUT + generation bump forever)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    name = next(iter(apiserver.slices))
    # the server "defaults" a field the driver never set
    apiserver.slices[name]["spec"]["perDeviceNodeSelection"] = False
    assert driver.publish_resource_slices()
    assert driver.publish_resource_slices()
    obj = apiserver.slices[name]
    assert obj["spec"]["pool"]["generation"] == 1
    assert [m for m, _ in apiserver.requests].count("PUT") == 0


def test_colliding_raw_ids_get_distinct_slice_names(host, apiserver):
    """ADVICE r3 (dra.py:149): two raw ids that collapse to the same DNS
    label must publish as distinct devices, not silently overwrite."""
    from tpu_device_plugin.registry import Registry, TpuDevice
    _, cfg = host
    a = TpuDevice(bdf="0000:00:04.0", device_id="0063", iommu_group="11",
                  numa_node=0)
    b = TpuDevice(bdf="0000:00:04_0", device_id="0063", iommu_group="12",
                  numa_node=0)  # same label after sanitization
    assert slice_device_name(a.bdf) == slice_device_name(b.bdf)
    registry = Registry(
        devices_by_model={"0063": (a, b)},
        iommu_map={"11": (a,), "12": (b,)},
        bdf_to_group={a.bdf: "11", b.bdf: "12"},
    )
    driver = DraDriver(cfg, registry, {}, node_name="node-a",
                       api=ApiClient(apiserver.url,
                                     token_path="/nonexistent-token"))
    slice_obj = driver.build_slice()
    names = [d["name"] for d in slice_obj["spec"]["devices"]]
    assert len(names) == 2 and len(set(names)) == 2
    # both remain preparable under their published names
    by_bdf = {driver._by_name[n][2].bdf: n for n in names}
    assert set(by_bdf) == {a.bdf, b.bdf}


def test_rematerialize_races_concurrent_unprepare(host, apiserver):
    """ADVICE r3 (dra.py:457): a concurrent NodeUnprepareResources during
    the re-materialize API fetch must not leave an orphaned CDI spec file
    with no checkpoint entry tracking it. Under the per-claim-UID lock the
    unprepare (on its own thread, like a second kubelet worker) blocks
    until the prepare finishes, so the two can never interleave — the
    invariant is that the final state is consistent either way."""
    import time

    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    claim = drapb.Claim(namespace="ns1", name="c1", uid="uid-1")
    resp = prepare(driver, claim)
    assert resp.claims["uid-1"].error == ""
    spec_path = driver._claim_spec_path("uid-1")
    # the spec file is lost (reboot wipes /var/run) ...
    os.unlink(spec_path)
    # ... and an unprepare races in on another thread while the retry
    # fetches the claim
    real_fetch = driver._allocation_results
    racers = []

    def racing_fetch(c):
        results = real_fetch(c)
        t = threading.Thread(
            target=lambda: driver.NodeUnprepareResources(
                drapb.NodeUnprepareResourcesRequest(claims=[claim]), None),
            daemon=True)
        t.start()
        racers.append(t)
        time.sleep(0.05)   # give the unprepare every chance to interleave
        return results

    driver._allocation_results = racing_fetch
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=[claim]), None)
    driver._allocation_results = real_fetch
    for t in racers:
        t.join(timeout=10)
        assert not t.is_alive(), "racing unprepare deadlocked"
    # the race resolves to a consistent state — never a spec without an
    # entry tracking it (nor the reverse)
    has_entry = driver.prepared_claim_count() == 1
    has_spec = os.path.exists(spec_path)
    assert has_entry == has_spec
    assert resp.claims["uid-1"].error == "" or not has_spec
    driver.stop()


def test_all_unhealthy_keeps_slice_with_bumped_generation(host, apiserver):
    """All-devices-unhealthy must NOT take the withdraw path: a
    delete/recreate cycle resets pool.generation to 1, making stale
    allocations look newer than the live pool."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    dead = {f"0000:00:{4 + i:02x}.0": False for i in range(4)}
    assert driver.apply_health(dead)
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["devices"] == []          # nothing allocatable
    assert obj["spec"]["pool"]["generation"] == 2  # slice NOT deleted
    # recovery continues the generation sequence instead of restarting
    assert driver.apply_health({"0000:00:04.0": True})
    obj = next(iter(apiserver.slices.values()))
    assert obj["spec"]["pool"]["generation"] == 3
    assert len(obj["spec"]["devices"]) == 1


def test_failed_health_republish_arms_retry(host, apiserver):
    """A health republish that fails (apiserver blip) must self-retry —
    nothing re-fires the transition, so a dropped publish would leave a
    dead device allocatable forever."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    real_publish = driver.publish_resource_slices
    driver.publish_resource_slices = lambda: False   # apiserver blip
    try:
        assert driver.apply_health({"0000:00:04.0": False}) is True
        assert driver._republish_timer is not None
    finally:
        driver.publish_resource_slices = real_publish
    driver._republish_retry()                    # the timer's action
    obj = next(iter(apiserver.slices.values()))
    assert chip_name(0) not in [d["name"] for d in obj["spec"]["devices"]]
    assert driver._republish_timer is None       # success disarms
    driver.stop()


def test_no_api_client_never_arms_republish_retry(host):
    """Without an API client publish_resource_slices can never succeed, so
    a failed health republish must NOT arm the 30 s retry — it would re-arm
    and log 'no API client' every 30 s forever (ADVICE r4)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver=None)
    assert driver.api is None
    try:
        assert driver.apply_health({"0000:00:04.0": False}) is True
        assert driver._republish_timer is None
    finally:
        driver.stop()


def test_colliding_names_are_order_independent(host, apiserver):
    """EVERY member of a colliding label group is suffixed (including the
    first), so a surviving device can never inherit a removed device's
    plain label and silently re-point old claims."""
    from tpu_device_plugin.registry import Registry, TpuDevice
    _, cfg = host

    def reg(devs):
        return Registry(
            devices_by_model={"0063": tuple(devs)},
            iommu_map={d.iommu_group: (d,) for d in devs},
            bdf_to_group={d.bdf: d.iommu_group for d in devs},
        )

    a = TpuDevice(bdf="0000:00:04.0", device_id="0063", iommu_group="11",
                  numa_node=0)
    b = TpuDevice(bdf="0000:00:04_0", device_id="0063", iommu_group="12",
                  numa_node=0)
    api = ApiClient(apiserver.url, token_path="/nonexistent-token")
    driver = DraDriver(cfg, reg([a, b]), {}, node_name="node-a", api=api)
    names = {driver._raw_id(k, o): n
             for n, (k, g, o) in driver._by_name.items()}
    plain = slice_device_name(a.bdf)
    assert plain not in names.values()           # both suffixed
    name_b_full = names[b.bdf]
    # drop A: B's published name must not change (ADVICE r4 — a name is
    # sticky for the process lifetime once published suffixed, so a claim
    # allocated under name_b_full still resolves on a post-swap prepare
    # retry)
    driver.set_inventory(reg([b]), {})
    assert set(driver._by_name) == {name_b_full}
    # ...and even if A returns, names stay exactly as first published
    driver.set_inventory(reg([a, b]), {})
    assert {driver._raw_id(k, o): n
            for n, (k, g, o) in driver._by_name.items()} == names
    # ...and the guarantee survives a driver restart (sticky set persisted
    # beside the claim checkpoint): a FRESH process that discovers only B
    # must still publish B under its suffixed name
    driver2 = DraDriver(cfg, reg([b]), {}, node_name="node-a", api=api)
    assert set(driver2._by_name) == {name_b_full}


def test_plain_label_never_inherited_by_different_device(host, apiserver):
    """A plain label ever published for raw id X must never later name a
    DIFFERENT raw id that sanitizes to the same label, even when the two
    never coexist (no collision is ever seen): an old claim against the
    label would silently resolve to the wrong device. The newcomer is
    suffixed; the original owner keeps the plain label if it returns."""
    from tpu_device_plugin.registry import Registry, TpuDevice
    _, cfg = host

    def reg(devs):
        return Registry(
            devices_by_model={"0063": tuple(devs)},
            iommu_map={d.iommu_group: (d,) for d in devs},
            bdf_to_group={d.bdf: d.iommu_group for d in devs},
        )

    a = TpuDevice(bdf="0000:00:04.0", device_id="0063", iommu_group="11",
                  numa_node=0)
    imposter = TpuDevice(bdf="0000:00:04_0", device_id="0063",
                         iommu_group="12", numa_node=0)
    plain = slice_device_name(a.bdf)
    assert slice_device_name(imposter.bdf) == plain  # same sanitized label
    api = ApiClient(apiserver.url, token_path="/nonexistent-token")
    driver = DraDriver(cfg, reg([a]), {}, node_name="node-a", api=api)
    assert set(driver._by_name) == {plain}          # A owns the plain label
    # swap A out, imposter in — never coexisting
    driver.set_inventory(reg([imposter]), {})
    (imp_name,) = driver._by_name
    assert imp_name != plain                        # suffixed, not inherited
    # the owner returns: it still gets its plain label, imposter stays
    # suffixed — and the same holds in a fresh process (persisted)
    for d in (driver, DraDriver(cfg, reg([a, imposter]), {},
                                node_name="node-a", api=api)):
        if d is driver:
            d.set_inventory(reg([a, imposter]), {})
        assert d._by_name[plain][2].bdf == a.bdf
        assert d._by_name[imp_name][2].bdf == imposter.bdf


def test_rebuilt_plugin_first_poll_unprunes_recovered_chip(host, apiserver):
    """A chip that recovers while its plugin is being rebuilt (rediscovery
    restart) produces NO health transition on the fresh all-HEALTHY device
    table — only the unconditional first-poll snapshot delivery reconciles
    the DRA prune set."""
    _, cfg = host
    registry, generations = discover(cfg)
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    from tpu_device_plugin.server import TpuDevicePlugin
    devs = next(iter(registry.devices_by_model.values()))
    plugin = TpuDevicePlugin(cfg, "v5e", registry, devs,
                             health_listener=driver.apply_health)
    plugin.set_devices_health(["0000:00:04.0"], False, "probe")
    assert driver.unhealthy_devices() == ["0000:00:04.0"]
    # rediscovery rebuilds the plugin: fresh table, all HEALTHY, no memory
    rebuilt = TpuDevicePlugin(cfg, "v5e", registry, devs,
                              health_listener=driver.apply_health)
    # the chip has recovered; the monitor's first poll emits True
    # unconditionally (health.py _run_probes first-observation rule) —
    # HEALTHY -> HEALTHY is not a transition, but the snapshot still flows
    rebuilt.set_devices_health(["0000:00:04.0"], True, "probe")
    assert driver.unhealthy_devices() == []
    obj = next(iter(apiserver.slices.values()))
    assert chip_name(0) in [d["name"] for d in obj["spec"]["devices"]]


def test_stop_withdraw_wins_over_inflight_retry(host, apiserver):
    """stop(withdraw_slice=True) must not lose to a late retry publish:
    after stop returns, the slice stays deleted."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    driver.stop(withdraw_slice=True)
    assert not apiserver.slices
    # a straggler retry fires after stop: the _stopped guard refuses it
    driver._republish_retry()
    assert not apiserver.slices
    assert driver._republish_timer is None


# ---------------------------------------------------- version tolerance


def test_v1_apiserver_publishes_flat_device_schema(host, apiserver):
    """A resource.k8s.io/v1-only apiserver (VERDICT r3 item 7): the driver
    must discover v1, publish under /apis/resource.k8s.io/v1, and emit the
    v1 device schema (attributes flattened, no 'basic' wrapper)."""
    _, cfg = host
    apiserver.versions = ["v1"]
    driver = make_driver(cfg, apiserver)
    assert driver.resource_api_version() == "v1"
    assert driver.publish_resource_slices()
    assert any(p.startswith("/apis/resource.k8s.io/v1/resourceslices")
               for m, p in apiserver.requests if m == "POST")
    obj = next(iter(apiserver.slices.values()))
    assert obj["apiVersion"] == "resource.k8s.io/v1"
    dev = obj["spec"]["devices"][0]
    assert "basic" not in dev
    assert dev["attributes"]["bdf"] == {"string": "0000:00:04.0"}
    # unchanged republish is still change-free under the flat schema
    assert driver.publish_resource_slices()
    assert [m for m, _ in apiserver.requests].count("PUT") == 0


def test_v1beta1_apiserver_keeps_wrapped_schema(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.resource_api_version() == "v1beta1"
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert obj["apiVersion"] == "resource.k8s.io/v1beta1"
    assert "basic" in obj["spec"]["devices"][0]


def test_api_client_reuses_keepalive_connections(host, apiserver):
    """The ApiClient pools keep-alive connections: repeated publishes
    (GET + POST/PUT each) must ride a handful of TCP connections, not one
    per request — per-request TLS handshakes are the dominant cost of a
    real claim prepare."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.publish_resource_slices()
    # flip a device's health so every publish is a real write (change-free
    # republishes cost only a single liveness GET on the delta path)
    for i in range(4):
        assert driver.apply_health({"0000:00:04.0": i % 2 == 1})
    n_requests = len(apiserver.requests)
    # discovery + node uid + first GET+POST + 4 delta PUTs
    assert n_requests >= 7
    # sequential single-threaded use: everything after the first request
    # should reuse the pooled connection
    assert apiserver.connections <= 2, (
        f"{apiserver.connections} connections for {n_requests} requests")
    driver.stop()


def test_v1beta2_apiserver_uses_flattened_schema(host, apiserver):
    """A k8s-1.33-era apiserver serving ONLY v1beta2 (v1beta1 disabled,
    v1 not yet served) must not strand the driver on the v1beta1 fallback
    (ADVICE r4): v1beta2 is schema-identical to v1, so the driver publishes
    the flattened device shape under /apis/resource.k8s.io/v1beta2."""
    _, cfg = host
    apiserver.versions = ["v1beta2"]
    driver = make_driver(cfg, apiserver)
    assert driver.resource_api_version() == "v1beta2"
    assert driver.publish_resource_slices()
    assert any(p.startswith("/apis/resource.k8s.io/v1beta2/resourceslices")
               for m, p in apiserver.requests if m == "POST")
    obj = next(iter(apiserver.slices.values()))
    assert obj["apiVersion"] == "resource.k8s.io/v1beta2"
    dev = obj["spec"]["devices"][0]
    assert "basic" not in dev
    assert dev["attributes"]["bdf"] == {"string": "0000:00:04.0"}
    # v1 outranks v1beta2 when both are served
    apiserver.versions = ["v1beta2", "v1"]
    driver._note_api_404()                     # force re-discovery
    assert driver.resource_api_version() == "v1"


def test_version_discovery_failure_is_not_cached(host, apiserver):
    """A transient discovery failure must fall back to v1beta1 for that
    call WITHOUT pinning it for the process lifetime."""
    _, cfg = host
    apiserver.versions = ["v1"]
    driver = make_driver(cfg, apiserver)
    api = driver.api
    driver.api = ApiClient("http://127.0.0.1:1",     # nothing listens
                           token_path="/nonexistent-token")
    assert driver.resource_api_version() == "v1beta1"
    driver.api = api
    assert driver.resource_api_version() == "v1"     # re-discovered


def test_prepare_over_v1_grpc_service(host, apiserver):
    """The kubelet may dial v1.DRAPlugin: same servicer, same messages,
    and the REST side resolves claims through the discovered version."""
    _, cfg = host
    apiserver.versions = ["v1"]
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "claim1", "uid-1", driver.driver_name,
                        [{"device": chip_name(3)}])
    driver.start()
    try:
        with grpc.insecure_channel(
                f"unix://{driver.dra_socket_path}") as ch:
            stub = draapi.DraPluginStub(ch, version="v1")
            resp = stub.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns1", name="claim1",
                                uid="uid-1")]), timeout=5)
            assert resp.claims["uid-1"].error == ""
            assert resp.claims["uid-1"].devices[0].device_name == chip_name(3)
            # claim was fetched via the v1 REST path
            assert any("/apis/resource.k8s.io/v1/namespaces/" in p
                       for m, p in apiserver.requests if m == "GET")
            resp = stub.NodeUnprepareResources(
                drapb.NodeUnprepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns1", name="claim1",
                                uid="uid-1")]), timeout=5)
            assert resp.claims["uid-1"].error == ""
    finally:
        driver.stop()


def test_getinfo_advertises_both_versions(host, apiserver):
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    info = driver.GetInfo(regpb.InfoRequest(), None)
    assert list(info.supported_versions) == ["v1", "v1beta1"]


def test_unknown_only_versions_fall_back(host, apiserver):
    _, cfg = host
    apiserver.versions = ["v99alpha1"]
    driver = make_driver(cfg, apiserver)
    assert driver.resource_api_version() == "v1beta1"


def test_version_dropped_by_upgrade_rediscovers(host, apiserver):
    """A control-plane upgrade that drops the cached version must not
    strand the driver: the 404 clears the cache and the next publish
    re-discovers (the daemon outlives apiservers)."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    assert driver.resource_api_version() == "v1beta1"
    assert driver.publish_resource_slices()
    # upgrade: apiserver now serves only v1, and the old versioned paths
    # 404 (simulate by dropping the slice + switching the group document)
    apiserver.versions = ["v1"]
    apiserver.slices.clear()
    # next publish: GET 404 -> POST against cached v1beta1 path still
    # "works" in the fake (path-agnostic), so force the mutation-404 path
    # directly instead: the invalidation hook is what we pin here
    driver._note_api_404()
    assert driver.resource_api_version() == "v1"
    assert driver.publish_resource_slices()
    obj = next(iter(apiserver.slices.values()))
    assert obj["apiVersion"] == "resource.k8s.io/v1"
    assert "basic" not in obj["spec"]["devices"][0]


# ------------------------------------------------ attach-path concurrency


def test_unprepare_serialization_error_is_claim_error(host, apiserver):
    """A non-OSError checkpoint failure (unserializable entry) used to
    escape NodeUnprepareResources' `except OSError` and kill the whole
    multi-claim RPC — it must surface as THAT claim's out.error while
    other claims in the request still answer."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    claim = drapb.Claim(namespace="ns1", name="c1", uid="uid-1")
    assert prepare(driver, claim).claims["uid-1"].error == ""
    # an unserializable entry poisons the NEXT checkpoint write
    driver._checkpoint["poison"] = {"bad": object()}
    other = drapb.Claim(namespace="ns1", name="ghost", uid="uid-ghost")
    resp = driver.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[claim, other]), None)
    assert resp.claims["uid-1"].error != ""          # reported, not raised
    assert resp.claims["uid-ghost"].error == ""      # others unaffected
    # the failed deletion was rolled back: the claim is still recorded, so
    # a kubelet retry (after the poison clears) drains it
    assert "uid-1" in driver._checkpoint
    del driver._checkpoint["poison"]
    resp = driver.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[claim]), None)
    assert resp.claims["uid-1"].error == ""
    assert driver.prepared_claim_count() == 0
    driver.stop()


def test_concurrent_same_uid_prepares_one_spec_write(host, apiserver):
    """Two kubelet retries of the SAME claim racing: the per-claim-UID
    lock serializes them into one spec write + one checkpoint entry, and
    both callers get identical devices."""
    import time

    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}, {"device": chip_name(1)}])
    claim = drapb.Claim(namespace="ns1", name="c1", uid="uid-1")
    writes = []
    real_write = driver._write_claim_spec

    def counting_write(uid, specs, envs):
        writes.append(uid)
        time.sleep(0.05)   # widen the race window
        return real_write(uid, specs, envs)

    driver._write_claim_spec = counting_write
    results = {}

    def worker(name):
        results[name] = prepare(driver, claim).claims["uid-1"]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
        assert not t.is_alive()
    assert writes == ["uid-1"]                       # ONE spec write
    assert driver.prepared_claim_count() == 1        # ONE checkpoint entry
    assert results[0].error == "" and results[1].error == ""
    assert results[0].devices == results[1].devices
    driver.stop()


def test_prepare_ack_durable_before_crash(host, apiserver):
    """Group-commit flush barrier: every claim ACKed by a concurrent burst
    must be recoverable from the on-disk checkpoint by a fresh driver (a
    simulated crash immediately after the RPC returns)."""
    from dataclasses import replace as dc_replace

    _, cfg = host
    cfg = dc_replace(cfg, prepare_workers=4)
    driver = make_driver(cfg, apiserver)
    uids = [f"uid-burst-{i}" for i in range(8)]
    for i, uid in enumerate(uids):
        apiserver.add_claim("ns1", uid, uid, driver.driver_name,
                            [{"device": chip_name(i % 4)}])
    claims = [drapb.Claim(namespace="ns1", name=uid, uid=uid)
              for uid in uids]
    resp = driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=claims), None)
    for uid in uids:
        assert resp.claims[uid].error == "", resp.claims[uid].error
    # a burst coalesced into strictly fewer checkpoint writes than claims
    stats = driver.checkpoint_stats()
    assert stats["checkpoint_claims_coalesced_total"] == 8
    assert stats["checkpoint_commits_total"] <= 8
    # crash: a FRESH driver over the same filesystem recovers every ACK
    driver2 = make_driver(cfg, apiserver)
    assert driver2.prepared_claim_count() == 8
    for uid in uids:
        again = driver2.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns1", name=uid, uid=uid)]), None)
        assert again.claims[uid].error == ""
        assert again.claims[uid].devices == resp.claims[uid].devices
    driver.stop()
    driver2.stop()


def test_status_surfaces_attach_plane(host, apiserver):
    """/status + /metrics carry the attach-plane gauges and group-commit
    counters."""
    from tpu_device_plugin.status import StatusServer

    class FakeManager:
        plugins = []
        pending = []
        native_info = {}
        draining = False

    _, cfg = host
    driver = make_driver(cfg, apiserver)
    apiserver.add_claim("ns1", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    prepare(driver, drapb.Claim(namespace="ns1", name="c1", uid="uid-1"))
    status = StatusServer(FakeManager(), dra_driver=driver)
    s = status.status()
    assert s["dra"]["prepare_inflight"] == 0
    assert s["dra"]["prepare_workers"] == driver.prepare_workers
    assert s["dra"]["checkpoint_commits_total"] >= 1
    assert s["dra"]["checkpoint_claims_coalesced_total"] >= 1
    metrics = status.metrics()
    assert "tpu_plugin_dra_prepare_inflight 0" in metrics
    assert f"tpu_plugin_dra_prepare_workers {driver.prepare_workers}" \
        in metrics
    assert "tpu_plugin_dra_checkpoint_commits_total" in metrics
    assert "tpu_plugin_dra_checkpoint_claims_coalesced_total" in metrics
    driver.stop()


def test_prepare_after_stop_errors_instead_of_resurrecting_writer(host,
                                                                  apiserver):
    """A straggler RPC outliving stop() must get a per-claim error from
    the flush barrier — never hang, never spawn a fresh checkpoint writer
    that defeats the drain."""
    _, cfg = host
    driver = make_driver(cfg, apiserver)
    driver.stop()
    apiserver.add_claim("ns1", "late", "uid-late", driver.driver_name,
                        [{"device": chip_name(0)}])
    resp = prepare(driver, drapb.Claim(namespace="ns1", name="late",
                                       uid="uid-late"))
    assert "stopped" in resp.claims["uid-late"].error
    # rolled back: nothing recorded, no orphan spec, no writer thread
    assert driver.prepared_claim_count() == 0
    assert not os.path.exists(driver._claim_spec_path("uid-late"))
    assert driver._ckpt_thread is None or not driver._ckpt_thread.is_alive()
