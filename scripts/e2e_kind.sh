#!/usr/bin/env bash
# Real-kubelet e2e (VERDICT r2 next-item #3): run the plugin against an
# actual kubelet in a kind cluster and assert the full resource lifecycle:
#
#   register -> node allocatable cloud-tpus.google.com/v4: 4 -> pod
#   requesting 2 admitted by the devicemanager -> container starts with the
#   VFIO DeviceSpecs mounted and the PCI_RESOURCE env var injected.
#
# The TPU "hardware" is a fixture sysfs/devfs tree (scripts/
# make_fixture_host.py) mounted into the kind node; its /dev entries are
# replaced with real char-device nodes (mknod c 1 3) inside the node so the
# container runtime can actually mount them. Requires: docker, kind, kubectl.
#
# Run locally:  scripts/e2e_kind.sh
# CI: .github/workflows/e2e.yml (nightly + manual dispatch).
set -euo pipefail

CLUSTER=${CLUSTER:-tpu-dp-e2e}
IMG=tpu-kubevirt-device-plugin:e2e
FIXTURE=/tmp/tpu-fixture-e2e
REPO="$(cd "$(dirname "$0")/.." && pwd)"

cleanup() { kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true; }
trap cleanup EXIT

echo "--- build image"
docker build -f "$REPO/deployments/container/Dockerfile" -t "$IMG" "$REPO"

echo "--- fixture host tree"
rm -rf "$FIXTURE"
python3 "$REPO/scripts/make_fixture_host.py" "$FIXTURE"

echo "--- kind cluster (fixture mounted into the node)"
cat <<EOF | kind create cluster --name "$CLUSTER" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
nodes:
  - role: control-plane
    extraMounts:
      - hostPath: $FIXTURE
        containerPath: $FIXTURE
EOF
kind load docker-image "$IMG" --name "$CLUSTER"
NODE="${CLUSTER}-control-plane"

echo "--- real device nodes for the runtime to mount"
docker exec "$NODE" bash -c '
  set -e
  for f in '"$FIXTURE"'/dev/vfio/vfio '"$FIXTURE"'/dev/vfio/[0-9]* \
           '"$FIXTURE"'/dev/accel* '"$FIXTURE"'/dev/iommu \
           '"$FIXTURE"'/dev/vfio/devices/vfio*; do
    [ -e "$f" ] || continue
    rm -f "$f" && mknod "$f" c 1 3 && chmod 666 "$f"
  done'

echo "--- deploy plugin"
sed "s|IMAGE_PLACEHOLDER|$IMG|; s|FIXTURE_PLACEHOLDER|$FIXTURE|" \
    "$REPO/manifests/e2e/tpu-device-plugin-e2e.yaml" | kubectl apply -f -
kubectl -n kube-system rollout status ds/tpu-device-plugin-e2e --timeout=120s

echo "--- node allocatable"
for i in $(seq 1 30); do
  GOT=$(kubectl get node "$NODE" \
        -o jsonpath='{.status.allocatable.cloud-tpus\.google\.com/v4}' || true)
  [ "$GOT" = "4" ] && break
  sleep 2
done
[ "$GOT" = "4" ] || { echo "FAIL: allocatable v4=$GOT (want 4)"; \
  kubectl -n kube-system logs ds/tpu-device-plugin-e2e --tail=50; exit 1; }
echo "allocatable OK: cloud-tpus.google.com/v4=$GOT"

echo "--- pod admission + device mount + env"
kubectl apply -f "$REPO/manifests/e2e/tpu-consumer-pod.yaml"
kubectl wait --for=condition=Ready pod/tpu-consumer --timeout=120s || {
  kubectl describe pod tpu-consumer; exit 1; }
ENVV=$(kubectl exec tpu-consumer -- sh -c 'env | grep PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4')
echo "env: $ENVV"
echo "$ENVV" | grep -q "0000:" || { echo "FAIL: no BDFs in env"; exit 1; }
kubectl exec tpu-consumer -- sh -c 'ls /dev/vfio/vfio' >/dev/null
GROUPS_IN_POD=$(kubectl exec tpu-consumer -- sh -c \
  'ls /dev/vfio | grep -E "^[0-9]+$" | wc -l')
[ "$GROUPS_IN_POD" -ge 1 ] || {
  echo "FAIL: no per-IOMMU-group /dev/vfio/<group> node mounted in the pod"
  kubectl exec tpu-consumer -- ls /dev/vfio; exit 1; }
echo "group mounts OK: $GROUPS_IN_POD /dev/vfio/<group> node(s)"
echo "E2E PASS: real kubelet admitted the pod with TPU VFIO devices"

# ---------------------------------------------------------------------------
# KubeVirt stage (VERDICT r3 item 4): the actual externalResourceProvider
# contract. Install KubeVirt, whitelist the TPU resource on the live CR
# (reference: examples/kubevirt-featuregate-cm.yaml:10-18), create a VMI,
# and assert the virt-launcher pod is ADMITTED with the extended-resource
# request and the plugin's PCI_RESOURCE_* env/device mounts. Guest boot may
# fail without real VFIO ioctls — the admission/env contract is the
# testable surface. KUBEVIRT=0 skips (e.g. network-restricted local runs).
# ---------------------------------------------------------------------------
KUBEVIRT=${KUBEVIRT:-1}
if [ "$KUBEVIRT" = "1" ]; then
  echo "--- KubeVirt install"
  KUBEVIRT_VERSION=${KUBEVIRT_VERSION:-v1.3.1}
  KV_BASE="https://github.com/kubevirt/kubevirt/releases/download/${KUBEVIRT_VERSION}"
  kubectl apply -f "$KV_BASE/kubevirt-operator.yaml"
  kubectl apply -f "$KV_BASE/kubevirt-cr.yaml"
  # emulation: no KVM inside the kind node in CI
  kubectl -n kubevirt patch kubevirt kubevirt --type=merge -p \
    '{"spec":{"configuration":{"developerConfiguration":{"useEmulation":true}}}}'
  kubectl -n kubevirt wait kv/kubevirt --for=condition=Available --timeout=600s

  echo "--- whitelist cloud-tpus.google.com/v4 (externalResourceProvider)"
  kubectl -n kubevirt patch kubevirt kubevirt --type=merge -p '{
    "spec": {"configuration": {
      "developerConfiguration": {
        "useEmulation": true,
        "featureGates": ["GPU", "HostDevices"]},
      "permittedHostDevices": {"pciHostDevices": [{
        "pciVendorSelector": "1AE0:0062",
        "resourceName": "cloud-tpus.google.com/v4",
        "externalResourceProvider": true}]}}}}'
  # wait for virt-operator to observe the patched config (no bare sleep:
  # observedGeneration catching up to metadata.generation is the signal
  # that the new permittedHostDevices made it into the live config)
  for i in $(seq 1 30); do
    GEN=$(kubectl -n kubevirt get kubevirt kubevirt \
          -o jsonpath='{.metadata.generation}' 2>/dev/null || true)
    OBS=$(kubectl -n kubevirt get kubevirt kubevirt \
          -o jsonpath='{.status.observedGeneration}' 2>/dev/null || true)
    [ -z "$GEN" ] && { sleep 2; continue; }
    [ -n "$OBS" ] && [ "$OBS" = "$GEN" ] && break
    sleep 2
  done
  echo "kubevirt CR observedGeneration=$OBS (generation=$GEN)"

  echo "--- VMI -> virt-launcher admission ($(date -u +%FT%TZ))"
  kubectl apply -f "$REPO/manifests/e2e/vmi-tpu-e2e.yaml"
  # virt-controller may still be settling on the new config; one delete +
  # re-apply retry covers a VMI rendered before propagation finished
  LAUNCHER=""
  for round in 1 2; do
    for i in $(seq 1 45); do
      LAUNCHER=$(kubectl get pods \
        -l kubevirt.io=virt-launcher,vm.kubevirt.io/name=vmi-tpu \
        -o name 2>/dev/null | head -1)
      [ -n "$LAUNCHER" ] && break
      sleep 2
    done
    [ -n "$LAUNCHER" ] && break
    if [ "$round" = "1" ]; then
      echo "note: no virt-launcher after 90s; re-applying the VMI once"
      kubectl delete vmi vmi-tpu --ignore-not-found --wait=true
      kubectl apply -f "$REPO/manifests/e2e/vmi-tpu-e2e.yaml"
    fi
  done
  [ -n "$LAUNCHER" ] || { echo "FAIL: no virt-launcher pod for vmi-tpu"
    kubectl describe vmi vmi-tpu; exit 1; }

  # 1) pod SPEC carries the extended resource (KubeVirt honored the
  #    whitelist and delegated advertisement to this plugin)
  REQ=$(kubectl get "$LAUNCHER" -o \
    jsonpath='{.spec.containers[?(@.name=="compute")].resources.limits.cloud-tpus\.google\.com/v4}')
  [ "$REQ" = "1" ] || { echo "FAIL: compute requests v4='$REQ' (want 1)"
    kubectl get "$LAUNCHER" -o yaml | sed -n '1,80p'; exit 1; }
  echo "virt-launcher spec requests cloud-tpus.google.com/v4=1 OK"

  # 2) devicemanager ADMITTED it (scheduling + container creation = the
  #    kubelet called this plugin's Allocate and granted the device)
  kubectl wait --for=condition=PodScheduled "$LAUNCHER" --timeout=180s
  CREATED=""
  for i in $(seq 1 90); do
    CREATED=$(kubectl get "$LAUNCHER" -o \
      jsonpath='{.status.containerStatuses[?(@.name=="compute")].name}' \
      2>/dev/null || true)
    [ -n "$CREATED" ] && break
    sleep 2
  done
  [ -n "$CREATED" ] || { echo "FAIL: compute container never created"
    kubectl describe "$LAUNCHER"; exit 1; }
  echo "virt-launcher admitted; compute container created (device granted)"

  # 3) the env contract inside the compute container (virt-launcher reads
  #    PCI_RESOURCE_* to pick the PCI device for QEMU). HARD assert while
  #    the container is Running; the downgrade is allowed ONLY when the
  #    container demonstrably crashed pre-exec (expected without real VFIO
  #    ioctls) — a Running container with no env is a plugin bug, not an
  #    environment artifact.
  ENVV=""
  for i in $(seq 1 20); do
    ENVV=$(kubectl exec "$LAUNCHER" -c compute -- sh -c \
      'env | grep PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4' 2>/dev/null || true)
    [ -n "$ENVV" ] && break
    sleep 3
  done
  if [ -n "$ENVV" ]; then
    echo "virt-launcher env: $ENVV"
    echo "$ENVV" | grep -q "0000:" || { echo "FAIL: env has no BDF"; exit 1; }
    kubectl exec "$LAUNCHER" -c compute -- sh -c 'ls /dev/vfio' || true
  else
    STATE=$(kubectl get "$LAUNCHER" -o jsonpath='{.status.containerStatuses[?(@.name=="compute")].state}' 2>/dev/null || true)
    case "$STATE" in
      *running*)
        echo "FAIL: compute container is Running but PCI_RESOURCE env is" \
             "absent — the kubelet did not inject this plugin's Allocate env"
        kubectl get "$LAUNCHER" -o yaml | sed -n '1,100p'
        exit 1;;
      *)
        echo "note: exec unavailable and compute container not Running" \
             "(state: ${STATE:-unknown}) — guest crashed pre-exec, expected" \
             "without real VFIO; admission + spec contract asserted above";;
    esac
  fi
  echo "KUBEVIRT CONTRACT PASS: virt-launcher admitted with the TPU resource ($(date -u +%FT%TZ))"
fi
