"""Unit tests for the fault-injection registry (faults.py): arming
semantics (kind/count/probability), the disarmed fast path, env/spec
parsing, and the instrumented production sites' local behavior.
"""

import pytest

from tpu_device_plugin import faults
from tpu_device_plugin.faults import FaultInjected


@pytest.fixture(autouse=True)
def clean_registry():
    faults.reset()
    yield
    faults.reset()


def test_disarmed_fire_is_false_noop():
    assert faults.fire("anything") is False
    assert faults.stats() == {}


def test_error_kind_raises_and_count_exhausts():
    faults.arm("site.a", kind="error", count=2)
    with pytest.raises(FaultInjected):
        faults.fire("site.a")
    with pytest.raises(FaultInjected):
        faults.fire("site.a")
    assert faults.fire("site.a") is False       # budget exhausted, disarmed
    assert faults.stats() == {"site.a": 2}
    assert faults.armed_sites() == {}


def test_value_kind_returns_true_without_raising():
    faults.arm("site.b", kind="drop", count=1)
    assert faults.fire("site.b") is True
    assert faults.fire("site.b") is False


def test_timeout_and_oserror_kinds():
    faults.arm("t", kind="timeout")
    with pytest.raises(TimeoutError):
        faults.fire("t")
    faults.arm("o", kind="oserror")
    with pytest.raises(ConnectionResetError):
        faults.fire("o")


def test_custom_exception_factory():
    faults.arm("c", exc=lambda: ValueError("custom"))
    with pytest.raises(ValueError, match="custom"):
        faults.fire("c")


def test_probability_schedule_is_seeded():
    faults.seed(1234)
    faults.arm("p", kind="drop", count=None, probability=0.5)
    first = [faults.fire("p") for _ in range(100)]
    faults.reset()
    faults.seed(1234)
    faults.arm("p", kind="drop", count=None, probability=0.5)
    assert [faults.fire("p") for _ in range(100)] == first
    assert 20 < sum(first) < 80                  # actually probabilistic


def test_unlimited_count():
    faults.arm("u", kind="drop", count=None)
    assert all(faults.fire("u") for _ in range(10))


def test_injected_context_manager_disarms_on_exit():
    with faults.injected("cm", kind="drop", count=None):
        assert faults.fire("cm") is True
    assert faults.fire("cm") is False


def test_arm_rejects_unknown_kind_and_bad_count():
    with pytest.raises(ValueError):
        faults.arm("x", kind="nope")
    with pytest.raises(ValueError):
        faults.arm("x", count=0)


def test_configure_spec_grammar():
    faults.configure("kubelet.register:error:count=3,"
                     "native.probe:drop:p=0.25,inotify.poll")
    armed = faults.armed_sites()
    assert armed["kubelet.register"] == {"kind": "error", "remaining": 3,
                                         "probability": 1.0, "fires": 0,
                                         "delay_s": 0.0, "jitter_s": 0.0,
                                         "ramp_s": 0.0}
    assert armed["native.probe"]["probability"] == 0.25
    assert armed["native.probe"]["remaining"] is None
    # bare site: defaults to the site's natural kind, not blanket "error"
    assert armed["inotify.poll"]["kind"] == "drop"


def test_configure_spec_delay_jitter_ramp():
    faults.configure("kubeapi.request:delay:delay=0.2:jitter=0.05:ramp=30")
    armed = faults.armed_sites()["kubeapi.request"]
    assert armed["kind"] == "delay"
    assert armed["delay_s"] == 0.2
    assert armed["jitter_s"] == 0.05
    assert armed["ramp_s"] == 30.0


def test_delay_jitter_spreads_sleeps_uniformly(monkeypatch):
    """jitter=J: each sleep is drawn uniformly from [delay-J, delay+J]
    (seeded, so the schedule replays)."""
    sleeps = []
    monkeypatch.setattr("tpu_device_plugin.faults.time.sleep",
                        sleeps.append)
    faults.seed(7)
    faults.arm("j", kind="delay", count=None, delay_s=0.1, jitter_s=0.05)
    for _ in range(50):
        assert faults.fire("j") is False     # delay: call proceeds
    assert all(0.05 - 1e-9 <= s <= 0.15 + 1e-9 for s in sleeps)
    assert len(set(round(s, 6) for s in sleeps)) > 1   # actually jittered
    replay = list(sleeps)
    sleeps.clear()
    faults.reset()
    faults.seed(7)
    faults.arm("j", kind="delay", count=None, delay_s=0.1, jitter_s=0.05)
    for _ in range(50):
        faults.fire("j")
    assert sleeps == replay


def test_delay_ramp_scales_linearly_from_arm_time(monkeypatch):
    """ramp=R: the sleep grows linearly from 0 at arm time to full
    strength R seconds later (a soak's gradual degradation, not a step)."""
    sleeps = []
    monkeypatch.setattr("tpu_device_plugin.faults.time.sleep",
                        sleeps.append)
    clock = [1000.0]
    monkeypatch.setattr("tpu_device_plugin.faults.time.monotonic",
                        lambda: clock[0])
    faults.arm("r", kind="delay", count=None, delay_s=0.4, ramp_s=10.0)
    faults.fire("r")                         # t=0: no degradation yet
    clock[0] += 5.0
    faults.fire("r")                         # mid-ramp: half strength
    clock[0] += 5.0
    faults.fire("r")                         # ramp complete: full delay
    clock[0] += 100.0
    faults.fire("r")                         # stays at full strength
    assert sleeps == pytest.approx([0.0, 0.2, 0.4, 0.4])


def test_jitter_and_ramp_require_delay_kind():
    with pytest.raises(ValueError, match="kind='delay'"):
        faults.arm("x", kind="error", jitter_s=0.1)
    with pytest.raises(ValueError, match="kind='delay'"):
        faults.arm("x", kind="drop", ramp_s=1.0)
    with pytest.raises(ValueError):
        faults.arm("x", kind="delay", delay_s=0.1, jitter_s=-1.0)


def test_configure_rejects_unknown_option():
    with pytest.raises(ValueError):
        faults.configure("kubelet.register:error:bogus=1")


def test_configure_rejects_unknown_site():
    # a typo'd env spec must abort the run, not silently inject nothing
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.configure("kubelet.regster:error")


def test_arm_rejects_mismatched_kind_category():
    # raising kind on a value site would kill the daemon thread that
    # consults it (HealthMonitor, watcher loop) instead of simulating
    # the documented failure
    with pytest.raises(ValueError, match="honors only value"):
        faults.arm("native.probe", kind="error")
    with pytest.raises(ValueError, match="honors only value"):
        faults.arm("inotify.poll", exc=lambda: RuntimeError("boom"))
    # value kind on a raising site is ignored by the call site: the run
    # would count fires while injecting nothing
    with pytest.raises(ValueError, match="honors only raising"):
        faults.arm("kubeapi.request", kind="drop")
    assert faults.armed_sites() == {}


def test_configure_from_env(monkeypatch):
    monkeypatch.setenv("TDP_FAULTS", "dra.publish:drop:count=1")
    monkeypatch.setenv("TDP_FAULTS_SEED", "99")
    assert faults.configure_from_env() is True
    assert faults.fire("dra.publish") is True
    monkeypatch.delenv("TDP_FAULTS")
    faults.reset()
    assert faults.configure_from_env() is False


# ------------------------------------------- instrumented production sites


def test_kubeapi_request_site_fires_as_apierror():
    """An armed kubeapi.request fault surfaces as ApiError (the client's
    one exception contract) and feeds the breaker."""
    from tpu_device_plugin.kubeapi import ApiClient, ApiError
    c = ApiClient("http://example.invalid:1", token_path="/nonexistent")
    faults.arm("kubeapi.request", kind="timeout", count=1)
    with pytest.raises(ApiError):
        c.request("/x")
    assert c.breaker.snapshot()["consecutive_failures"] == 1


def test_inotify_poll_site_drops_events(short_root):
    """A fired inotify.poll fault swallows a real event batch."""
    import os

    from tpu_device_plugin.health import InotifyWatcher
    w = InotifyWatcher()
    try:
        w.watch_dir(short_root)
        faults.arm("inotify.poll", kind="drop", count=1)
        open(os.path.join(short_root, "f1"), "w").close()
        assert w.poll(1.0) == []                  # batch dropped
        open(os.path.join(short_root, "f2"), "w").close()
        events = w.poll(1.0)                      # next batch delivered
        assert any(name == "f2" for _, name, _ in events)
    finally:
        w.close()
