"""Device + socket health monitoring.

Two mechanisms, mirroring the reference's split (SURVEY.md §5):

1. Filesystem watch (the reference's fsnotify, generic_device_plugin.go:611-690):
   an inotify watcher (ctypes over libc — fsnotify is itself just an inotify
   wrapper) on the socket dir and on `/dev/vfio/`. Group node Remove/Rename →
   every device in the group goes Unhealthy; Create → Healthy; removal of the
   plugin's own socket means the kubelet restarted and wiped its socket dir →
   the plugin must re-register.

2. Native liveness probe (the reference's NVML XID watch,
   generic_vgpu_device_plugin.go:387-433): every `health_poll_s` (5 s, the
   NVML WaitForEvent cadence) the libtpuhealth shim reads each chip's PCI
   config space — a vfio-bound chip has no host driver to ask, but config
   reads still work and a dead/fallen-off chip returns all-FF. See
   `tpu_device_plugin.native`.

Production no longer runs one `HealthMonitor` per plugin server: the
shared host-level hub (`tpu_device_plugin.healthhub.HealthHub`) owns the
one inotify fd, the one existence reconciler, and the deduped
deadline-bounded probe scheduler, and plugin servers subscribe to it.
`InotifyWatcher` is the hub's watcher; `HealthMonitor` remains as the
standalone single-consumer form (tests, embedding).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
import select
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import faults

log = logging.getLogger(__name__)

IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_MOVED_FROM = 0x00000040
IN_MOVED_TO = 0x00000080
IN_DELETE_SELF = 0x00000400
IN_ATTRIB = 0x00000004

_GONE = IN_DELETE | IN_MOVED_FROM
_BACK = IN_CREATE | IN_MOVED_TO

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


class InotifyWatcher:
    """Minimal inotify directory watcher: poll() yields (dir, name, mask)."""

    def __init__(self) -> None:
        libc_name = ctypes.util.find_library("c") or "libc.so.6"
        self._libc = ctypes.CDLL(libc_name, use_errno=True)
        self._fd = self._libc.inotify_init1(os.O_NONBLOCK)
        if self._fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._wd_to_dir: Dict[int, str] = {}
        # bytes of a partial trailing event carried across reads: a 64 KiB
        # read boundary can split an event (header or name truncated) and
        # the parser must not discard the remainder
        self._pending = b""

    def watch_dir(self, path: str) -> None:
        mask = IN_CREATE | IN_DELETE | IN_MOVED_FROM | IN_MOVED_TO
        wd = self._libc.inotify_add_watch(self._fd, path.encode(), mask)
        if wd < 0:
            raise OSError(ctypes.get_errno(), f"inotify_add_watch({path}) failed")
        self._wd_to_dir[wd] = path

    def poll(self, timeout_s: float) -> List[Tuple[str, str, int]]:
        ready, _, _ = select.select([self._fd], [], [], timeout_s)
        if not ready:
            return []
        # fault point "inotify.poll" (value kind): drop this batch of
        # events unread-from-the-caller's-view, simulating lost inotify
        # delivery — the periodic existence scan must reconcile
        if faults.fire("inotify.poll"):
            try:
                os.read(self._fd, 65536)   # consume so the fd doesn't spin
            except BlockingIOError:
                pass
            self._pending = b""  # the dropped batch takes its remainder along
            return []
        try:
            buf = self._pending + os.read(self._fd, 65536)
        except BlockingIOError:
            buf = self._pending
        self._pending = b""
        events: List[Tuple[str, str, int]] = []
        off = 0
        while off + _EVENT_HDR.size <= len(buf):
            wd, mask, _cookie, name_len = _EVENT_HDR.unpack_from(buf, off)
            if off + _EVENT_HDR.size + name_len > len(buf):
                break  # partial trailing event: name bytes still to come
            off += _EVENT_HDR.size
            name = buf[off:off + name_len].split(b"\0", 1)[0].decode(errors="replace")
            off += name_len
            directory = self._wd_to_dir.get(wd, "")
            events.append((directory, name, mask))
        # carry any incomplete remainder (truncated header OR name) into the
        # next read instead of discarding it
        self._pending = buf[off:]
        return events

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


class HealthMonitor(threading.Thread):
    """Watches group nodes + the plugin socket; drives health callbacks.

    Callbacks (all thread-safe on the caller's side):
      on_device_health(group, healthy, source)
                                        — source "fs" (node came/went) or
                                          "probe" (native liveness verdict)
      on_socket_removed()               — kubelet restarted; plugin must restart
      probe(bdf, node_path) -> bool     — native liveness (node_path is the
                                          group's watched node, or None);
                                          False marks the chip's group
                                          Unhealthy
    """

    def __init__(
        self,
        socket_path: str,
        group_paths: Dict[str, str],        # watch key -> device node path
                                            # (iommu group -> /dev/vfio/<grp>,
                                            #  partition uuid -> accel/mdev)
        group_bdfs: Dict[str, List[str]],   # watch key -> member BDFs
        on_device_health: Callable[[str, bool, str], None],
        on_socket_removed: Callable[[], None],
        probe: Optional[Callable[[str, Optional[str]], bool]] = None,
        poll_interval_s: float = 5.0,
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        super().__init__(daemon=True, name=f"health-{os.path.basename(socket_path)}")
        self._socket_path = socket_path
        self._group_paths = dict(group_paths)
        self._group_bdfs = {g: list(b) for g, b in group_bdfs.items()}
        self._on_device_health = on_device_health
        self._on_socket_removed = on_socket_removed
        self._probe = probe
        self._poll_interval_s = poll_interval_s
        self.stop_event = stop_event or threading.Event()
        self._probe_state: Dict[str, bool] = {}
        self._watcher: Optional[InotifyWatcher] = None
        # probe callbacks that raised: a raising probe scores its group
        # Unhealthy instead of killing the monitor thread (see _run_probes).
        # NOTE: in production the hub's counter feeds tdp_probe_errors_total
        # (healthhub.stats probe_errors_total → status.py); this one is for
        # embedders of the standalone monitor to export themselves.
        self.probe_errors = 0

    def start(self) -> None:
        """Register inotify watches *before* the thread runs, so an event
        arriving immediately after start() (e.g. the kubelet wiping its socket
        dir during registration) cannot be lost to setup latency. If inotify
        is unavailable (fd/watch limits exhausted), the monitor degrades to
        existence polling rather than running blind."""
        watcher = None
        try:
            watcher = InotifyWatcher()
            watcher.watch_dir(os.path.dirname(self._socket_path) or ".")
            vfio_dirs = {os.path.dirname(p) for p in self._group_paths.values()}
            for d in vfio_dirs:
                if os.path.isdir(d):
                    watcher.watch_dir(d)
            self._watcher = watcher
        except OSError as exc:
            if watcher is not None:
                watcher.close()
            log.error("health monitor: inotify unavailable (%s); "
                      "falling back to existence polling", exc)
            self._watcher = None
        super().start()

    def _socket_gone(self) -> bool:
        """Handle disappearance of the plugin's own socket; True = terminate."""
        if self.stop_event.is_set():
            # intentional teardown: grpc unlinks the unix socket during
            # server.stop(); not a kubelet restart
            return True
        log.info("plugin socket %s removed — kubelet restart", self._socket_path)
        self._on_socket_removed()
        return True  # restart tears this monitor down

    def _scan_existing(self, fs_state: Dict[str, bool]) -> None:
        """Reconcile against current node existence. inotify only reports
        *future* events, so a group node already missing at monitor start
        (e.g. removed during a restart window) must be flagged here; also the
        whole event source in polling-fallback mode."""
        for group, path in self._group_paths.items():
            exists = os.path.exists(path)
            if fs_state.get(group) != exists:
                fs_state[group] = exists
                if not exists:
                    log.warning("device node %s missing", path)
                self._on_device_health(group, exists, "fs")

    def run(self) -> None:
        watcher = self._watcher
        # several keys may share one node path (logical partitions of a chip
        # all ride /dev/accelN) — basename maps to ALL of them
        groups_by_node: Dict[str, List[str]] = {}
        for g, p in self._group_paths.items():
            groups_by_node.setdefault(os.path.basename(p), []).append(g)
        socket_name = os.path.basename(self._socket_path)
        fs_state: Dict[str, bool] = {g: True for g in self._group_paths}
        self._scan_existing(fs_state)
        # The socket is bound (by grpc) before this monitor starts watching;
        # an unlink in that window leaves no future inotify event, so check
        # current existence once.
        if not os.path.exists(self._socket_path):
            if self._socket_gone():
                return
        last_probe = 0.0
        last_scan = 0.0
        try:
            while not self.stop_event.is_set():
                if watcher is not None:
                    for directory, name, mask in watcher.poll(0.2):
                        if name == socket_name and \
                                directory == os.path.dirname(self._socket_path):
                            if mask & _GONE and self._socket_gone():
                                return
                            continue
                        for group in groups_by_node.get(name, ()):
                            if mask & _GONE:
                                log.warning("device node %s removed", name)
                                fs_state[group] = False
                                self._on_device_health(group, False, "fs")
                            elif mask & _BACK:
                                log.info("device node %s (re)created", name)
                                fs_state[group] = True
                                self._on_device_health(group, True, "fs")
                else:
                    # polling fallback: existence is the event source
                    self.stop_event.wait(0.2)
                    if not os.path.exists(self._socket_path):
                        if self._socket_gone():
                            return
                    self._scan_existing(fs_state)
                now = time.monotonic()
                if watcher is not None and now - last_scan >= self._poll_interval_s:
                    # periodic reconciliation even with inotify: sysfs (kernfs)
                    # emits no inotify events at all (mdev paths), and dirs
                    # missing at start (udev still populating /dev/vfio) get
                    # no watch — existence scanning is the ground truth
                    last_scan = now
                    self._scan_existing(fs_state)
                if self._probe is not None and now - last_probe >= self._poll_interval_s:
                    last_probe = now
                    self._run_probes()
        finally:
            if watcher is not None:
                watcher.close()

    def _run_probes(self) -> None:
        for group, bdfs in self._group_bdfs.items():
            node = self._group_paths.get(group)
            try:
                healthy = all(self._probe(bdf, node) for bdf in bdfs)
            except Exception as exc:
                # a raising probe used to propagate out of run() and
                # silently kill the monitor thread — score the group
                # Unhealthy and keep monitoring
                self.probe_errors += 1
                log.error("liveness probe for group %s raised (%s); "
                          "scoring Unhealthy", group, exc)
                healthy = False
            if self._probe_state.get(group) != healthy:
                self._probe_state[group] = healthy
                if not healthy:
                    log.warning("liveness probe failed for group %s (%s)",
                                group, ",".join(bdfs))
                self._on_device_health(group, healthy, "probe")
