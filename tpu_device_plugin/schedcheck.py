"""schedcheck — yield-point hooks for deterministic interleaving checking.

The lock-free planes (epoch publish, trace shards, the seqlock response
ring, CAS placement commit, the LiveAttrReader fast path) synchronize
through C-atomic operations the interpreter guarantees, not through
locks — so lockdep and tsalint cannot see their schedule points. This
module marks them explicitly: production code calls

    schedcheck.yield_point("epoch.publish.store", obj=self, mode="w")

immediately before a C-atomic read or write that a concurrent protocol
depends on. Disabled (always, in production), a yield point is one
module-global bool check and a return — the zero-lock read-path gates
and the r10 trace-overhead bench both run with the hooks in place and
pin their budgets, which is the proof the no-op stays a no-op. Enabled
(only inside tools/weave's cooperative scheduler), each yield point
becomes a schedule point: the checker parks the calling thread there
and enumerates every interleaving of the marked accesses.

`obj` identifies the shared location (two yield points race only if
they name the same location and at least one is a write); `mode` is
"r" or "w" from the caller's perspective. When the shared location is
not one Python object — the response ring's writer and reader are two
objects mapping the same memory — pass an explicit string `key`
instead; equal keys are the same location. A yield point with neither
keys on its label alone — use only for points that race with every
peer sharing the label.
"""

from __future__ import annotations

from typing import Callable, Optional

__all__ = ["active", "install", "uninstall", "yield_point"]

Hook = Callable[[str, Optional[object], str, Optional[str]], None]

_ACTIVE = False
_HOOK: Optional[Hook] = None


def yield_point(label: str, obj: Optional[object] = None,
                mode: str = "w", key: Optional[str] = None) -> None:
    """Mark one C-atomic access as a schedule point (no-op unless a
    checker installed a hook)."""
    if not _ACTIVE:
        return
    hook = _HOOK
    if hook is not None:
        hook(label, obj, mode, key)


def install(hook: Hook) -> None:
    """Route every yield point through `hook` (the weave scheduler)."""
    global _ACTIVE, _HOOK
    _HOOK = hook
    _ACTIVE = True


def uninstall() -> None:
    global _ACTIVE, _HOOK
    _ACTIVE = False
    _HOOK = None


def active() -> bool:
    return _ACTIVE
