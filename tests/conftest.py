"""Test env: force JAX onto a virtual 8-device CPU mesh before any jax import."""

import os
import shutil
import sys
import tempfile

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments force-register an out-of-process TPU PJRT plugin from
# sitecustomize, overriding JAX_PLATFORMS; initializing it would contend for
# the (single) real chip from every test process. Pin the config to CPU
# before any backend initialization.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def short_root():
    """A short tmpdir for fixtures that bind unix sockets: pytest's tmp_path
    can push socket paths past the kernel's 107-char sun_path limit."""
    root = tempfile.mkdtemp(prefix="tdp-")
    yield root
    shutil.rmtree(root, ignore_errors=True)
