"""lockdep — runtime lock-order and lock-hold validation ($TDP_LOCKDEP=1).

The static analyzer (tools/tsalint) proves what it can see; callbacks,
injected policies and cross-object delivery chains it cannot. This module
closes that gap the way the kernel's lockdep does: every registered lock
is wrapped in a recording proxy, each thread keeps its acquisition stack,
and every FIRST observation of "B acquired while A held" adds the edge
A -> B to a global order graph with an exemplar stack. At the end of a
run (tests/conftest.py wires this into the tier-1 suite), the graph is
checked:

- **inversions**: both A -> B and B -> A observed anywhere in the run —
  two threads interleaving those paths can deadlock, even if this run got
  lucky. Includes same-name self-edges (two INSTANCES of the same lock
  class nested — an ABBA hazard between peers).
- **cycles**: longer loops (A -> B -> C -> A) via DFS over the edge graph.
- **long holds**: a watched lock (the hot set: device-table condition,
  DRA global lock, checkpoint condition, hub lock) held longer than
  $TDP_LOCKDEP_HOLD_MS (default 500) — the runtime symptom of blocking
  work under a hot lock. Condition.wait/wait_for pause the hold clock
  (and the order stack): a waiter is not a holder.

Everything is keyed by the REGISTERED NAME ("module.Class.attr"), shared
across instances — the same names tsalint reports, so a static finding
and a runtime report point at the same lock.

Production cost: `instrument()` returns the raw lock unchanged unless
lockdep was enabled BEFORE the lock was created (module-level locks are
created at import, so enable() must run first — conftest does). The
enabled fast path is one thread-local peek plus a set lookup per acquire;
stacks are captured only the first time an edge is seen.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from contextlib import contextmanager
from typing import (Any, Callable, ContextManager, Dict, Iterator, List,
                    Optional, Set, Tuple, TypeVar, cast)

__all__ = ["enable", "disable", "enabled", "instrument", "path_stats",
           "read_path", "report", "reset", "scoped", "watch",
           "LockdepReport"]

_LockT = TypeVar("_LockT")

_enabled = False
_registry_lock = threading.Lock()
_registered: Set[str] = set()               # names seen by instrument()
# (holder name, acquired name) -> exemplar stack text
_edges: Dict[Tuple[str, str], str] = {}
_long_holds: List[Tuple[str, float, str]] = []   # (name, seconds, stack)
_watched: Set[str] = set()
_hold_threshold_s = 0.5
# hot-read-path accounting: path name -> [entries, lock acquisitions].
# Production code brackets its lock-free read paths with read_path(name);
# the per-path acquisition counter is the CI gate proving they acquire
# ZERO registered locks in steady state (tests/test_epoch.py).
_paths: Dict[str, List[int]] = {}

_DEFAULT_WATCHED = (
    "epoch.EpochStore._cond",
    "dra.DraDriver._lock",
    "dra.DraDriver._ckpt_cond",
    "healthhub.HealthHub._lock",
)


class _HoldRec:
    __slots__ = ("name", "key", "t0", "count")

    def __init__(self, name: str, key: int, t0: float) -> None:
        self.name = name
        self.key = key       # id() of the proxy instance
        self.t0 = t0         # monotonic acquire time; 0.0 = unwatched
        self.count = 1       # reentrant depth (RLock)


class _TLS(threading.local):
    def __init__(self) -> None:
        self.stack: List[_HoldRec] = []
        # the innermost read_path record this thread is inside, or None
        self.path: Optional[List[int]] = None


_tls = _TLS()


def enable(hold_threshold_ms: Optional[float] = None) -> None:
    """Turn recording on (idempotent). Reads $TDP_LOCKDEP_HOLD_MS unless
    an explicit threshold is given. Locks created BEFORE enable() stay
    raw — enable first, import/construct after."""
    global _enabled, _hold_threshold_s
    if hold_threshold_ms is None:
        try:
            hold_threshold_ms = float(
                os.environ.get("TDP_LOCKDEP_HOLD_MS", "") or 500.0)
        except ValueError:
            hold_threshold_ms = 500.0
    _hold_threshold_s = max(hold_threshold_ms, 0.0) / 1000.0
    with _registry_lock:
        if not _watched:
            _watched.update(_DEFAULT_WATCHED)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def watch(name: str) -> None:
    """Add a lock name to the long-hold watch set."""
    with _registry_lock:
        _watched.add(name)


def reset() -> None:
    """Clear recorded edges/holds/path counters (test isolation);
    registration stays."""
    with _registry_lock:
        _edges.clear()
        del _long_holds[:]
        for rec in _paths.values():
            rec[0] = rec[1] = 0


@contextmanager
def scoped(hold_threshold_ms: Optional[float] = None,
           watched: Optional[Set[str]] = None) -> Iterator[None]:
    """Enable lockdep for a with-block with ISOLATED recording state —
    unit tests exercise intentional inversions/holds without polluting
    (or failing) a surrounding TDP_LOCKDEP=1 session's final report.
    Prior edges/holds, threshold, watch set and enablement are restored
    on exit."""
    global _enabled, _hold_threshold_s
    with _registry_lock:
        saved_edges = dict(_edges)
        saved_holds = list(_long_holds)
        saved_watched = set(_watched)
        saved_paths = {name: list(rec) for name, rec in _paths.items()}
        _edges.clear()
        del _long_holds[:]
        _paths.clear()
        if watched is not None:
            _watched.clear()
            _watched.update(watched)
    saved_enabled = _enabled
    saved_threshold = _hold_threshold_s
    enable(hold_threshold_ms)
    try:
        yield
    finally:
        with _registry_lock:
            _edges.clear()
            _edges.update(saved_edges)
            del _long_holds[:]
            _long_holds.extend(saved_holds)
            _watched.clear()
            _watched.update(saved_watched)
            _paths.clear()
            _paths.update(saved_paths)
        _enabled = saved_enabled
        _hold_threshold_s = saved_threshold


def instrument(name: str, lock: _LockT) -> _LockT:
    """Register `lock` under `name`. Disabled (production): returns the
    raw lock — zero overhead. Enabled: returns a recording proxy (typed
    as the wrapped lock: the proxy is API-compatible)."""
    with _registry_lock:
        _registered.add(name)
    if not _enabled:
        return lock
    if isinstance(lock, threading.Condition):
        return cast(_LockT, _ConditionProxy(name, lock))
    return cast(_LockT, _LockProxy(name, lock))


# ---------------------------------------------------------- read paths

class _PathCtx:
    """Active read_path bracket: counts entries and attributes every
    registered-lock acquisition made on this thread to the path."""

    __slots__ = ("_rec", "_prev")

    def __init__(self, rec: List[int]) -> None:
        self._rec = rec
        self._prev: Optional[List[int]] = None

    def __enter__(self) -> List[int]:
        self._rec[0] += 1
        self._prev = _tls.path
        _tls.path = self._rec
        return self._rec

    def __exit__(self, *exc: object) -> None:
        _tls.path = self._prev


class _NullCtx:
    """Reusable no-op bracket: the production cost of read_path when
    lockdep is disabled is one call + two no-op dunders."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: object) -> None:
        return None


_NULL_CTX = _NullCtx()


def read_path(name: str) -> "ContextManager[Optional[List[int]]]":
    """Bracket one hot read path (`with lockdep.read_path("server.Allocate")`).

    Disabled (production): a cached no-op context. Enabled: every
    registered-lock acquisition inside the bracket (on this thread) is
    charged to `name` — `path_stats()` exposes the totals, and the
    read-path gate asserts they stay 0 (tests/test_epoch.py)."""
    if not _enabled:
        return _NULL_CTX
    rec = _paths.get(name)
    if rec is None:
        with _registry_lock:
            rec = _paths.setdefault(name, [0, 0])
    return _PathCtx(rec)


def path_stats() -> Dict[str, Dict[str, int]]:
    """{path: {"calls": n, "lock_acquisitions": n}} for every bracket
    entered since enable()/reset()."""
    with _registry_lock:
        return {name: {"calls": rec[0], "lock_acquisitions": rec[1]}
                for name, rec in _paths.items()}


# --------------------------------------------------------------- recording

def _note_acquired(name: str, key: int) -> None:
    rec = _tls.path
    if rec is not None:
        rec[1] += 1
    stack = _tls.stack
    for rec in stack:
        if rec.key == key:          # reentrant re-acquire (RLock)
            rec.count += 1
            return
    for rec in stack:
        _note_edge(rec.name, name)
    t0 = time.monotonic() if name in _watched else 0.0
    stack.append(_HoldRec(name, key, t0))


def _note_edge(holder: str, acquired: str) -> None:
    pair = (holder, acquired)
    if pair in _edges:              # racy peek: worst case one extra lock
        return
    stack_text = "".join(traceback.format_stack(limit=14)[:-2])
    with _registry_lock:
        _edges.setdefault(pair, stack_text)


def _note_released(name: str, key: int) -> None:
    stack = _tls.stack
    for i in range(len(stack) - 1, -1, -1):
        rec = stack[i]
        if rec.key != key:
            continue
        rec.count -= 1
        if rec.count > 0:
            return
        del stack[i]
        if rec.t0:
            held_s = time.monotonic() - rec.t0
            if held_s >= _hold_threshold_s:
                text = "".join(traceback.format_stack(limit=14)[:-2])
                with _registry_lock:
                    _long_holds.append((name, held_s, text))
        return


def _suspend(key: int) -> Optional[_HoldRec]:
    """Pop this lock's hold record for the duration of a Condition wait:
    a waiter holds nothing."""
    stack = _tls.stack
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].key == key:
            return stack.pop(i)
    return None


def _resume(rec: Optional[_HoldRec]) -> None:
    if rec is None:
        return
    if rec.name in _watched:
        rec.t0 = time.monotonic()   # the hold clock restarts post-wait
    _tls.stack.append(rec)


class _LockProxy:
    """Recording wrapper for Lock/RLock."""

    def __init__(self, name: str, lock: Any) -> None:
        self._name = name
        self._lock = lock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = bool(self._lock.acquire(blocking, timeout))
        if ok:
            _note_acquired(self._name, id(self))
        return ok

    def release(self) -> None:
        _note_released(self._name, id(self))
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def locked(self) -> bool:
        return bool(self._lock.locked())

    def __repr__(self) -> str:
        return f"<lockdep {self._name} wrapping {self._lock!r}>"


class _ConditionProxy:
    """Recording wrapper for Condition: wait/wait_for release the lock, so
    the hold record (and order stack membership) is suspended around them."""

    def __init__(self, name: str, cond: threading.Condition) -> None:
        self._name = name
        self._cond = cond

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        ok = bool(self._cond.acquire(*args, **kwargs))
        if ok:
            _note_acquired(self._name, id(self))
        return ok

    def release(self) -> None:
        _note_released(self._name, id(self))
        self._cond.release()

    def __enter__(self) -> bool:
        self._cond.__enter__()
        _note_acquired(self._name, id(self))
        return True

    def __exit__(self, *exc: object) -> None:
        _note_released(self._name, id(self))
        self._cond.__exit__(None, None, None)

    def wait(self, timeout: Optional[float] = None) -> bool:
        rec = _suspend(id(self))
        try:
            return self._cond.wait(timeout)
        finally:
            _resume(rec)

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        rec = _suspend(id(self))
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _resume(rec)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<lockdep {self._name} wrapping {self._cond!r}>"


# ----------------------------------------------------------------- report

class LockdepReport:
    """What the run observed. `violations()` is the CI gate."""

    def __init__(self, registered: Set[str],
                 edges: Dict[Tuple[str, str], str],
                 inversions: List[Tuple[str, str]],
                 cycles: List[List[str]],
                 long_holds: List[Tuple[str, float, str]]) -> None:
        self.registered = registered
        self.edges = edges
        self.inversions = inversions
        self.cycles = cycles
        self.long_holds = long_holds

    def violations(self) -> List[str]:
        out: List[str] = []
        for a, b in self.inversions:
            out.append(f"lock-order inversion: {a} <-> {b}")
        for cycle in self.cycles:
            if len(cycle) > 2:   # 2-cycles already reported as inversions
                out.append("lock-order cycle: " +
                           " -> ".join(cycle + [cycle[0]]))
        for name, held_s, _stack in self.long_holds:
            out.append(f"long hold: {name} held {held_s * 1e3:.0f} ms "
                       f"(threshold {_hold_threshold_s * 1e3:.0f} ms)")
        return out

    def render(self, stacks: bool = False) -> str:
        lines = [f"lockdep: {len(self.registered)} registered lock name(s), "
                 f"{len(self.edges)} order edge(s), "
                 f"{len(self.inversions)} inversion(s), "
                 f"{len(self.long_holds)} long hold(s)"]
        for a, b in self.inversions:
            lines.append(f"  INVERSION {a} <-> {b}")
            if stacks:
                lines.append("   first saw " + repr((a, b)) + " at:\n" +
                             _indent(self.edges.get((a, b), "")))
                lines.append("   first saw " + repr((b, a)) + " at:\n" +
                             _indent(self.edges.get((b, a), "")))
        for cycle in self.cycles:
            if len(cycle) > 2:
                lines.append("  CYCLE " + " -> ".join(cycle + [cycle[0]]))
                if stacks:
                    for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                        lines.append(f"   first saw {(a, b)!r} at:\n" +
                                     _indent(self.edges.get((a, b), "")))
        for name, held_s, stack in self.long_holds:
            lines.append(f"  LONG HOLD {name}: {held_s * 1e3:.0f} ms")
            if stacks:
                lines.append(_indent(stack))
        return "\n".join(lines)


def _indent(text: str) -> str:
    return "\n".join("    " + ln for ln in text.splitlines())


def report() -> LockdepReport:
    with _registry_lock:
        edges = dict(_edges)
        registered = set(_registered)
        long_holds = list(_long_holds)
    inversions = sorted({(min(a, b), max(a, b))
                         for (a, b) in edges
                         if a == b or (b, a) in edges})
    return LockdepReport(registered, edges, inversions,
                         _find_cycles(edges), long_holds)


def _tarjan_sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan: SCCs of >1 node, plus self-looping singletons."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    order: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(graph) | {b for bs in graph.values() for b in bs})
    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, List[str]]] = [(root, sorted(graph.get(root,
                                                                     ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        order.append(root)
        on_stack.add(root)
        while work:
            v, children = work[-1]
            if children:
                w = children.pop(0)
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    order.append(w)
                    on_stack.add(w)
                    work.append((w, sorted(graph.get(w, ()))))
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            else:
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
                if low[v] == index[v]:
                    scc: List[str] = []
                    while True:
                        w = order.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    if len(scc) > 1 or v in graph.get(v, ()):
                        sccs.append(sorted(scc))
    return sccs


def _bfs_path(graph: Dict[str, Set[str]], members: Set[str],
              start: str, goal: str) -> Optional[List[str]]:
    """Shortest start→goal path inside `members`, or None."""
    frontier = [start]
    parents: Dict[str, Optional[str]] = {start: None}
    while frontier:
        nxt: List[str] = []
        for v in frontier:
            for w in sorted(graph.get(v, ())):
                if w == goal:
                    path = [goal, v]
                    node: Optional[str] = v
                    while node is not None and parents[node] is not None:
                        node = parents[node]
                        if node is not None:
                            path.append(node)
                    path.reverse()
                    return path
                if w in members and w not in parents:
                    parents[w] = v
                    nxt.append(w)
        frontier = nxt
    return None


def find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """One representative cycle per SCC of the directed graph, with nodes
    in ACTUAL EDGE ORDER: cycle[i] -> cycle[i+1] (and last -> first) are
    all real edges, so a rendered arc can be traced through the exemplar
    stacks instead of naming edges nobody ever took. Self-loops come out
    as single-node cycles. Shared by the static analyzer (tools/tsalint)
    and the runtime report below — one implementation for both halves."""
    cycles: List[List[str]] = []
    for scc in _tarjan_sccs(graph):
        members = set(scc)
        start = min(scc)
        if len(scc) == 1:
            cycles.append([start])
            continue
        best: Optional[List[str]] = None
        for succ in sorted(set(graph.get(start, ())) & members):
            path = _bfs_path(graph, members, succ, start)
            if path is not None and (best is None or len(path) < len(best)):
                best = path
        # strongly connected ⇒ best is never None; guard anyway
        cycles.append([start] + (best[:-1] if best else []))
    return cycles


def _find_cycles(edges: Dict[Tuple[str, str], str]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    return find_cycles(graph)
