"""ctypes binding for libtpuhealth.so, with a pure-Python fallback.

Role-equivalent of the reference's vendored NVML cgo binding (SURVEY.md §2
#14): the native shim is loaded dynamically at runtime; when the .so is not
present (unit tests, cross-builds) a Python implementation of the same
probes keeps the plugin functional — health checks are I/O-bound, the native
path exists for deployments that must not run probe I/O under the GIL.
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import Optional, Tuple

log = logging.getLogger(__name__)

OK = 0
DEAD = 1
MISSING = 2
ERR = -1

# PCI status-register error bits (config offset 0x06) — the passthrough
# analogue of NVML XID events: master data parity error (8), signaled
# target abort (11), received target/master abort (12/13), signaled system
# error (14), detected parity error (15).
PCI_STATUS_ERROR_MASK = 0xF900


def link_is_degraded(link: Optional[dict]) -> bool:
    """THE degraded-link predicate (single source for probe/status/metrics):
    trained speed or width below the device maximum. None (unreadable
    capability) is not degraded — no signal, no alarm."""
    if link is None:
        return False
    return (link["cur_speed"] < link["max_speed"]
            or link["cur_width"] < link["max_width"])

_SEARCH_PATHS = (
    os.path.join(os.path.dirname(__file__), "libtpuhealth.so"),
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "libtpuhealth.so"),
    "libtpuhealth.so",
)


class TpuHealth:
    """Probe API; backed by libtpuhealth.so when loadable, else Python."""

    def __init__(self, lib_path: Optional[str] = None):
        self._lib = None
        self._has_pci_status = False
        self._has_pcie_link = False
        self._has_chip_diag = False
        self._err_logged: dict = {}   # bdf -> last-logged error bits
        self._link_logged: dict = {}  # bdf -> last-logged degraded tuple
        candidates = (lib_path,) if lib_path else _SEARCH_PATHS
        for cand in candidates:
            if cand is None:
                continue
            try:
                lib = ctypes.CDLL(cand)
                if lib.tpuhealth_abi_version() not in (1, 2, 3, 4):
                    log.warning("libtpuhealth %s has unknown ABI; ignoring", cand)
                    continue
                for fn in ("tpuhealth_probe_config", "tpuhealth_probe_node",
                           "tpuhealth_libtpu_available"):
                    getattr(lib, fn).restype = ctypes.c_int
                    if fn != "tpuhealth_libtpu_available":
                        getattr(lib, fn).argtypes = [ctypes.c_char_p]
                # v2/v3 symbols; older shims use the Python readers instead
                try:
                    lib.tpuhealth_pci_status.restype = ctypes.c_int
                    lib.tpuhealth_pci_status.argtypes = [ctypes.c_char_p]
                    self._has_pci_status = True
                except AttributeError:
                    self._has_pci_status = False
                try:
                    lib.tpuhealth_pcie_link.restype = ctypes.c_int
                    lib.tpuhealth_pcie_link.argtypes = [
                        ctypes.c_char_p] + [ctypes.POINTER(ctypes.c_int)] * 4
                    self._has_pcie_link = True
                except AttributeError:
                    self._has_pcie_link = False
                try:
                    lib.tpuhealth_chip_diag.restype = ctypes.c_int
                    lib.tpuhealth_chip_diag.argtypes = [
                        ctypes.c_char_p] + [ctypes.POINTER(ctypes.c_int)] * 5
                    self._has_chip_diag = True
                except AttributeError:
                    self._has_chip_diag = False
                self._lib = lib
                log.info("loaded native libtpuhealth from %s", cand)
                break
            except (OSError, AttributeError):
                # unloadable path, or a foreign .so without our symbols —
                # degrade to the Python fallback rather than crash startup
                continue
        if self._lib is None:
            log.info("libtpuhealth.so not found; using Python probe fallback")

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def probe_config(self, config_path: str) -> int:
        """PCI config-space liveness: 0xFFFF/unreadable vendor id == dead."""
        if self._lib is not None:
            return self._lib.tpuhealth_probe_config(config_path.encode())
        try:
            with open(config_path, "rb") as f:
                data = f.read(2)
        except FileNotFoundError:
            return MISSING
        except OSError:
            return ERR
        if len(data) != 2:
            return DEAD
        vendor = data[0] | (data[1] << 8)
        return DEAD if vendor in (0xFFFF, 0x0000) else OK

    def probe_node(self, dev_path: str) -> int:
        if self._lib is not None:
            return self._lib.tpuhealth_probe_node(dev_path.encode())
        if not os.path.exists(dev_path):
            return MISSING
        return OK

    def libtpu_available(self) -> bool:
        if self._lib is not None:
            return bool(self._lib.tpuhealth_libtpu_available())
        return False

    def pci_status(self, config_path: str) -> Optional[int]:
        """Raw PCI status register (config offset 6), or None if unreadable."""
        if self._lib is not None and self._has_pci_status:
            value = self._lib.tpuhealth_pci_status(config_path.encode())
            return None if value < 0 else value
        try:
            with open(config_path, "rb") as f:
                f.seek(6)
                data = f.read(2)
        except OSError:
            return None
        if len(data) != 2:
            return None
        return data[0] | (data[1] << 8)

    def pcie_link(self, config_path: str) -> Optional[dict]:
        """PCIe link state: {cur_speed, cur_width, max_speed, max_width}
        (speeds are PCIe generation codes, widths lane counts), or None when
        the capability is unreachable (device gone, short non-root sysfs
        read, fixture trees with no config/capability list)."""
        if self._lib is not None and self._has_pcie_link:
            outs = [ctypes.c_int() for _ in range(4)]
            rc = self._lib.tpuhealth_pcie_link(
                config_path.encode(), *[ctypes.byref(o) for o in outs])
            if rc != OK:
                return None
            cs, cw, ms_, mw = (o.value for o in outs)
            return {"cur_speed": cs, "cur_width": cw,
                    "max_speed": ms_, "max_width": mw}
        try:
            with open(config_path, "rb") as f:
                cfg = f.read(256)
        except OSError:
            return None
        return self._parse_link_cfg(cfg)

    @staticmethod
    def _parse_link_cfg(cfg: bytes) -> Optional[dict]:
        """Walk the capability list in raw config bytes for the PCIe link
        registers (shared by pcie_link and the chip_diagnostics fallback)."""
        if len(cfg) < 64 or cfg[0:2] == b"\xff\xff":
            return None
        if not cfg[0x06] & 0x10:   # no capability list
            return None
        off = cfg[0x34] & 0xFC
        for _ in range(48):
            if off < 0x40 or off + 0x14 > len(cfg):
                return None
            if cfg[off] == 0x10:   # PCI Express capability
                linkcap = int.from_bytes(cfg[off + 0x0C:off + 0x10], "little")
                linkstat = int.from_bytes(cfg[off + 0x12:off + 0x14], "little")
                return {"cur_speed": linkstat & 0xF,
                        "cur_width": (linkstat >> 4) & 0x3F,
                        "max_speed": linkcap & 0xF,
                        "max_width": (linkcap >> 4) & 0x3F}
            off = cfg[off + 1] & 0xFC
        return None

    def chip_diagnostics(self, pci_base_path: str,
                         bdf: str) -> "Tuple[int, Optional[dict]]":
        """(latched error bits, PCIe link state) from ONE config read.

        The /status and /metrics scrapes and the 5 s health poll want both
        facts per device; reading the config file once per device halves
        their syscall load versus separate pci_status + pcie_link probes.
        Error bits are the XID-events analogue (0 = clean/unreadable;
        all-FF no-response reads count as clean — that's the off-bus
        artifact, probe_config's DEAD case, not latched errors). The link
        dict is None when the PCIe capability is unreachable."""
        path = os.path.join(pci_base_path, bdf, "config")
        if self._lib is not None and self._has_chip_diag:
            outs = [ctypes.c_int() for _ in range(5)]
            rc = self._lib.tpuhealth_chip_diag(
                path.encode(), *[ctypes.byref(o) for o in outs])
            status, cs, cw, ms_, mw = (o.value for o in outs)
            if rc != OK or status < 0:
                return 0, None
            link = (None if ms_ < 0 else
                    {"cur_speed": cs, "cur_width": cw,
                     "max_speed": ms_, "max_width": mw})
            return status & PCI_STATUS_ERROR_MASK, link
        # fallback: one 256-byte read serves both facts, same as the C side
        try:
            with open(path, "rb") as f:
                cfg = f.read(256)
        except OSError:
            return 0, None
        if len(cfg) < 8:
            return 0, None
        status = cfg[6] | (cfg[7] << 8)
        bits = 0 if status == 0xFFFF else status & PCI_STATUS_ERROR_MASK
        return bits, self._parse_link_cfg(cfg)

    def chip_link_degraded(self, pci_base_path: str, bdf: str) -> bool:
        """True when the chip's PCIe link trained below its maximum —
        connector fault / thermal retrain signal (NVML's
        CurrPcieLinkWidth/Generation analogue). Diagnostic, never a
        liveness veto: a degraded chip still works, just slower."""
        return link_is_degraded(
            self.chip_diagnostics(pci_base_path, bdf)[1])

    def chip_error_bits(self, pci_base_path: str, bdf: str) -> int:
        """Latched PCI error bits for one chip (0 = clean/unreadable).

        The XID-events analogue: parity/SERR/abort bits latch on bus errors
        even while the chip is vfio-bound. Diagnostic, not a liveness veto —
        the bits can be sticky from boot-time bus probing."""
        return self.chip_diagnostics(pci_base_path, bdf)[0]

    def chip_alive(self, pci_base_path: str, bdf: str,
                   node_path: Optional[str] = None) -> bool:
        """Composite liveness for one chip (what the health hub's probe
        scheduler polls — healthhub.HealthHub; also the standalone
        HealthMonitor's probe).

        ANDs two independent native probes: PCI config space (a fallen-off
        chip reads all-FF) and, when the chip has an associated device node
        (`/dev/vfio/<group>`, `/dev/accelN`, mdev sysfs dir), its presence via
        `probe_node` — so a vanished node flips health through the native
        source even when the inotify watcher is degraded (the reference's
        NVML XID watch plays this role, generic_vgpu_device_plugin.go:387-433).
        """
        status = self.probe_config(os.path.join(pci_base_path, bdf, "config"))
        if status == MISSING:
            # Fixture trees have no config file; absence of the whole device
            # dir is the real death signal there.
            alive = os.path.isdir(os.path.join(pci_base_path, bdf))
        else:
            alive = status == OK
        if alive and node_path is not None:
            alive = self.probe_node(node_path) == OK
        if alive:
            # surface latched bus errors + link degradation without
            # vetoing; one config read for both, logged on change only
            bits, link = self.chip_diagnostics(pci_base_path, bdf)
            if bits != self._err_logged.get(bdf, 0):
                self._err_logged[bdf] = bits
                if bits:
                    log.warning("chip %s: PCI status error bits 0x%04x "
                                "latched (diagnostic, not vetoing health)",
                                bdf, bits)
            if link is not None:
                degraded = link_is_degraded(link)
                if degraded != self._link_logged.get(bdf, False):
                    self._link_logged[bdf] = degraded
                    if degraded:
                        log.warning(
                            "chip %s: PCIe link degraded — gen%d x%d trained"
                            " vs gen%d x%d capable (diagnostic, not vetoing"
                            " health)", bdf, link["cur_speed"],
                            link["cur_width"], link["max_speed"],
                            link["max_width"])
                    else:
                        log.info("chip %s: PCIe link recovered to gen%d x%d",
                                 bdf, link["cur_speed"], link["cur_width"])
        return alive
