"""Fake TPU host filesystem fixtures.

Builds tmpdir sysfs/devfs trees with real files and symlinks, emulating the
kernel the way the reference's tests do (reference:
pkg/device_plugin/device_plugin_test.go:137-166, :279-323 — tmpdir trees with
driver/iommu_group symlinks and attribute files).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional, Sequence


class FakeKubelet:
    """A real gRPC Registration server playing the kubelet.

    Records every RegisterRequest; `wait_for(n)` blocks until n registrations
    arrived. Shared by every suite that needs a kubelet endpoint.
    """

    def __init__(self, kubelet_socket: str, max_workers: int = 4):
        from concurrent import futures

        import grpc

        from tpu_device_plugin import kubeletapi as api
        from tpu_device_plugin.kubeletapi import pb

        self.registrations = []
        self.cond = threading.Condition()
        outer = self

        class Reg(api.RegistrationServicer):
            def Register(self, request, context):
                with outer.cond:
                    outer.registrations.append(request)
                    outer.cond.notify_all()
                return pb.Empty()

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        api.add_registration_servicer(self._server, Reg())
        self._server.add_insecure_port(f"unix://{kubelet_socket}")
        self._server.start()

    def wait_for(self, n: int, timeout: float = 10) -> bool:
        with self.cond:
            return self.cond.wait_for(lambda: len(self.registrations) >= n,
                                      timeout=timeout)

    @property
    def resource_names(self):
        with self.cond:
            return [r.resource_name for r in self.registrations]

    def stop(self) -> None:
        self._server.stop(0)


@dataclass
class FakeChip:
    bdf: str
    device_id: str = "0062"            # default: v4 placeholder id
    iommu_group: str = "1"
    numa_node: int = 0
    vendor: str = "0x1ae0"
    driver: Optional[str] = "vfio-pci"
    accel_index: Optional[int] = None  # also expose /sys/class/accel + /dev/accelN
    vfio_dev: Optional[str] = None     # e.g. "vfio3": create <bdf>/vfio-dev/vfio3
    serial: Optional[str] = None       # sysfs serial_number (replug identity)
    # upstream PCIe bridge BDF: materializes the device nested under
    # /sys/devices/pci0000:00/<parent>/<bdf> with a symlink from the flat
    # bus view, like real sysfs
    pcie_parent: Optional[str] = None


class FakeHost:
    """Materialize chips/mdevs/devfs under a root dir; returns a Config-able root."""

    def __init__(self, root: str):
        self.root = str(root)
        self.pci = os.path.join(self.root, "sys/bus/pci/devices")
        self.drivers = os.path.join(self.root, "sys/bus/pci/drivers")
        self.iommu_groups = os.path.join(self.root, "sys/kernel/iommu_groups")
        self.mdev = os.path.join(self.root, "sys/bus/mdev/devices")
        self.accel = os.path.join(self.root, "sys/class/accel")
        self.devfs = os.path.join(self.root, "dev")
        for d in (self.pci, self.drivers, self.iommu_groups, self.mdev,
                  self.accel, os.path.join(self.devfs, "vfio")):
            os.makedirs(d, exist_ok=True)
        self._write(os.path.join(self.devfs, "vfio", "vfio"), "")

    def _write(self, path: str, content: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="ascii") as f:
            f.write(content)

    def add_chip(self, chip: FakeChip) -> None:
        base = os.path.join(self.pci, chip.bdf)
        if chip.pcie_parent:
            real = os.path.join(self.root, "sys/devices/pci0000:00",
                                chip.pcie_parent, chip.bdf)
            os.makedirs(real, exist_ok=True)
            if not os.path.islink(base):
                os.symlink(real, base)
        os.makedirs(base, exist_ok=True)
        self._write(os.path.join(base, "vendor"), chip.vendor + "\n")
        self._write(os.path.join(base, "device"), "0x" + chip.device_id + "\n")
        self._write(os.path.join(base, "numa_node"), f"{chip.numa_node}\n")
        if chip.serial is not None:
            self._write(os.path.join(base, "serial_number"), chip.serial + "\n")
        if chip.driver:
            drv_dir = os.path.join(self.drivers, chip.driver)
            os.makedirs(drv_dir, exist_ok=True)
            link = os.path.join(base, "driver")
            if not os.path.islink(link):
                os.symlink(drv_dir, link)
        grp_dir = os.path.join(self.iommu_groups, chip.iommu_group)
        os.makedirs(grp_dir, exist_ok=True)
        link = os.path.join(base, "iommu_group")
        if not os.path.islink(link):
            os.symlink(grp_dir, link)
        self._write(os.path.join(self.devfs, "vfio", chip.iommu_group), "")
        if chip.accel_index is not None:
            accel_dir = os.path.join(self.accel, f"accel{chip.accel_index}")
            os.makedirs(accel_dir, exist_ok=True)
            dev_link = os.path.join(accel_dir, "device")
            if not os.path.islink(dev_link):
                os.symlink(base, dev_link)
            self._write(os.path.join(self.devfs, f"accel{chip.accel_index}"), "")
        if chip.vfio_dev:
            os.makedirs(os.path.join(base, "vfio-dev", chip.vfio_dev), exist_ok=True)
            self._write(os.path.join(self.devfs, "vfio", "devices", chip.vfio_dev), "")

    def enable_iommufd(self) -> None:
        self._write(os.path.join(self.devfs, "iommu"), "")

    def add_mdev(self, uuid: str, type_name: str, parent_bdf: str,
                 iommu_group: Optional[str] = None) -> None:
        """mdev device: a symlink whose resolved path has the parent BDF
        second-to-last (reference derives parent that way, :347-357)."""
        parent_dir = os.path.join(self.pci, parent_bdf)
        real = os.path.join(parent_dir, uuid)
        os.makedirs(os.path.join(real, "mdev_type"), exist_ok=True)
        self._write(os.path.join(real, "mdev_type", "name"), type_name + "\n")
        if iommu_group is not None:
            grp_dir = os.path.join(self.iommu_groups, iommu_group)
            os.makedirs(grp_dir, exist_ok=True)
            grp_link = os.path.join(real, "iommu_group")
            if not os.path.islink(grp_link):
                os.symlink(grp_dir, grp_link)
            self._write(os.path.join(self.devfs, "vfio", iommu_group), "")
        link = os.path.join(self.mdev, uuid)
        if not os.path.islink(link):
            os.symlink(real, link)

    def add_shared_device(self, name: str, member_bdfs: Sequence[str],
                          class_name: str = "egm") -> None:
        """EGM-analogue shared device: class entry + membership file + /dev node."""
        base = os.path.join(self.root, "sys/class", class_name, name)
        os.makedirs(base, exist_ok=True)
        self._write(os.path.join(base, "chip_devices"), "\n".join(member_bdfs) + "\n")
        self._write(os.path.join(self.devfs, name), "")

    def remove_vfio_group(self, group: str) -> None:
        os.unlink(os.path.join(self.devfs, "vfio", group))
