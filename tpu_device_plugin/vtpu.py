"""VtpuDevicePlugin — shareable sub-chip partitions (the reference's vGPU slot).

Analogue of `GenericVGpuDevicePlugin` (generic_vgpu_device_plugin.go:55-433),
with two deliberate upgrades:

- Allocate mounts only the partition's own VFIO group instead of all of
  `/dev/vfio` (the reference mounts the whole directory, :229-233 — noted in
  SURVEY.md §2 #12 as a fix);
- GetPreferredAllocation is implemented (the reference stubs it, :269-277):
  partitions are packed onto the fewest parent chips to curb fragmentation,
  then NUMA, then kubelet order.

Health: partition presence (mdev dir / accel node, the reference's fsnotify
path :319-328) plus a parent-chip liveness probe fanned out to every
partition of a dead chip (the reference's XID→vGpuMap fan-out :334-339).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Sequence

import grpc

from . import broker as broker_mod
from . import lockdep
from .allocate import (AllocationError, AllocationPlanner, LiveAttrReader,
                       live_mdev_type)
from .config import Config
from .healthhub import HubSubscription
from .kubeletapi import pb
from .naming import sanitize_name
from .registry import Registry, TpuPartition
from .server import TpuDevicePlugin

log = logging.getLogger(__name__)


class VtpuDevicePlugin(TpuDevicePlugin):
    def __init__(
        self,
        cfg: Config,
        type_name: str,
        registry: Registry,
        partitions: Sequence[TpuPartition],
        health_shim=None,
        cdi_enabled: bool = False,
        cdi_uuids: frozenset = frozenset(),
        health_listener=None,
        health_hub=None,
        lifecycle=None,
        policy=None,
        remediation=None,
    ) -> None:
        self.partitions = list(partitions)
        # only partitions with a resolvable CDI spec entry get CDI names
        self.cdi_uuids = cdi_uuids
        # byte_plane=False: every vTPU response is assembled per request
        # (both _allocate_impl and GetPreferredAllocation are overridden
        # with message-path implementations), so the inherited planner
        # must not build — or ledger — byte records nothing reads
        super().__init__(cfg, type_name, registry, devices=[],
                         health_shim=health_shim, cdi_enabled=cdi_enabled,
                         health_listener=health_listener,
                         health_hub=health_hub, lifecycle=lifecycle,
                         policy=policy, remediation=remediation,
                         byte_plane=False)
        # own socket namespace so a generation and a partition type never collide
        self.socket_path = os.path.join(
            cfg.device_plugin_path, f"{cfg.socket_prefix}-vtpu-{type_name}.sock")
        # passthrough planner for vfio-backed logical partitions (parent-BDF
        # group expansion). The inherited self._planner was built from
        # devices=[] (allowed_bdfs=frozenset()) and would reject every
        # parent; this one is unscoped — partition membership is already
        # validated against self.partitions before plan() is called.
        # Message path only (vTPU responses are assembled per request),
        # so no byte records are built or ledgered.
        self._parent_planner = AllocationPlanner(cfg, registry, type_name,
                                                 byte_records=False)
        # partition set is fixed for this server's lifetime (rediscovery
        # rebuilds the server) — index it once, not per RPC
        self._by_uuid = {p.uuid: p for p in self.partitions}
        # live mdev_type/name reads for _validate_mdev (see LiveAttrReader)
        self._mdev_name_reader = LiveAttrReader()

    # ------------------------------------------------------------------ state

    def _device_rows(self):
        # partitions are this server's advertised devices; the shared
        # epoch builder (epoch.build_server_epoch) renders them
        return tuple((p.uuid, p.numa_node) for p in self.partitions)

    def _start_monitor(self) -> None:
        paths: Dict[str, str] = {}
        children: Dict[str, List[str]] = {}
        for p in self.partitions:
            if p.provider == "mdev":
                paths[p.uuid] = os.path.join(self.cfg.mdev_base_path, p.uuid)
            elif p.accel_index is not None:
                paths[p.uuid] = self.cfg.dev_path("dev", f"accel{p.accel_index}")
            else:
                group = self.registry.bdf_to_group.get(p.parent_bdf)
                if group is not None:
                    # vfio-backed logical partition: watch the group node the
                    # allocation will mount
                    paths[p.uuid] = self.cfg.dev_path("dev/vfio", group)
            children.setdefault(p.parent_bdf, []).append(p.uuid)
        # Probes are keyed by parent BDF while `paths` is keyed by partition
        # uuid — resolve a representative child node per parent so the
        # node-presence AND inside chip_alive (the degraded-inotify backstop)
        # actually sees the node the allocation mounts.
        parent_node: Dict[str, str] = {}
        for p in self.partitions:
            node = paths.get(p.uuid)
            if node is not None:
                parent_node.setdefault(p.parent_bdf, node)

        def on_health(key: str, ok: bool, src: str) -> None:
            # fs events arrive keyed by partition uuid; probe verdicts by
            # parent BDF and fan out to every partition of that chip
            self.set_devices_health(children.get(key, [key]), ok, src)

        probe = lambda bdf, _node: self.health_shim.chip_alive(  # noqa: E731
            self.cfg.pci_base_path, bdf, parent_node.get(bdf))
        self._attach_probe_batch(probe, node_for=parent_node.get)
        self._subscribe_health(HubSubscription(
            name=self.resource_name,
            socket_path=self.socket_path,
            on_socket_removed=self._restart_async,
            group_paths=paths,
            # probe each DISTINCT parent chip once per cycle (64 per-core
            # partitions of 8 chips = 8 probes, not 64), XID-fan-out style;
            # the hub additionally dedups a parent shared with another
            # resource's subscription down to ONE physical read
            group_bdfs={parent: [parent] for parent in children},
            on_device_health=on_health,
            probe=probe,
        ))

    # ------------------------------------------------------------------- RPCs

    def _validate_mdev(self, p: TpuPartition) -> None:
        """Live mdev type must still match this plugin (reference :216-221)."""
        live = live_mdev_type(self._mdev_name_reader, self.cfg, p.uuid)
        if live != self.resource_suffix:
            raise AllocationError(
                f"partition {p.uuid}: live type {live!r} != {self.resource_suffix!r}")

    def _allocate_impl(self, request, context):
        by_uuid = self._by_uuid
        # one epoch read per RPC: keys the parent planner's precompiled
        # fragments (a parent-chip health flip publishes a new epoch, so
        # the next plan recompiles — no uuid->parent invalidation mapping)
        epoch_id = self._store.current.epoch_id
        resp = pb.AllocateResponse()
        try:
            for creq in request.container_requests:
                uuids = list(creq.devices_ids)
                specs: List[pb.DeviceSpec] = []
                seen_paths = set()
                pci_addrs: List[str] = []  # vfio-backed parents, group-expanded

                def add(host: str, container: str, perms: str = "mrw") -> None:
                    if host not in seen_paths:
                        seen_paths.add(host)
                        specs.append(pb.DeviceSpec(
                            host_path=host, container_path=container,
                            permissions=perms))

                for uuid in uuids:
                    p = by_uuid.get(uuid)
                    if p is None:
                        raise AllocationError(f"unknown partition {uuid}")
                    if p.provider == "mdev":
                        self._validate_mdev(p)
                        add(self.cfg.dev_path("dev/vfio/vfio"), "/dev/vfio/vfio")
                        # via the privilege seam: spawn mode brokers the
                        # readlink, a read-only daemon never touches the
                        # host tree during Allocate
                        group = broker_mod.seam_read_link(
                            os.path.join(self.cfg.mdev_base_path, uuid, "iommu_group"))
                        if group is not None:
                            add(self.cfg.dev_path("dev/vfio", group),
                                f"/dev/vfio/{group}")
                        else:
                            # no per-mdev group visible: reference-compatible
                            # wide mount of the vfio dir (:229-233)
                            add(self.cfg.dev_path("dev/vfio"), "/dev/vfio")
                    elif p.accel_index is not None:
                        # permissions are operator policy (docs/design.md
                        # "vTPU trust boundary"): "rw" default, "r" for
                        # fleets whose guest stack tolerates it
                        add(self.cfg.dev_path("dev", f"accel{p.accel_index}"),
                            f"/dev/accel{p.accel_index}",
                            self.cfg.partition_node_permissions)
                    else:
                        # Logical partition of a vfio-bound parent: the guest
                        # can only reach the chip through its VFIO group, so
                        # mount it whole. Discovery guarantees at most ONE
                        # such partition per parent (a VFIO group attaches to
                        # one VM at a time) and drops partitions with neither
                        # an accel node nor a vfio-bound parent, so an
                        # allocation NEVER returns zero DeviceSpecs.
                        # the parent planner supplies the same sysfs
                        # revalidation + iommufd handling passthrough gets.
                        if p.parent_bdf not in self.registry.bdf_to_group:
                            raise AllocationError(
                                f"partition {uuid}: parent {p.parent_bdf} has "
                                "no accel node and is not vfio-bound")
                        plan = self._parent_planner.plan(
                            [p.parent_bdf], shared_devices=[],
                            epoch=epoch_id)
                        for s in plan.device_specs:
                            add(s.host_path, s.container_path, s.permissions)
                        for addr in plan.expanded_bdfs:
                            if addr not in pci_addrs:
                                pci_addrs.append(addr)
                env_key = f"{self.cfg.vtpu_env_prefix}_{sanitize_name(self.resource_suffix)}"
                envs = {env_key: ",".join(uuids)}
                if pci_addrs:
                    # vfio-backed partitions attach as PCI passthrough of the
                    # parent: virt-launcher locates the device through the
                    # PCI_RESOURCE env (config.py env_prefix contract), not
                    # the MDEV uuid env
                    pci_key = (f"{self.cfg.env_prefix}_"
                               f"{sanitize_name(self.resource_suffix)}")
                    envs[pci_key] = ",".join(pci_addrs)
                cresp = pb.ContainerAllocateResponse(envs=envs, devices=specs)
                if self.cdi_enabled:
                    from .cdi import cdi_device_name
                    cresp.cdi_devices.extend(
                        pb.CDIDevice(name=cdi_device_name(self.cfg, uuid))
                        for uuid in uuids if uuid in self.cdi_uuids)
                resp.container_responses.append(cresp)
        except AllocationError as exc:
            log.error("%s: allocate failed: %s", self.resource_name, exc)
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        return resp

    def GetPreferredAllocation(self, request, context):
        """Pack partitions onto the fewest parent chips (anti-fragmentation),
        preferring parents on the NUMA node the allocation started on.
        Pure compute over the construction-time partition index — the
        read-path bracket pins it lock-free like the base class's.
        Message path by design (the packing depends on the live request's
        availability set, so there is nothing epoch-stable to
        pre-serialize): counted on the serialization ledger."""
        with lockdep.read_path("server.GetPreferredAllocation"):
            self._alloc_serializations.add()
            return self._preferred_impl(request, context)

    def _preferred_impl(self, request, context):
        by_uuid = self._by_uuid
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            must = list(creq.must_include_deviceIDs)
            size = creq.allocation_size
            if len(must) > size:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"{len(must)} must-include devices > allocation size {size}")
            avail = [u for u in creq.available_deviceIDs
                     if u in by_uuid and u not in set(must)]
            # kubelet order preserved within each parent bucket
            buckets: Dict[str, List[str]] = {}
            for u in avail:
                buckets.setdefault(by_uuid[u].parent_bdf, []).append(u)
            # parents already pinned by must-include go first, then
            # fullest-first; NUMA locality to the anchor breaks ties (the
            # reference stubs this RPC entirely for vGPUs)
            must_parents = [by_uuid[u].parent_bdf for u in must if u in by_uuid]
            # anchor on the first KNOWN device, must-includes first (an
            # unknown must uuid is skipped here like in must_parents above)
            anchor = next((by_uuid[u].numa_node
                           for u in (*must, *avail) if u in by_uuid), None)

            def numa_of(parent: str) -> int:
                uuids = buckets[parent]
                return by_uuid[uuids[0]].numa_node

            order = sorted(
                buckets.items(),
                key=lambda kv: (kv[0] not in must_parents, -len(kv[1]),
                                numa_of(kv[0]) != anchor, kv[0]))
            chosen = list(must)
            for _, uuids in order:
                for u in uuids:
                    if len(chosen) >= size:
                        break
                    chosen.append(u)
            resp.container_responses.append(
                pb.ContainerPreferredAllocationResponse(deviceIDs=chosen[:size]))
        return resp
