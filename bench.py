#!/usr/bin/env python3
"""Benchmark: the plugin's VMI-attach control-plane critical path.

BASELINE.md config 1 defines the measurable baseline ("1 vfio-pci stub
device → 1 VMI: Allocate() RPC latency; devices advertised; plugin on CPU").
This bench builds a fake 8-chip v5e host, serves a real plugin over a real
unix-socket gRPC server, and measures the kubelet-visible critical path for
a 4-chip ICI-adjacent allocation: GetPreferredAllocation + Allocate RPC
round-trips. The reference publishes no numbers (SURVEY.md §6), so the
baseline is this protocol's own recorded round-1 p50 (BENCH_r01.json):
vs_baseline = round1_p50 / current_p50, >1.0 meaning faster than round 1.

Methodology (round 4, VERDICT r3 item 8): the HEADLINE `value`/
`vs_baseline` is now the load-insensitive HANDLER COMPUTE number — direct
in-process servicer calls (GetPreferredAllocation + Allocate), no gRPC
RTTs — because the wall-clock path on this single shared CPU core is
hostage to co-tenant load (observed 804-1062 us same-code spread in round
3, with two gRPC RTTs ~460-740 us of it). Its baseline is round 3's
recorded handler measurement (41 us, BASELINE.md config 1):
vs_baseline = 41.0 / current, >1.0 meaning faster than round 3. The full
kubelet-visible wall path is still measured and reported alongside
(`wall_p50_us`, `wall_vs_round1` against BENCH_r01's 820.3 us,
`best_epoch_p50_us` = min of 4 epoch medians as the achievable-latency
estimate, p99 over all samples).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

`python bench.py --matrix` additionally runs the scaling matrix
({8,16,64} devices × allocation sizes {1,4,8} × {0,128} partitions),
prints a human-readable table on stderr, and writes
docs/bench_matrix_r05.json (scaling matrix, VERDICT r2 next-item #5).
"""

import json
import math
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time
from concurrent import futures

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import grpc

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import (HostSnapshot, count_reads, discover,
                                         discover_passthrough)
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import LOOPBACK_GRPC_OPTIONS, TpuDevicePlugin
from tpu_device_plugin.vtpu import VtpuDevicePlugin

ITERATIONS = 300
WARMUP = 20
EPOCHS = 4


def _timed_median_us(fn, iterations, warmup):
    """Median µs of fn() after warmup (single-measurement loops;
    _handler_compute keeps its own loop because it interleaves paired
    pref/alloc timings)."""
    samples = []
    for i in range(iterations + warmup):
        t1 = time.perf_counter()
        fn()
        if i >= warmup:
            samples.append((time.perf_counter() - t1) * 1e6)
    return statistics.median(samples)


def _min_epoch_p50(samples, epochs=EPOCHS):
    """Min of per-epoch medians (see module docstring: single shared core)."""
    n = len(samples) // epochs
    return min(statistics.median(samples[i * n:(i + 1) * n])
               for i in range(epochs))


def _build_host(root, n_devices, device_id="0063"):
    host = FakeHost(root)
    for i in range(n_devices):
        # two NUMA nodes, split in halves — the same layout rounds 1-2
        # measured (i//4 on 8 chips), kept so vs_baseline compares like
        # against like
        host.add_chip(FakeChip(f"0000:{i // 32:02x}:{4 + i % 32:02x}.0",
                               device_id=device_id,
                               iommu_group=str(11 + i),
                               numa_node=i // max(1, n_devices // 2)))
    return host


def _serve(plugin, workers=4):
    # same channel options as the production server (server.py
    # LOOPBACK_GRPC_OPTIONS): the bench must measure the config the
    # kubelet actually talks to
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=workers),
                         options=LOOPBACK_GRPC_OPTIONS)
    api.add_device_plugin_servicer(server, plugin)
    server.add_insecure_port(f"unix://{plugin.socket_path}")
    server.start()
    return server


def _attach_path(stub, all_ids, alloc_size, iterations, warmup):
    """(pref_us, attach_us) samples for the 2-RPC critical path."""
    pref_us, attach_us = [], []
    for i in range(iterations + warmup):
        t1 = time.perf_counter()
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=all_ids,
                    allocation_size=alloc_size)]),
            timeout=5)
        t2 = time.perf_counter()
        picked = list(pref.container_responses[0].deviceIDs)
        resp = stub.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=picked)]),
            timeout=5)
        t3 = time.perf_counter()
        assert len(resp.container_responses[0].devices) >= 1 + alloc_size
        if i >= warmup:
            pref_us.append((t2 - t1) * 1e6)
            attach_us.append((t3 - t1) * 1e6)
    return pref_us, attach_us


def _handler_compute(plugin, all_ids, alloc_size, iterations=2000,
                     warmup=100):
    """Deterministic handler-compute medians via DIRECT servicer calls.

    No sockets, no serialization round-trips, no scheduler handoffs: this
    is the plugin's own CPU work on the attach path, the only number on a
    shared core that round-over-round comparisons can trust. (Context is
    None: the happy path never touches it.)"""
    pref_req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=all_ids, allocation_size=alloc_size)])
    pref_us, alloc_us = [], []
    for i in range(iterations + warmup):
        t1 = time.perf_counter()
        pref = plugin.GetPreferredAllocation(pref_req, None)
        t2 = time.perf_counter()
        alloc_req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(
                devices_ids=list(pref.container_responses[0].deviceIDs))])
        t3 = time.perf_counter()
        resp = plugin.Allocate(alloc_req, None)
        t4 = time.perf_counter()
        assert len(resp.container_responses[0].devices) >= 1 + alloc_size
        if i >= warmup:
            pref_us.append((t2 - t1) * 1e6)
            alloc_us.append((t4 - t3) * 1e6)
    # cold-path preferred allocation: the memo cache cleared every call, so
    # the number reflects a first-seen availability set (full box scan)
    cold_us = []
    for i in range(iterations // 4 + warmup // 4):
        plugin._pref_cache.clear()
        t1 = time.perf_counter()
        plugin.GetPreferredAllocation(pref_req, None)
        t2 = time.perf_counter()
        if i >= warmup // 4:
            cold_us.append((t2 - t1) * 1e6)
    # best-epoch variant alongside the medians: even direct-call numbers
    # swing with co-tenant load on this single shared core. The alloc and
    # cold series are timed in SEPARATE loops, so the sum of their minima
    # is a lower bound no single quiet window necessarily achieved —
    # slightly optimistic vs best_epoch_p50_us (min over one contiguous
    # series). NOT the headline — the 41 us round-3 anchor was a median.
    best = (_min_epoch_p50(alloc_us), _min_epoch_p50(cold_us))
    return (statistics.median(pref_us), statistics.median(alloc_us),
            statistics.median(cold_us), best)


def _dra_prepare_bench(root, registry, generations, iterations=150,
                       warmup=15):
    """Cold NodePrepareResources / NodeUnprepareResources handler p50.

    The DRA driver is the successor API surface (PARITY #15, no reference
    counterpart) — this keeps its kubelet-visible prepare path measured
    alongside the classic Allocate. Each iteration prepares a FRESH claim
    (API fetch over localhost HTTP + device planning + per-claim CDI spec
    write + checkpoint write) and unprepares it (spec unlink + checkpoint
    write), so every sample is the cold path a real first-prepare pays.
    """
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin.dra import DraDriver, slice_device_name
    from tpu_device_plugin.kubeletapi import drapb
    from tpu_device_plugin.kubeapi import ApiClient

    apiserver = FakeApiServer()
    try:
        api_client = ApiClient(apiserver.url, token_path="/nonexistent")
        driver = DraDriver(Config().with_root(root), registry, generations,
                           node_name="bench-node", api=api_client)
        devs = next(iter(registry.devices_by_model.values()))
        names = [slice_device_name(devs[0].bdf),
                 slice_device_name(devs[1].bdf)]
        prep_us, unprep_us = [], []
        for i in range(iterations + warmup):
            uid = f"bench-claim-{i}"
            apiserver.add_claim("bench", f"c{i}", uid, driver.driver_name,
                                [{"device": n} for n in names])
            claim = drapb.Claim(namespace="bench", name=f"c{i}", uid=uid)
            t0 = time.perf_counter()
            resp = driver.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[claim]), None)
            t1 = time.perf_counter()
            assert resp.claims[uid].error == "", resp.claims[uid].error
            assert len(resp.claims[uid].devices) == 2
            t2 = time.perf_counter()
            driver.NodeUnprepareResources(
                drapb.NodeUnprepareResourcesRequest(claims=[claim]), None)
            t3 = time.perf_counter()
            if i >= warmup:
                prep_us.append((t1 - t0) * 1e6)
                unprep_us.append((t3 - t2) * 1e6)
        driver.stop()
        return (round(statistics.median(prep_us), 1),
                round(statistics.median(unprep_us), 1))
    finally:
        apiserver.stop()


def run_config1(root):
    """The headline config-1 measurement on an 8-chip v5e host."""
    host = _build_host(root, 8)
    cfg = Config().with_root(root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)

    t0 = time.perf_counter()
    registry, generations = discover_passthrough(cfg)
    discovery_ms = (time.perf_counter() - t0) * 1e3
    devices = registry.devices_by_model["0063"]

    plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                             torus_dims=generations["0063"].host_topology)
    server = _serve(plugin, workers=4)
    all_ids = [d.bdf for d in devices]
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        pref_us, attach_us = _attach_path(stub, all_ids, 4, ITERATIONS, WARMUP)
    (handler_pref_us, handler_alloc_us, handler_pref_cold_us,
     handler_best) = _handler_compute(plugin, all_ids, 4)
    server.stop(0)

    # secondary: vTPU partition Allocate p50 (mdev path with live sysfs
    # revalidation) on the same host
    host.add_mdev("bench-uuid-0", "TPU vhalf", "0000:00:04.0",
                  iommu_group="31")
    host.add_mdev("bench-uuid-1", "TPU vhalf", "0000:00:04.0",
                  iommu_group="32")
    vregistry, _ = discover(cfg)
    vplugin = VtpuDevicePlugin(cfg, "TPU_vhalf", vregistry,
                               vregistry.partitions_by_type["TPU_vhalf"])
    vserver = _serve(vplugin, workers=4)
    vreq = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(
            devices_ids=["bench-uuid-0", "bench-uuid-1"])])

    def check(vresp):
        # the measured path must be the per-group mount (vfio cdev +
        # groups 31, 32), never the wide /dev/vfio fallback
        assert len(vresp.container_responses[0].devices) == 3

    with grpc.insecure_channel(f"unix://{vplugin.socket_path}") as ch:
        vstub = api.DevicePluginStub(ch)
        vtpu_p50 = _timed_median_us(
            lambda: check(vstub.Allocate(vreq, timeout=5)),
            ITERATIONS // 3, WARMUP)
    # vTPU handler compute (direct servicer calls — same load-insensitive
    # methodology as the headline; the wall number above keeps the
    # kubelet-visible gRPC path)
    vhandler_p50 = _timed_median_us(
        lambda: check(vplugin.Allocate(vreq, None)), ITERATIONS, WARMUP)
    vserver.stop(0)

    # successor API surface: cold DRA prepare/unprepare handler p50
    dra_prep_us, dra_unprep_us = _dra_prepare_bench(root, registry,
                                                    generations)

    # environment self-calibration (round 9): handler_allocate is ~30
    # sysfs syscalls deep (live TOCTOU revalidation), so its wall is a
    # function of per-syscall cost — sub-us on a native kernel, ~20-40 us
    # under sandboxed/emulated kernels (gVisor-style). Recording the
    # in-run stat() p50 makes rounds comparable across environments:
    # divide the sysfs-bound numbers by this before calling a regression.
    # The probe stats a REAL device attribute (full sysfs path depth —
    # path-resolution cost scales with component count in emulated
    # kernels, so a shallow probe would under-normalize).
    cal_path = os.path.join(cfg.pci_base_path, devices[0].bdf, "vendor")
    cal_ts = []
    for _ in range(500):
        t1 = time.perf_counter()
        os.stat(cal_path)
        cal_ts.append((time.perf_counter() - t1) * 1e6)
    syscall_stat_p50_us = round(statistics.median(cal_ts), 2)

    p50 = statistics.median(attach_us)   # same estimator as rounds 1-2
    round1_p50_us = 820.3
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r01.json")) as f:
            round1_p50_us = float(json.load(f)["parsed"]["value"])
    except (OSError, KeyError, ValueError, TypeError):
        pass  # keep the recorded constant if the file is gone/reshaped
    pref_p50 = statistics.median(pref_us)
    # HEADLINE uses the COLD preferred-allocation path (memo cache cleared
    # per call): round 3's 41 us baseline was measured without the cache, so
    # a warm-hit headline would compare a ~1 us lookup against a 12 us scan
    # and claim a speedup a real kubelet (changing availability between
    # allocations) would rarely see. The warm number is reported alongside.
    handler_us = handler_pref_cold_us + handler_alloc_us
    # round 3's recorded handler-compute measurement (BASELINE.md config 1:
    # preferred_allocation 12 us + allocate_response 29 us on this host)
    round3_handler_us = 41.0
    return {
        "metric": "attach_handler_compute_p50",
        "value": round(handler_us, 1),
        "unit": "us",
        "vs_baseline": round(round3_handler_us / handler_us, 3),
        # vs_baseline was re-based in round 4: rounds 1-3 compared wall p50
        # against round 1's 820 us wall capture (still emitted as
        # wall_p50_us / wall_vs_round1); the headline ratio now divides the
        # round-3 handler-compute constant below by this round's
        # handler-compute. Ratios across BENCH_r0{1..3}.json are therefore
        # NOT comparable with r04+ without this field.
        "baseline_source": ("round-3 handler-compute constant 41.0 us "
                            "(BASELINE.md config 1: preferred 12 us + "
                            "allocate 29 us); wall_vs_round1 keeps the "
                            "rounds-1-3 wall-clock basis"),
        "handler_preferred_cold_us": round(handler_pref_cold_us, 1),
        "handler_preferred_warm_us": round(handler_pref_us, 1),
        "handler_allocate_us": round(handler_alloc_us, 1),
        # min of per-epoch medians per series (cold pref + allocate, timed
        # in separate loops — a jointly-optimistic lower bound), reported
        # alongside the median headline, never as it
        "handler_best_epoch_us": round(sum(handler_best), 1),
        "wall_p50_us": round(p50, 1),
        "wall_vs_round1": round(round1_p50_us / p50, 3),
        "preferred_allocation_p50_us": round(pref_p50, 1),
        "allocate_p50_us": round(p50 - pref_p50, 1),
        "p99_us": round(statistics.quantiles(attach_us, n=100)[98], 1),
        "best_epoch_p50_us": round(_min_epoch_p50(attach_us), 1),
        "vtpu_allocate_p50_us": round(vtpu_p50, 1),
        "vtpu_handler_allocate_us": round(vhandler_p50, 1),
        "dra_prepare_p50_us": dra_prep_us,
        "dra_unprepare_p50_us": dra_unprep_us,
        "discovery_ms": round(discovery_ms, 2),
        # in-run per-syscall cost (see comment above): the sysfs-bound
        # numbers scale with this; BENCH_r05's environment ran it <1 us
        "syscall_stat_p50_us": syscall_stat_p50_us,
        "devices_advertised": len(devices),
        "allocation_size": 4,
        "iterations": ITERATIONS,
        "epochs": EPOCHS,
    }


def run_matrix():
    """Scaling matrix: devices × allocation size, plus partition scaling.

    Hosts above 8 chips use a synthetic generation map with a matching
    host torus ([4,4] for 16, [8,8] for 64) so the ICI sub-box scan — the
    most shape-sensitive code on the path — is exercised at every scale
    rather than falling back to NUMA tiering.
    """
    results = {"devices": [], "partitions": []}
    tori = {8: [2, 4], 16: [4, 4], 64: [8, 8]}
    for n in (8, 16, 64):
        root = tempfile.mkdtemp(prefix=f"tdpmx{n}-")
        try:
            _build_host(root, n)
            gen_map = {"0063": {"name": "v5e", "chips_per_host": n,
                                "host_topology": tori[n], "cores_per_chip": 1}}
            gen_path = os.path.join(root, "genmap.json")
            with open(gen_path, "w") as f:
                json.dump(gen_map, f)
            from dataclasses import replace
            cfg = replace(Config().with_root(root),
                          generation_map_path=gen_path)
            os.makedirs(cfg.device_plugin_path, exist_ok=True)
            t0 = time.perf_counter()
            registry, generations = discover_passthrough(cfg)
            discovery_ms = (time.perf_counter() - t0) * 1e3
            devices = registry.devices_by_model["0063"]
            plugin = TpuDevicePlugin(
                cfg, "v5e", registry, devices,
                torus_dims=generations["0063"].host_topology)
            server = _serve(plugin, workers=4)
            all_ids = [d.bdf for d in devices]
            with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
                stub = api.DevicePluginStub(ch)
                for alloc in (1, 4, 8):
                    pref_us, attach_us = _attach_path(
                        stub, all_ids, alloc, 100, 15)
                    results["devices"].append({
                        "n_devices": n, "allocation_size": alloc,
                        "torus": tori[n],
                        "discovery_ms": round(discovery_ms, 2),
                        "attach_p50_us": round(statistics.median(attach_us), 1),
                        "pref_p50_us": round(statistics.median(pref_us), 1),
                        "p99_us": round(
                            statistics.quantiles(attach_us, n=100)[98], 1),
                    })
            server.stop(0)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    # partition scaling: 0 vs 128 mdev partitions on a 64-chip host
    for n_parts in (0, 128):
        root = tempfile.mkdtemp(prefix=f"tdpmp{n_parts}-")
        try:
            host = _build_host(root, 64)
            for p in range(n_parts):
                host.add_mdev(f"mx-uuid-{p:03d}", "TPU vhalf",
                              f"0000:{(p % 64) // 32:02x}:{4 + p % 32:02x}.0",
                              iommu_group=str(200 + p))
            cfg = Config().with_root(root)
            os.makedirs(cfg.device_plugin_path, exist_ok=True)
            t0 = time.perf_counter()
            registry, _ = discover(cfg)
            discovery_ms = (time.perf_counter() - t0) * 1e3
            row = {"n_partitions": n_parts, "n_chips": 64,
                   "discovery_ms": round(discovery_ms, 2)}
            if n_parts:
                parts = registry.partitions_by_type["TPU_vhalf"]
                vplugin = VtpuDevicePlugin(cfg, "TPU_vhalf", registry, parts)
                vserver = _serve(vplugin, workers=4)
                vtpu_us = []
                with grpc.insecure_channel(
                        f"unix://{vplugin.socket_path}") as ch:
                    vstub = api.DevicePluginStub(ch)
                    ids = [p.uuid for p in parts[:2]]
                    for i in range(100 + 15):
                        t1 = time.perf_counter()
                        vstub.Allocate(pb.AllocateRequest(container_requests=[
                            pb.ContainerAllocateRequest(devices_ids=ids)]),
                            timeout=5)
                        if i >= 15:
                            vtpu_us.append((time.perf_counter() - t1) * 1e6)
                vserver.stop(0)
                row["advertised"] = len(parts)
                row["vtpu_allocate_p50_us"] = round(
                    statistics.median(vtpu_us), 1)
            results["partitions"].append(row)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    out = os.environ.get("BENCH_MATRIX_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "bench_matrix_r05.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    for row in results["devices"]:
        print(f"  {row['n_devices']:3d} chips torus={row['torus']} "
              f"alloc={row['allocation_size']}: discovery {row['discovery_ms']:6.2f} ms, "
              f"attach p50 {row['attach_p50_us']:7.1f} us (pref {row['pref_p50_us']:6.1f})",
              file=sys.stderr)
    for row in results["partitions"]:
        print(f"  {row['n_partitions']:3d} partitions on 64 chips: "
              f"discovery {row['discovery_ms']:6.2f} ms"
              + (f", vtpu alloc p50 {row['vtpu_allocate_p50_us']:.1f} us"
                 if row["n_partitions"] else ""),
              file=sys.stderr)
    return results


def _p50_p99(samples):
    return (round(statistics.median(samples), 1),
            round(statistics.quantiles(samples, n=100)[98], 1))


def _discovery_cell(n_devices, n_partitions, cold_iters=5, warm_iters=50):
    """Cold full-scan vs warm dirty-set rescan at one matrix point.

    The headline per cell is the SYSFS READ COUNT (deterministic on a fixed
    tree, so load on the shared bench core cannot fake the ratio); wall
    p50/p99 is reported alongside. The warm iteration models the production
    steady state: one flapped chip in the dirty set, everything else
    untouched since the last tick.
    """
    root = tempfile.mkdtemp(prefix=f"tdpdisc{n_devices}x{n_partitions}-")
    try:
        host = _build_host(root, n_devices)
        for p in range(n_partitions):
            parent = p % n_devices
            host.add_mdev(f"disc-uuid-{p:03d}", "TPU vhalf",
                          f"0000:{parent // 32:02x}:{4 + parent % 32:02x}.0",
                          iommu_group=str(1000 + p))
        cfg = Config().with_root(root)
        cold_reads, cold_us = [], []
        registry = None
        for _ in range(cold_iters):
            snap = HostSnapshot(cfg)
            with count_reads() as w:
                t0 = time.perf_counter()
                registry, _ = snap.rescan()
                cold_us.append((time.perf_counter() - t0) * 1e6)
            cold_reads.append(w.reads)
        snap = HostSnapshot(cfg)
        warm_registry, _ = snap.rescan()
        # sanity: the incremental path must see the same inventory
        assert len(warm_registry.all_devices()) == len(registry.all_devices())
        dirty_bdf = "0000:00:04.0"
        warm_reads, warm_us = [], []
        for _ in range(warm_iters):
            with count_reads() as w:
                t0 = time.perf_counter()
                snap.rescan(dirty={dirty_bdf})
                warm_us.append((time.perf_counter() - t0) * 1e6)
            warm_reads.append(w.reads)
        cold_p50_us, cold_p99_us = _p50_p99(cold_us)
        warm_p50_us, warm_p99_us = _p50_p99(warm_us)
        cold_n = int(statistics.median(cold_reads))
        warm_n = int(statistics.median(warm_reads))
        return {
            "n_devices": n_devices,
            "n_partitions": n_partitions,
            "chips_discovered": len(registry.all_devices()),
            "cold_reads": cold_n,
            "warm_reads_p50": warm_n,
            "read_ratio": round(cold_n / max(1, warm_n), 1),
            "cold_p50_us": cold_p50_us, "cold_p99_us": cold_p99_us,
            "warm_p50_us": warm_p50_us, "warm_p99_us": warm_p99_us,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _flip_storm(n_flips=100, settle_s=5.0):
    """Drive a 100-flip health storm into a served plugin and count what a
    kubelet on the ListAndWatch stream actually receives.

    Asserted facts recorded in the row: re-send count after coalescing
    (acceptance: <= 5), final stream state == the device table's ground
    truth (coalescing must never eat the last transition), and the
    reconcile-to-stream latency from the storm's last flip to the stream
    response that matched ground truth.
    """
    root = tempfile.mkdtemp(prefix="tdpstorm-")
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devices = registry.devices_by_model["0063"]
        plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                 torus_dims=generations["0063"].host_topology)
        server = _serve(plugin)
        responses = []          # (t, {device_id: health})
        first = threading.Event()

        def consume():
            with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
                try:
                    for resp in api.DevicePluginStub(ch).ListAndWatch(
                            pb.Empty()):
                        responses.append(
                            (time.perf_counter(),
                             {d.ID: d.health for d in resp.devices}))
                        first.set()
                except grpc.RpcError:
                    pass  # server stopped: stream ends

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert first.wait(timeout=10), "initial ListAndWatch snapshot missing"
        groups = sorted({d.iommu_group for d in devices})
        for i in range(n_flips):
            plugin.set_group_health(groups[i % len(groups)],
                                    healthy=(i % 2 == 0), source="storm")
        storm_end = time.perf_counter()
        truth = plugin.status_snapshot()["devices"]
        deadline = time.monotonic() + settle_s
        matched_at = None
        while time.monotonic() < deadline:
            if responses and responses[-1][1] == truth:
                matched_at = responses[-1][0]
                break
            time.sleep(0.005)
        server.stop(0).wait()
        t.join(timeout=5)
        resends = len(responses) - 1
        return {
            "flips": n_flips,
            "debounce_ms": cfg.lw_debounce_s * 1e3,
            "resends": resends,
            "final_state_matches": matched_at is not None,
            "reconcile_to_stream_ms":
                round((matched_at - storm_end) * 1e3, 2)
                if matched_at is not None else None,
            "unhealthy_in_final": sorted(
                k for k, v in truth.items() if v != "Healthy"),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_discovery():
    """`bench.py --discovery`: incremental-rescan + churn-coalescing bench.

    Matrix: {8, 64, 256} devices x {0, 128} partitions, cold full scan vs
    warm dirty-set rescan (read counts + wall), plus the 100-flip
    ListAndWatch storm. Writes docs/bench_discovery_r06.json and prints the
    one-line headline JSON (read-ratio criterion at 64 devices).
    """
    cells = []
    for n in (8, 64, 256):
        for n_parts in (0, 128):
            cell = _discovery_cell(n, n_parts)
            cells.append(cell)
            print(f"  {n:3d} chips {n_parts:3d} partitions: cold "
                  f"{cell['cold_reads']:4d} reads {cell['cold_p50_us']:8.1f} us"
                  f" | warm {cell['warm_reads_p50']:3d} reads "
                  f"{cell['warm_p50_us']:7.1f} us | ratio "
                  f"{cell['read_ratio']:.0f}x", file=sys.stderr)
    storm = _flip_storm()
    print(f"  storm: {storm['flips']} flips -> {storm['resends']} re-sends, "
          f"final state matched={storm['final_state_matches']}, reconcile "
          f"{storm['reconcile_to_stream_ms']} ms", file=sys.stderr)
    matrix = {"cells": cells, "flip_storm": storm}
    out_path = os.environ.get("BENCH_DISCOVERY_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "bench_discovery_r06.json")
    with open(out_path, "w") as f:
        json.dump(matrix, f, indent=1)
    key = next(c for c in cells
               if c["n_devices"] == 64 and c["n_partitions"] == 0)
    return {
        "metric": "discovery_warm_vs_cold_read_ratio_64dev",
        "value": key["read_ratio"],
        "unit": "x",
        # acceptance floor: warm dirty-set rescan at 64 devices must cost
        # at least 5x fewer sysfs reads than the cold full scan
        "vs_baseline": round(key["read_ratio"] / 5.0, 3),
        "baseline_source": "ISSUE 2 acceptance floor: 5x fewer sysfs reads "
                           "(counted, load-insensitive) for the warm "
                           "dirty-set rescan at 64 devices",
        "cold_reads_64dev": key["cold_reads"],
        "warm_reads_p50_64dev": key["warm_reads_p50"],
        "cold_p50_us_64dev": key["cold_p50_us"],
        "warm_p50_us_64dev": key["warm_p50_us"],
        "storm_resends": storm["resends"],
        "storm_final_state_matches": storm["final_state_matches"],
        "storm_reconcile_to_stream_ms": storm["reconcile_to_stream_ms"],
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def _health_cell(n_devices, slow_chips, deadline_s=0.25, workers=8,
                 cycles=3, slow_hang_s=1.0):
    """One shared-health-plane matrix point.

    Builds a hub with one subscription per 8 devices (mirroring one plugin
    server per resource), real watched socket/node files (so the inotify-fd
    gauge measures the production shape), and a probe where `slow_chips`
    chips hang their config-space read for `slow_hang_s`. The headline per
    cell is the probe-cycle WALL vs the per-cycle deadline — with the old
    serial loop the cycle would cost slow_chips x slow_hang_s.
    """
    from tpu_device_plugin.healthhub import HealthHub, HubSubscription

    root = tempfile.mkdtemp(prefix=f"tdphlt{n_devices}-")
    try:
        vfio = os.path.join(root, "dev", "vfio")
        sockdir = os.path.join(root, "plugins")
        os.makedirs(vfio)
        os.makedirs(sockdir)
        n_resources = max(1, n_devices // 8)
        # slow chips sit mid-fleet, not first, so submission order cannot
        # accidentally front-load the hang
        slow = {f"bdf-{n_devices // 2 + i}" for i in range(slow_chips)}

        def probe(bdf, node):
            if bdf in slow:
                time.sleep(slow_hang_s)
            return True

        hub = HealthHub(poll_interval_s=3600.0, probe_workers=workers,
                        probe_deadline_s=deadline_s)
        idx = 0
        per_res = n_devices // n_resources
        for r in range(n_resources):
            sock = os.path.join(sockdir, f"r{r}.sock")
            open(sock, "w").close()
            paths, bdfs = {}, {}
            for _ in range(per_res):
                node = os.path.join(vfio, str(idx))
                open(node, "w").close()
                paths[f"g{idx}"] = node
                bdfs[f"g{idx}"] = [f"bdf-{idx}"]
                idx += 1
            hub.subscribe(HubSubscription(
                name=f"r{r}", socket_path=sock,
                on_socket_removed=lambda: None,
                group_paths=paths, group_bdfs=bdfs,
                on_device_health=lambda *a: None, probe=probe))
        walls = []
        for _ in range(cycles):
            t0 = time.perf_counter()
            hub.probe_cycle()
            walls.append((time.perf_counter() - t0) * 1e3)
            if slow_chips:
                # let the hung workers drain so each sample starts with a
                # full pool (steady state between 5 s poll ticks)
                time.sleep(slow_hang_s + 0.1)
        stats = hub.stats()
        hub.stop()
        return {
            "n_devices": n_devices,
            "n_resources": n_resources,
            "slow_chips": slow_chips,
            "slow_hang_ms": round(slow_hang_s * 1e3, 1),
            "deadline_ms": round(deadline_s * 1e3, 1),
            "probe_workers": workers,
            "cycle_wall_ms_p50": round(statistics.median(walls), 2),
            "cycle_wall_ms_max": round(max(walls), 2),
            # what the old per-server serial loop would have paid for the
            # same cycle: every slow chip's full hang, back to back
            "serial_sum_est_ms": round(slow_chips * slow_hang_s * 1e3
                                       + statistics.median(walls)
                                       * (0 if slow_chips else 1), 2),
            "probe_timeouts": stats["probe_timeouts_total"],
            "inotify_fds": stats["inotify_fds"],
            "hub_threads": stats["threads"],
            # the replaced shape: one monitor thread + one inotify fd PER
            # resource
            "legacy_threads": n_resources,
            "legacy_inotify_fds": n_resources,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_health():
    """`bench.py --health`: shared-health-plane bench (make bench-health).

    Matrix: {8, 64, 256} devices x {0, 1} injected-slow chips — probe-cycle
    wall vs the per-cycle deadline — plus the inotify-fd/thread gauges vs
    resource count. Writes docs/bench_health_r07.json and prints the
    one-line headline (deadline-bounded cycle at 64 devices + 1 slow chip;
    exactly one fd at 8 vs 256 resources).
    """
    deadline_s = 0.25
    cells = []
    for n in (8, 64, 256):
        for slow_chips in (0, 1):
            cell = _health_cell(n, slow_chips, deadline_s=deadline_s)
            cells.append(cell)
            print(f"  {n:3d} devices ({cell['n_resources']:2d} resources) "
                  f"{slow_chips} slow: cycle p50 "
                  f"{cell['cycle_wall_ms_p50']:7.2f} ms (deadline "
                  f"{cell['deadline_ms']:.0f} ms, serial est "
                  f"{cell['serial_sum_est_ms']:7.2f} ms) | fds "
                  f"{cell['inotify_fds']} (was {cell['legacy_inotify_fds']})"
                  f" | threads {cell['hub_threads']} "
                  f"(was {cell['legacy_threads']})", file=sys.stderr)
    matrix = {"deadline_ms": deadline_s * 1e3, "cells": cells}
    out_path = os.environ.get("BENCH_HEALTH_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "bench_health_r07.json")
    with open(out_path, "w") as f:
        json.dump(matrix, f, indent=1)
    key = next(c for c in cells
               if c["n_devices"] == 64 and c["slow_chips"] == 1)
    fd8 = next(c for c in cells
               if c["n_devices"] == 8 and c["slow_chips"] == 0)
    fd256 = next(c for c in cells
                 if c["n_devices"] == 256 and c["slow_chips"] == 0)
    # acceptance: the 1-slow-chip cycle is bounded by deadline + epsilon
    # (pool handoff + fast probes), NOT the 1 s x chips serial sum
    eps_ms = 250.0
    bounded = key["cycle_wall_ms_p50"] <= key["deadline_ms"] + eps_ms
    return {
        "metric": "health_probe_cycle_wall_64dev_1slow_ms",
        "value": key["cycle_wall_ms_p50"],
        "unit": "ms",
        # >1.0 means the deduped parallel cycle beat the serial-loop
        # estimate for the same fleet + fault
        "vs_baseline": round(key["serial_sum_est_ms"]
                             / max(0.001, key["cycle_wall_ms_p50"]), 3),
        "baseline_source": "serial per-server probe loop estimate for the "
                           "same cycle (1 slow chip x 1000 ms hang, "
                           "health.py:_run_probes before the hub)",
        "deadline_ms": key["deadline_ms"],
        "deadline_bounded": bounded,
        "probe_timeouts": key["probe_timeouts"],
        "inotify_fds_8dev": fd8["inotify_fds"],
        "inotify_fds_256dev": fd256["inotify_fds"],
        "hub_threads_256dev": fd256["hub_threads"],
        "legacy_threads_256dev": fd256["legacy_threads"],
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def _attach_burst_cell(driver, apiserver, names, k, rounds=5, workers=None):
    """One burst point: K claims prepared CONCURRENTLY (one multi-claim
    NodePrepareResources, fanned out on the driver's prepare pool), then
    unprepared the same way. Headline facts per cell: burst wall, per-claim
    throughput, and the COUNTED checkpoint writes the burst cost (the
    group-commit win is load-insensitive: writes are counted, not timed)."""
    from tpu_device_plugin.kubeletapi import drapb

    walls_ms, unprep_walls_ms, writes, coalesced = [], [], [], []
    for r in range(rounds):
        uids = [f"burst-{k}-{r}-{i}" for i in range(k)]
        for i, uid in enumerate(uids):
            apiserver.add_claim("bench", uid, uid, driver.driver_name,
                                [{"device": names[i % len(names)]}])
        claims = [drapb.Claim(namespace="bench", name=uid, uid=uid)
                  for uid in uids]
        c0 = driver.checkpoint_stats()
        t0 = time.perf_counter()
        resp = driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=claims), None)
        t1 = time.perf_counter()
        for uid in uids:
            assert resp.claims[uid].error == "", resp.claims[uid].error
        c1 = driver.checkpoint_stats()
        t2 = time.perf_counter()
        driver.NodeUnprepareResources(
            drapb.NodeUnprepareResourcesRequest(claims=claims), None)
        t3 = time.perf_counter()
        walls_ms.append((t1 - t0) * 1e3)
        unprep_walls_ms.append((t3 - t2) * 1e3)
        writes.append(c1["checkpoint_commits_total"]
                      - c0["checkpoint_commits_total"])
        coalesced.append(c1["checkpoint_claims_coalesced_total"]
                         - c0["checkpoint_claims_coalesced_total"])
    wall_ms = statistics.median(walls_ms)
    return {
        "k_claims": k,
        "prepare_workers": workers or driver.prepare_workers,
        "burst_wall_ms_p50": round(wall_ms, 2),
        "burst_wall_ms_max": round(max(walls_ms), 2),
        "unprepare_wall_ms_p50": round(statistics.median(unprep_walls_ms), 2),
        "throughput_claims_per_s": round(k / (wall_ms / 1e3), 1),
        "checkpoint_writes_p50": int(statistics.median(writes)),
        "checkpoint_writes_max": max(writes),
        "claims_coalesced_p50": int(statistics.median(coalesced)),
    }


def _calibrate_syscalls(root, rounds=300):
    """Per-syscall p50 cost of exactly the calls the attach path makes,
    measured against the same tree in the same run. The TOCTOU
    revalidation is LIVE sysfs I/O by design, so its syscall floor is an
    ENVIRONMENT property (native kernel: <1 us/call, the BENCH_r05
    recording env; gVisor-style sandboxes: ~15-25 us/call) — separating
    it out is what makes the daemon-overhead number comparable across
    environments. The fixture lives at the same tree depth as the pci
    device attributes (path-resolution cost scales with component count
    in emulated kernels), so the floor is representative, not flattered."""
    import statistics as st
    d = os.path.join(root, "sys", "bus", "pci", "devices", "_cal")
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, "f")
    with open(p, "w") as f:
        f.write("0x1ae0\n")
    link = os.path.join(d, "l")
    if not os.path.islink(link):
        os.symlink(p, link)
    fd = os.open(p, os.O_RDONLY)
    try:
        costs = {}
        for name, fn in (("stat", lambda: os.stat(p)),
                         ("readlink", lambda: os.readlink(link)),
                         ("pread", lambda: os.pread(fd, 256, 0)),
                         ("fstat", lambda: os.fstat(fd)),
                         ("listdir", lambda: os.listdir(d))):
            ts = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                fn()
                ts.append((time.perf_counter() - t0) * 1e6)
            costs[name] = round(st.median(ts), 2)
        return costs
    finally:
        os.close(fd)


def _count_attach_syscalls(attach_fn):
    """Exact os.* syscall counts for ONE attach, via counting wrappers
    (bench-only instrumentation; counted, so load-insensitive)."""
    counts = {"stat": 0, "readlink": 0, "pread": 0, "fstat": 0,
              "listdir": 0}
    real = {name: getattr(os, name) for name in counts}

    def wrap(name):
        fn = real[name]

        def counted(*a, **kw):
            counts[name] += 1
            return fn(*a, **kw)
        return counted

    for name in counts:
        setattr(os, name, wrap(name))
    try:
        attach_fn()
    finally:
        for name, fn in real.items():
            setattr(os, name, fn)
    return counts


def run_attach(quick=False):
    """`bench.py --attach` (r09): the epoch read-plane attach breakdown.

    BENCH_r05's 761.9 us attach "wall" was the 2-RPC gRPC estimator: two
    unix-socket round trips whose cost is transport + scheduler hand-off,
    with only 38.4 us of it handler compute. This bench separates the
    parts so the epoch refactor's win is attributable:

      - `wall_p50_us` (HEADLINE): the daemon-side attach critical path —
        GetPreferredAllocation (cold memo; the kubelet's availability set
        changes between allocations) + Allocate, direct servicer calls,
        per-attach wall. Post-epoch the only components are handler
        compute and the LIVE TOCTOU sysfs I/O: the sync/queue component
        is GONE (readers take zero registered locks).
      - `sysfs_io_floor_p50_us`: counted attach syscalls x in-run
        calibrated per-syscall cost — the irreducible live-revalidation
        I/O, an ENVIRONMENT property (sub-us native, ~20 us/call in
        sandboxed kernels). `daemon_overhead_p50_us` = wall - floor is
        the environment-comparable number the <200 us target pins.
      - `contended_wall_p50_us`: the same path with 4 concurrent client
        threads — queue/sync hand-off the daemon imposes beyond serial
        execution (pre-epoch this included lock convoys; now only GIL
        time-slicing of compute + I/O).
      - `transport_wall_p50_us`: the r05-comparable 2-RPC gRPC number,
        reported for continuity; it is transport-bound, not lock-bound,
        and the epoch refactor does not claim it.
      - `lock_acquisitions_per_attach`: COUNTED under lockdep.scoped()
        (load-insensitive) — 0, vs 11 measured on the pre-epoch tree
        (fragment lock x4, vendor-reader lock x4, device-table condition
        x2, memo lock x1; recorded in docs/perf.md).

    Writes docs/bench_attach_r09.json ($BENCH_ATTACH_PATH_OUT overrides;
    --quick cuts iterations for the CI smoke job, whose guards are the
    counted ones — timing pins run against the committed JSON).
    """
    from tpu_device_plugin import lockdep

    iters_grpc = 80 if quick else ITERATIONS
    warm_grpc = 10 if quick else WARMUP
    iters = 400 if quick else 2000
    warm = 40 if quick else 100
    root = tempfile.mkdtemp(prefix="tdpattachpath-")
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devices = registry.devices_by_model["0063"]
        torus = generations["0063"].host_topology
        plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                 torus_dims=torus)
        all_ids = [d.bdf for d in devices]
        pref_req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=all_ids, allocation_size=4)])

        # transport phase: the kubelet-visible 2-RPC gRPC path (r05's
        # estimator), for continuity
        server = _serve(plugin, workers=4)
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            _, transport_us = _attach_path(stub, all_ids, 4,
                                           iters_grpc, warm_grpc)
        server.stop(0)

        def attach_once(plg, req):
            """One daemon-side attach: timed pref (cold memo) + timed
            alloc; request construction excluded (same composition as the
            r05 handler-compute estimator, so the numbers compare)."""
            plg._pref_cache.clear()
            t0 = time.perf_counter()
            pref = plg.GetPreferredAllocation(req, None)
            t1 = time.perf_counter()
            alloc_req = pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(
                    devices_ids=list(pref.container_responses[0].deviceIDs))])
            t2 = time.perf_counter()
            resp = plg.Allocate(alloc_req, None)
            t3 = time.perf_counter()
            assert len(resp.container_responses[0].devices) >= 5
            return (t1 - t0) + (t3 - t2), (t1 - t0), (t3 - t2)

        single_us, pref_us, alloc_us = [], [], []
        for i in range(iters + warm):
            wall, p, a = attach_once(plugin, pref_req)
            if i >= warm:
                single_us.append(wall * 1e6)
                pref_us.append(p * 1e6)
                alloc_us.append(a * 1e6)

        # contended phase: 4 client threads, per-attach wall under
        # concurrency — the daemon-imposed queue/sync cost
        n_threads = 4
        per_thread = max(50, iters // n_threads)
        contended_us = [[] for _ in range(n_threads)]
        barrier = threading.Barrier(n_threads)

        def client(out):
            req = pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=all_ids, allocation_size=4)])
            barrier.wait()
            for i in range(per_thread + warm // n_threads):
                wall, _, _ = attach_once(plugin, req)
                if i >= warm // n_threads:
                    out.append(wall * 1e6)

        threads = [threading.Thread(target=client, args=(contended_us[i],))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        contended_all = [x for out in contended_us for x in out]

        # lock accounting: counted, load-insensitive — a fresh plugin
        # built under lockdep.scoped() gets recording proxies; steady-
        # state path counters must be zero
        n_counted = 50
        with lockdep.scoped():
            plg2 = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                   torus_dims=torus)
            attach_once(plg2, pref_req)          # warm the slow paths
            plg2.status_snapshot()
            plg2._lw_response(plg2._store.current)
            lockdep.reset()
            for _ in range(n_counted):
                attach_once(plg2, pref_req)
                plg2.status_snapshot()
                plg2._lw_response(plg2._store.current)
            path_stats = lockdep.path_stats()
        attach_acqs = sum(
            rec["lock_acquisitions"] for name, rec in path_stats.items())
        locks_per_attach = attach_acqs / n_counted

        # sysfs I/O floor: exact syscall counts for one steady-state
        # attach x in-run per-syscall calibration
        syscalls = _count_attach_syscalls(
            lambda: attach_once(plugin, pref_req))
        cal = _calibrate_syscalls(root)
        floor_us = sum(syscalls[name] * cal[name] for name in syscalls)

        wall_p50 = statistics.median(single_us)
        contended_p50 = statistics.median(contended_all)
        daemon_overhead = wall_p50 - floor_us
        out = {
            "metric": "attach_wall_p50_us",
            "value": round(wall_p50, 1),
            "unit": "us",
            # r05's 761.9 us wall was the 2-RPC gRPC estimator; its
            # non-compute component (transport + hand-offs + locks) is
            # what this refactor attacks on the daemon side. The
            # transport-only figure is reported alongside unclaimed.
            "vs_baseline": round(761.9 / wall_p50, 3),
            "baseline_source": (
                "BENCH_r05 wall_p50_us 761.9 (2-RPC gRPC estimator). r09 "
                "re-bases the wall to the daemon-side attach critical "
                "path (direct servicer calls, cold preferred-allocation "
                "memo + Allocate): with epochs the daemon adds ZERO lock "
                "wait — what remains is handler compute plus the LIVE "
                "TOCTOU sysfs I/O floor, which is an environment "
                "property (see syscall_cost_calibration_us: ~20 us/call "
                "in this sandboxed kernel vs <1 us native where r05's "
                "38.4 us handler figure was recorded). "
                "daemon_overhead_p50_us is the environment-comparable "
                "number; gRPC transport is reported as "
                "transport_wall_p50_us and not claimed by this PR"),
            "handler_compute_p50_us": round(
                statistics.median(pref_us) + statistics.median(alloc_us), 1),
            "pref_cold_p50_us": round(statistics.median(pref_us), 1),
            "allocate_p50_us": round(statistics.median(alloc_us), 1),
            "wall_p99_us": round(
                statistics.quantiles(single_us, n=100)[98], 1),
            # the lock-wait/queue vs I/O vs compute attribution
            "sysfs_syscalls_per_attach": syscalls,
            "sysfs_syscalls_per_attach_total": sum(syscalls.values()),
            "syscall_cost_calibration_us": cal,
            "sysfs_io_floor_p50_us": round(floor_us, 1),
            "daemon_overhead_p50_us": round(daemon_overhead, 1),
            "contended_clients": n_threads,
            "contended_wall_p50_us": round(contended_p50, 1),
            "contended_wall_p99_us": round(
                statistics.quantiles(contended_all, n=100)[98], 1),
            # queue/sync the daemon adds under 4-way contention beyond
            # pure serialization of compute + I/O (pre-epoch: lock
            # convoys; now ~GIL hand-off only)
            "queue_sync_overhead_p50_us": round(
                contended_p50 - n_threads * wall_p50, 1),
            "transport_wall_p50_us": round(
                statistics.median(transport_us), 1),
            "transport_wall_p99_us": round(
                statistics.quantiles(transport_us, n=100)[98], 1),
            # counted (load-insensitive): registered-lock acquisitions
            # per steady-state attach, and per-path detail
            "lock_acquisitions_per_attach": locks_per_attach,
            "lock_acquisitions_per_attach_r05": 11,
            "lock_path_stats": path_stats,
            "devices_advertised": len(devices),
            "allocation_size": 4,
            "iterations": iters,
            "quick": quick,
        }
        out_path = os.environ.get("BENCH_ATTACH_PATH_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "bench_attach_r09.json")
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        out["matrix_file"] = os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__)))
        print(f"  attach wall p50 {out['value']:7.1f} us = sysfs I/O floor "
              f"{out['sysfs_io_floor_p50_us']:.1f} us "
              f"({out['sysfs_syscalls_per_attach_total']} syscalls @ "
              f"~{cal['stat']:.0f} us) + daemon overhead "
              f"{out['daemon_overhead_p50_us']:.1f} us | contended x4 "
              f"{out['contended_wall_p50_us']:7.1f} us (queue/sync "
              f"{out['queue_sync_overhead_p50_us']:+.1f} us) | transport "
              f"{out['transport_wall_p50_us']:7.1f} us | locks/attach "
              f"{locks_per_attach:g} (r05: 11)", file=sys.stderr)
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_trace_overhead(quick=False):
    """`bench.py --trace-overhead` (r10): the flight recorder's cost on
    the attach path — the honesty guard for "always-on, low-overhead"
    (docs/observability.md).

    Two kinds of numbers, matching the r09 discipline of pinning what is
    COUNTED and recording what is timed:

      - COUNTED (load-insensitive): trace records produced by one
        steady-state attach — exactly 2 spans (GetPreferredAllocation +
        Allocate), 0 events (fragment rebuilds are cold-path only).
        tests/test_perf_honesty.py re-counts this live.
      - TIMED (recorded in the artifact, pinned against the committed
        file): per-attach wall with tracing ENABLED vs DISABLED,
        interleaved A/B per iteration so co-tenant load drift hits both
        arms equally. overhead = traced_p50 - untraced_p50.

    Writes docs/bench_attach_r10.json ($BENCH_TRACE_OUT overrides).
    """
    from tpu_device_plugin import trace

    iters = 400 if quick else 2000
    warm = 40 if quick else 100
    root = tempfile.mkdtemp(prefix="tdptrace-")
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devices = registry.devices_by_model["0063"]
        plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                 torus_dims=generations["0063"].host_topology)
        all_ids = [d.bdf for d in devices]
        pref_req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=all_ids, allocation_size=4)])

        def attach_once():
            # same composition as run_attach's estimator: cold pref memo
            # + Allocate, direct servicer calls
            plugin._pref_cache.clear()
            t0 = time.perf_counter()
            pref = plugin.GetPreferredAllocation(pref_req, None)
            alloc_req = pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(
                    devices_ids=list(pref.container_responses[0].deviceIDs))])
            plugin.Allocate(alloc_req, None)
            return time.perf_counter() - t0

        # counted: records per steady-state attach
        for _ in range(3):
            attach_once()                     # warm slow paths (fragments)
        trace.reset()
        before = trace.stats()
        attach_once()
        after = trace.stats()
        spans_per_attach = (after["spans_recorded_total"]
                            - before["spans_recorded_total"])
        events_per_attach = (after["events_recorded_total"]
                             - before["events_recorded_total"])

        # timed: interleaved A/B
        traced_us, untraced_us = [], []
        for i in range(iters + warm):
            trace.configure(enabled=True)
            t_on = attach_once() * 1e6
            trace.configure(enabled=False)
            t_off = attach_once() * 1e6
            if i >= warm:
                traced_us.append(t_on)
                untraced_us.append(t_off)
        trace.configure(enabled=True)

        traced_p50 = statistics.median(traced_us)
        untraced_p50 = statistics.median(untraced_us)
        overhead = traced_p50 - untraced_p50
        out = {
            "metric": "trace_overhead_per_attach_us",
            "value": round(overhead, 2),
            "unit": "us",
            "baseline_source": (
                "untraced same-run interleaved A/B median; spans counted "
                "per attach are the load-insensitive pin (3 since r13: "
                "GetPreferredAllocation + Allocate + the broker.ipc "
                "crossing of the batched TOCTOU revalidation — every "
                "privilege crossing is traceable by design; 0 events "
                "warm). Since r17 every span also mints/inherits its "
                "W3C trace context (per-thread RNG ids, zero locks) — "
                "the propagation plane is LIVE in this measurement. "
                "The documented bound the honesty guard enforces: "
                "recorded overhead <= 35 us AND <= 10% of the untraced "
                "wall (in this sandboxed kernel, "
                "where a monotonic read costs what a native syscall "
                "does; observed 19-32 us / 4-8% across recordings, "
                "swinging with co-tenant load)"),
            "trace_spans_per_attach": spans_per_attach,
            "trace_events_per_attach": events_per_attach,
            "traced_wall_p50_us": round(traced_p50, 1),
            "untraced_wall_p50_us": round(untraced_p50, 1),
            "overhead_pct": round(100.0 * overhead / untraced_p50, 2),
            "ring_size": trace.stats()["ring_size"],
            "devices_advertised": len(devices),
            "allocation_size": 4,
            "iterations": iters,
            "quick": quick,
        }
        out_path = os.environ.get("BENCH_TRACE_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "bench_attach_r10.json")
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        out["matrix_file"] = os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__)))
        print(f"  trace overhead/attach {out['value']:+6.2f} us "
              f"({out['overhead_pct']:+.2f}%): traced p50 "
              f"{traced_p50:7.1f} us vs untraced {untraced_p50:7.1f} us | "
              f"records/attach {spans_per_attach} spans + "
              f"{events_per_attach} events", file=sys.stderr)
        return out
    finally:
        trace.reset()
        shutil.rmtree(root, ignore_errors=True)


def _measure_sched_wakeup(rounds=300):
    """Measured cross-thread scheduler-wakeup cost: an Event ping-pong
    between two threads, half a round trip per handoff. This is the
    queueing/wakeup floor a gRPC unary RPC pays at least twice (request
    handoff to a server worker, response handoff back) — measured in-run,
    not estimated, because it is an environment property exactly like the
    r09 syscall floor."""
    ev_req, ev_resp = threading.Event(), threading.Event()
    stop = [False]

    def responder():
        while True:
            ev_req.wait()
            ev_req.clear()
            if stop[0]:
                return
            ev_resp.set()

    t = threading.Thread(target=responder, daemon=True,
                         name="bench-wakeup-responder")
    t.start()
    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        ev_req.set()
        ev_resp.wait()
        ev_resp.clear()
        samples.append((time.perf_counter() - t0) * 1e6 / 2)
    stop[0] = True
    ev_req.set()
    t.join(timeout=2)
    return statistics.median(samples)


def run_transport(quick=False):
    """`bench.py --transport` (r15): the attach RPC transport endgame.

    r09 made the daemon's attach compute lock-free and attributed the
    remaining wall to handler compute + the live TOCTOU sysfs floor; the
    gRPC transport + protobuf serialization + queueing were reported
    UNCLAIMED. r15 attacks the serving side (pre-serialized epoch-keyed
    response bytes, RawResponse passthrough serializers, loopback channel
    tuning) and decomposes what remains — each component MEASURED in-run:

      - `wall_p50_us`: daemon-side attach critical path with the byte
        plane live (cold preferred memo + Allocate, handlers driven with
        RAW_CONTEXT so they produce exactly the wire bytes the transport
        serializer forwards untouched).
      - `sysfs_io_floor_p50_us`: counted attach syscalls x in-run
        calibrated per-syscall cost (r09 methodology, unchanged by this
        round — the TOCTOU guard stays live by design).
      - HEADLINE `value` = wall - floor: the environment-calibrated wall
        the < 200 us acceptance pin guards (raw wall in this sandboxed
        kernel is dominated by ~20-30 us syscalls that cost <1 us on the
        native kernel BENCH_r05 recorded).
      - serialization: interleaved A/B per iteration — the PRE-PR path
        (build response protos per call + the SerializeToString the
        transport then paid) vs the byte plane (the live handlers,
        including their span/lockdep overhead — the comparison is biased
        AGAINST the byte plane, which makes the win honest).
      - queueing/scheduler wakeup: measured Event ping-pong handoff
        (half a round trip), the floor a unary RPC pays >= 2x.
      - gRPC framing: measured no-op RPC (GetDevicePluginOptions — empty
        request, 2-field response) over the tuned loopback channel;
        `grpc_framing_p50_us` = noop RTT - 2 x wakeup is the only
        DERIVED number, and it is arithmetic on two measured ones.
      - `transport_wall_p50_us`: the r05-comparable 2-RPC gRPC wall with
        the byte plane + RawResponse passthrough + tuned channel live,
        and the residual it leaves unattributed.
      - COUNTED (load-insensitive, the CI pins): bytes-reused and
        serializations per WARM attach — 2 reused, 0 serializations, or
        the byte plane is not actually serving bytes.

    Writes docs/bench_transport_r15.json ($BENCH_TRANSPORT_OUT overrides).
    """
    iters = 400 if quick else 2000
    warm = 40 if quick else 100
    iters_grpc = 80 if quick else ITERATIONS
    warm_grpc = 10 if quick else WARMUP
    root = tempfile.mkdtemp(prefix="tdptransport-")
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devices = registry.devices_by_model["0063"]
        torus = generations["0063"].host_topology
        plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                 torus_dims=torus)
        all_ids = [d.bdf for d in devices]
        pref_req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=all_ids, allocation_size=4)])
        RAW = api.RAW_CONTEXT

        def attach_bytes_once():
            """One serving-side attach on the byte plane: the handlers
            produce the exact wire payloads (RawResponse) the passthrough
            serializer forwards; the client-side parse between the two
            RPCs is excluded from the timed windows (the kubelet pays it,
            not the daemon)."""
            plugin._pref_cache.clear()
            t0 = time.perf_counter()
            pref_raw = plugin.GetPreferredAllocation(pref_req, RAW)
            t1 = time.perf_counter()
            picked = list(pb.PreferredAllocationResponse.FromString(
                pref_raw.data).container_responses[0].deviceIDs)
            alloc_req = pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=picked)])
            t2 = time.perf_counter()
            alloc_raw = plugin.Allocate(alloc_req, RAW)
            t3 = time.perf_counter()
            assert len(alloc_raw.data) > 50
            return (t1 - t0) + (t3 - t2), (t1 - t0), (t3 - t2)

        # The A/B twin: byte_plane=False routes the SAME handlers (same
        # spans, same read-path brackets, same TOCTOU revalidation)
        # through the pre-PR build-protos-per-call path; the explicit
        # SerializeToString is what the transport serializer then paid.
        # Only the serialization strategy differs between the arms.
        plugin_reser = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                       torus_dims=torus, byte_plane=False)

        def attach_reser_once():
            plugin_reser._pref_cache.clear()
            t0 = time.perf_counter()
            pref = plugin_reser.GetPreferredAllocation(pref_req, None)
            pref_bytes = pref.SerializeToString()
            t1 = time.perf_counter()
            alloc_req = pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=list(
                    pref.container_responses[0].deviceIDs))])
            t2 = time.perf_counter()
            aresp = plugin_reser.Allocate(alloc_req, None)
            alloc_bytes = aresp.SerializeToString()
            t3 = time.perf_counter()
            assert pref_bytes and len(alloc_bytes) > 50
            return (t1 - t0) + (t3 - t2)

        # exact syscall counts for one steady-state attach (counted —
        # load-insensitive; the floor multiplies these by the adjacent
        # per-epoch calibration below)
        for _ in range(3):
            attach_bytes_once()                      # warm slow paths
        syscalls = _count_attach_syscalls(lambda: attach_bytes_once())

        # interleaved A/B + INTERLEAVED floor calibration: one sample of
        # each attach-path syscall is taken per iteration, against the
        # same tree, BETWEEN the timed attaches — so the wall medians and
        # the per-syscall calibration medians see the exact same
        # co-tenant load distribution. A floor calibrated in its own
        # block minutes away mispairs by 100+ us run-to-run on this
        # shared core (a spike inside a short calibration block can even
        # push the paired difference negative); time-interleaved medians
        # subtract meaningfully.
        for _ in range(warm):
            attach_bytes_once()
            attach_reser_once()
        cal_dir = os.path.join(root, "sys", "bus", "pci", "devices",
                               "_cal")
        os.makedirs(cal_dir, exist_ok=True)
        cal_file = os.path.join(cal_dir, "f")
        with open(cal_file, "w") as f:
            f.write("0x1ae0\n")
        cal_link = os.path.join(cal_dir, "l")
        os.symlink(cal_file, cal_link)
        cal_fd = os.open(cal_file, os.O_RDONLY)
        cal_fns = (("stat", lambda: os.stat(cal_file)),
                   ("readlink", lambda: os.readlink(cal_link)),
                   ("pread", lambda: os.pread(cal_fd, 256, 0)),
                   ("fstat", lambda: os.fstat(cal_fd)),
                   ("listdir", lambda: os.listdir(cal_dir)))
        cal_samples = {name: [] for name, _ in cal_fns}
        bytes_us, reser_us, pref_us, alloc_us = [], [], [], []
        try:
            for _i in range(iters):
                wb, p, a = attach_bytes_once()
                wr = attach_reser_once()
                bytes_us.append(wb * 1e6)
                pref_us.append(p * 1e6)
                alloc_us.append(a * 1e6)
                reser_us.append(wr * 1e6)
                for name, fn in cal_fns:
                    t0 = time.perf_counter()
                    fn()
                    cal_samples[name].append(
                        (time.perf_counter() - t0) * 1e6)
        finally:
            os.close(cal_fd)
        cal = {name: round(statistics.median(ts), 2)
               for name, ts in cal_samples.items()}
        floor_us = sum(syscalls[name] * cal[name] for name in syscalls)
        # per-epoch paired differences (recorded for drift visibility,
        # not pinned — the run-median pair is the headline)
        n_epochs = EPOCHS
        per_epoch = len(bytes_us) // n_epochs
        calibrated_per_epoch = []
        for e in range(n_epochs):
            sl = slice(e * per_epoch, (e + 1) * per_epoch)
            floor_e = sum(
                syscalls[name]
                * statistics.median(cal_samples[name][sl])
                for name in syscalls)
            calibrated_per_epoch.append(
                statistics.median(bytes_us[sl]) - floor_e)

        # ISOLATED serialization component (the breakdown's
        # "serialization" number): response CONSTRUCTION only, with the
        # TOCTOU revalidation stubbed to a no-op on two dedicated
        # planners — the live-syscall floor (~12 x 30-50 us in this
        # sandbox, high variance) otherwise swamps the ~tens-of-us
        # serialization delta the A/B exists to measure. Interleaved per
        # iteration like every A/B here; the revalidation is NOT part of
        # either arm by construction, so stubbing it is isolation, not
        # dishonesty (the end-to-end arms above keep it live).
        class _NoReval:
            mode = "inproc"

            def revalidate_batch(self, planner, items):
                return None

        from tpu_device_plugin.allocate import AllocationPlanner
        iso_bytes_planner = AllocationPlanner(
            cfg, registry, "v5e", broker_client=_NoReval())
        iso_reser_planner = AllocationPlanner(
            cfg, registry, "v5e", broker_client=_NoReval(),
            byte_records=False)
        iso_req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devices_ids=all_ids[:4])])
        iso_bytes_planner.allocate_response_bytes(iso_req, epoch=1)  # warm
        iso_reser_planner.allocate_response(iso_req, epoch=1)
        iso_bytes_us, iso_reser_us = [], []
        for i in range(iters + warm):
            t0 = time.perf_counter()
            data = iso_bytes_planner.allocate_response_bytes(iso_req,
                                                             epoch=1)
            t1 = time.perf_counter()
            wire = iso_reser_planner.allocate_response(
                iso_req, epoch=1).SerializeToString()
            t2 = time.perf_counter()
            if i >= warm:
                iso_bytes_us.append((t1 - t0) * 1e6)
                iso_reser_us.append((t2 - t1) * 1e6)
            assert len(data) > 50 and len(wire) > 50

        # warm serving wall: the kubelet re-asking with an unchanged
        # availability set — the full byte-reuse path end to end
        plugin.GetPreferredAllocation(pref_req, RAW)   # prime the memo
        warm_req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devices_ids=all_ids[:4])])
        warm_us = []
        for i in range(iters // 2 + warm // 2):
            t0 = time.perf_counter()
            plugin.GetPreferredAllocation(pref_req, RAW)
            plugin.Allocate(warm_req, RAW)
            if i >= warm // 2:
                warm_us.append((time.perf_counter() - t0) * 1e6)

        # COUNTED: bytes reused / serializations per warm attach
        r0 = plugin._alloc_bytes_reused.value
        s0 = plugin._alloc_serializations.value
        plugin.GetPreferredAllocation(pref_req, RAW)
        plugin.Allocate(warm_req, RAW)
        reused_per_attach = plugin._alloc_bytes_reused.value - r0
        ser_per_attach = plugin._alloc_serializations.value - s0

        # queueing/scheduler-wakeup floor (measured)
        wakeup_us = _measure_sched_wakeup()

        # gRPC phase: no-op RTT + the r05-comparable 2-RPC wall over the
        # tuned loopback channel with the passthrough serializers live
        server = _serve(plugin, workers=4)
        noop_us = []
        with grpc.insecure_channel(
                f"unix://{plugin.socket_path}",
                options=LOOPBACK_GRPC_OPTIONS) as ch:
            stub = api.DevicePluginStub(ch)
            for i in range(iters_grpc + warm_grpc):
                t0 = time.perf_counter()
                stub.GetDevicePluginOptions(pb.Empty(), timeout=5)
                if i >= warm_grpc:
                    noop_us.append((time.perf_counter() - t0) * 1e6)
            _, transport_us = _attach_path(stub, all_ids, 4,
                                           iters_grpc, warm_grpc)
        server.stop(0)

        wall_p50 = statistics.median(bytes_us)
        wall_best = _min_epoch_p50(bytes_us, epochs=n_epochs)
        warm_p50 = statistics.median(warm_us)
        reser_p50 = statistics.median(reser_us)
        noop_p50 = statistics.median(noop_us)
        transport_p50 = statistics.median(transport_us)
        # the PINNED number: run-median wall minus the time-interleaved
        # run-median floor — both halves saw the same load distribution
        calibrated = wall_p50 - floor_us

        # r09's recorded daemon overhead is the like-for-like baseline
        # for the calibrated wall (same estimator composition, same
        # environment-calibration discipline)
        r09_overhead = 86.3
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "docs", "bench_attach_r09.json")) as f:
                r09_overhead = float(
                    json.load(f)["daemon_overhead_p50_us"])
        except (OSError, KeyError, ValueError, TypeError):
            pass
        out = {
            "metric": "attach_wall_calibrated_p50_us",
            "value": round(calibrated, 1),
            "unit": "us",
            "vs_baseline": round(r09_overhead / calibrated, 3)
            if calibrated > 0 else None,
            "baseline_source": (
                "r09 daemon_overhead_p50_us (docs/bench_attach_r09.json: "
                "attach wall minus the counted-syscalls x in-run-"
                "calibrated sysfs floor — the environment-comparable "
                "number). r15 keeps the estimator composition (cold "
                "preferred memo + Allocate, direct servicer calls) but "
                "measures the handlers PRODUCING THE WIRE BYTES "
                "(RAW_CONTEXT — the exact payload the passthrough "
                "serializer forwards), work r09's message-returning "
                "estimator never paid, so the ratio UNDERSTATES the "
                "serving-side win. The <200 us acceptance pin guards "
                "`value` = run-median wall minus the TIME-INTERLEAVED "
                "run-median floor (one sample of each attach syscall "
                "taken between the timed attaches, so both medians see "
                "the identical co-tenant load distribution — a floor "
                "calibrated in its own block mispairs by 100+ us on "
                "this shared core; calibrated_per_epoch_us records the "
                "per-epoch paired drift). The A/B arms run the SAME handler code "
                "interleaved per iteration (byte_plane=False routes the "
                "identical spans/brackets/TOCTOU through the pre-PR "
                "build-protos-per-call + SerializeToString path) — only "
                "the serialization strategy differs; because the live "
                "syscall floor's variance dominates those end-to-end "
                "arms in this sandbox, the PINNED serialization number "
                "is the isolated pair (serialization_*_p50_us: response "
                "construction only, revalidation stubbed on both arms). "
                "transport_wall_p50_us is the r05-comparable 2-RPC gRPC "
                "wall, now with passthrough serializers + loopback "
                "tuning; its queueing and framing components are "
                "measured (sched_wakeup, noop RTT), framing and the "
                "residual are the only derived fields"),
            "wall_p50_us": round(wall_p50, 1),
            "wall_best_epoch_p50_us": round(wall_best, 1),
            "calibrated_per_epoch_us": [round(c, 1)
                                        for c in calibrated_per_epoch],
            "wall_p99_us": round(
                statistics.quantiles(bytes_us, n=100)[98], 1),
            "pref_cold_p50_us": round(statistics.median(pref_us), 1),
            "allocate_p50_us": round(statistics.median(alloc_us), 1),
            "warm_wall_p50_us": round(warm_p50, 1),
            # the r09 floor discipline
            "sysfs_syscalls_per_attach": syscalls,
            "sysfs_syscalls_per_attach_total": sum(syscalls.values()),
            "syscall_cost_calibration_us": cal,
            "sysfs_io_floor_p50_us": round(floor_us, 1),
            # serialization, isolated (the breakdown component + the
            # robust pin: response construction only, revalidation
            # stubbed on BOTH arms — no syscall noise)
            "serialization_reserialize_p50_us": round(
                statistics.median(iso_reser_us), 1),
            "serialization_bytes_p50_us": round(
                statistics.median(iso_bytes_us), 1),
            "serialization_saved_p50_us": round(
                statistics.median(iso_reser_us)
                - statistics.median(iso_bytes_us), 1),
            # serialization, end-to-end (recorded unpinned: the live
            # syscall floor's variance dominates arm-to-arm deltas)
            "ab_reserialize_wall_p50_us": round(reser_p50, 1),
            "ab_bytes_wall_p50_us": round(wall_p50, 1),
            "serialization_p50_us": round(reser_p50 - wall_p50, 1),
            # queueing + framing (measured; framing derived from the two)
            "sched_wakeup_p50_us": round(wakeup_us, 1),
            "grpc_noop_rtt_p50_us": round(noop_p50, 1),
            "grpc_framing_p50_us": round(noop_p50 - 2 * wakeup_us, 1),
            # the kubelet-visible 2-RPC wall and what it leaves over
            "transport_wall_p50_us": round(transport_p50, 1),
            "transport_wall_p99_us": round(
                statistics.quantiles(transport_us, n=100)[98], 1),
            "transport_vs_r05": round(761.9 / transport_p50, 3),
            "transport_unattributed_p50_us": round(
                transport_p50 - 2 * noop_p50 - warm_p50, 1),
            # counted (load-insensitive): the CI pins
            "bytes_reused_per_warm_attach": reused_per_attach,
            "serializations_per_warm_attach": ser_per_attach,
            "devices_advertised": len(devices),
            "allocation_size": 4,
            "iterations": iters,
            "quick": quick,
        }
        out_path = os.environ.get("BENCH_TRANSPORT_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "bench_transport_r15.json")
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        out["matrix_file"] = os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__)))
        print(f"  attach wall p50 {wall_p50:7.1f} us - interleaved floor "
              f"{floor_us:.1f} us = calibrated {calibrated:7.1f} us "
              f"(<200 pin; per-epoch "
              f"{[round(c) for c in calibrated_per_epoch]}) | "
              f"serialization (isolated) "
              f"{out['serialization_reserialize_p50_us']:.1f} -> "
              f"{out['serialization_bytes_p50_us']:.1f} us (saved "
              f"{out['serialization_saved_p50_us']:.1f}) | warm "
              f"{warm_p50:6.1f} us | wakeup {wakeup_us:.1f} us | noop RTT "
              f"{noop_p50:.1f} us | transport {transport_p50:7.1f} us | "
              f"warm attach counted: {reused_per_attach} reused / "
              f"{ser_per_attach} serialized", file=sys.stderr)
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


# RTT injected into the fake apiserver's claim GETs for the attach bench.
# A loopback fake shares this process's GIL and has no network, so the wait
# a REAL in-cluster apiserver round-trip costs — the thing the parallel
# prepare pool overlaps — would be invisible without it (same technique as
# the health bench's injected 1s-slow chip). 5 ms is conservative for an
# in-cluster HTTPS GET (connect + TLS-resumed request + etcd-backed read);
# the serial baseline pays the SAME latency, serially.
ATTACH_APISERVER_RTT_S = 0.005


def run_attach_burst():
    """`bench.py --attach-burst`: concurrent-attach bench (make bench-attach).

    A K∈{1,8,32}-claim concurrent prepare burst (node-recovery storm
    shape) at prepare_workers=8 vs the measured serial baseline — the SAME
    claims on a prepare_workers=1 driver with a zero commit window, i.e.
    the pre-PR shape: K sequential API round trips and one whole-file
    checkpoint write per claim. Both sides pay the same injected apiserver
    RTT (ATTACH_APISERVER_RTT_S). Checkpoint writes are COUNTED per burst
    (load-insensitive). Also records the precompiled-fragment plan cost on
    an iommufd host (counted sysfs reads, warm vs cold). Writes
    docs/bench_attach_r08.json.
    """
    from dataclasses import replace

    from tests.fakehost import FakeChip, FakeHost
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin import allocate as allocate_mod
    from tpu_device_plugin.discovery import discover_passthrough as dp
    from tpu_device_plugin.dra import (CHECKPOINT_COMMIT_WINDOW_S, DraDriver,
                                       slice_device_name)
    from tpu_device_plugin.kubeapi import ApiClient

    root = tempfile.mkdtemp(prefix="tdpattach-")
    apiserver = FakeApiServer()
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devs = next(iter(registry.devices_by_model.values()))
        names = [slice_device_name(d.bdf) for d in devs]
        apiserver.latency_s = ATTACH_APISERVER_RTT_S

        def make_driver(workers, window_s):
            d = DraDriver(
                replace(cfg, prepare_workers=workers), registry, generations,
                node_name="bench-node",
                api=ApiClient(apiserver.url, token_path="/nonexistent"))
            d.checkpoint_commit_window_s = window_s
            return d

        # serial baseline driver: one worker, no coalescing window — each
        # claim pays its own API round trip and its own full-file write,
        # back to back, like the old under-one-lock handler did
        serial_driver = make_driver(1, 0.0)
        serial_cells = {
            k: _attach_burst_cell(serial_driver, apiserver, names, k)
            for k in (1, 8, 32)
        }
        serial_driver.stop()
        burst_driver = make_driver(8, CHECKPOINT_COMMIT_WINDOW_S)
        cells = [
            _attach_burst_cell(burst_driver, apiserver, names, k)
            for k in (1, 8, 32)
        ]
        burst_driver.stop()
        for cell in cells:
            k = cell["k_claims"]
            serial = serial_cells[k]
            cell["serial_wall_ms_p50"] = serial["burst_wall_ms_p50"]
            cell["serial_checkpoint_writes"] = serial["checkpoint_writes_p50"]
            cell["speedup_vs_serial"] = round(
                serial["burst_wall_ms_p50"]
                / max(0.001, cell["burst_wall_ms_p50"]), 2)
            print(f"  burst k={k:2d} @ {cell['prepare_workers']} workers: "
                  f"wall p50 {cell['burst_wall_ms_p50']:7.2f} ms (serial "
                  f"{cell['serial_wall_ms_p50']:7.2f} ms, "
                  f"{cell['speedup_vs_serial']:.1f}x) | "
                  f"{cell['checkpoint_writes_p50']} checkpoint writes "
                  f"(serial paid {cell['serial_checkpoint_writes']}) | "
                  f"{cell['throughput_claims_per_s']:.0f} claims/s",
                  file=sys.stderr)

        # precompiled-fragment plan cost on an iommufd host (the per-member
        # vfio-dev listdirs are the fragment-cacheable sysfs cost; the
        # TOCTOU revalidation reads stay in both plans by design)
        frag_root = tempfile.mkdtemp(prefix="tdpfrag-")
        try:
            fhost = FakeHost(frag_root)
            for i in range(8):
                fhost.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                                        device_id="0063",
                                        iommu_group=str(11 + i),
                                        vfio_dev=f"vfio{i}"))
            fhost.enable_iommufd()
            fcfg = Config().with_root(frag_root)
            fregistry, _ = dp(fcfg)
            planner = allocate_mod.AllocationPlanner(fcfg, fregistry, "v5e")
            bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(8)]
            with allocate_mod.count_plan_reads() as cold_w:
                t0 = time.perf_counter()
                planner.plan(bdfs)
                cold_us = (time.perf_counter() - t0) * 1e6
            with allocate_mod.count_plan_reads() as warm_w:
                t0 = time.perf_counter()
                planner.plan(bdfs)
                warm_us = (time.perf_counter() - t0) * 1e6
            frag_reads = len([p for p in cold_w.paths if "vfio-dev" in p])
            warm_frag_reads = len(
                [p for p in warm_w.paths if "vfio-dev" in p])
            frag = {
                "plan_bdfs": len(bdfs),
                "cold_plan_reads": cold_w.reads,
                "warm_plan_reads": warm_w.reads,
                "cold_fragment_reads": frag_reads,
                "warm_fragment_reads": warm_frag_reads,
                "fragment_read_ratio": round(
                    frag_reads / max(1, warm_frag_reads), 2),
                "cold_plan_us": round(cold_us, 1),
                "warm_plan_us": round(warm_us, 1),
                "fragment_stats": planner.fragment_stats(),
            }
        finally:
            shutil.rmtree(frag_root, ignore_errors=True)
        print(f"  fragments: cold plan {frag['cold_plan_reads']} reads "
              f"({frag['cold_fragment_reads']} fragment-path, "
              f"{frag['cold_plan_us']:.0f} us) vs warm "
              f"{frag['warm_plan_reads']} reads "
              f"({frag['warm_fragment_reads']} fragment-path, "
              f"{frag['warm_plan_us']:.0f} us)", file=sys.stderr)

        matrix = {
            "prepare_workers": 8,
            "apiserver_rtt_ms_injected": ATTACH_APISERVER_RTT_S * 1e3,
            "bursts": cells,
            "serial_baseline": list(serial_cells.values()),
            "fragments": frag,
        }
        out_path = os.environ.get("BENCH_ATTACH_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "bench_attach_r08.json")
        with open(out_path, "w") as f:
            json.dump(matrix, f, indent=1)
        key = next(c for c in cells if c["k_claims"] == 32)
        return {
            "metric": "attach_burst_32_wall_ms",
            "value": key["burst_wall_ms_p50"],
            "unit": "ms",
            # >1.0 means the concurrent burst beat the measured serial
            # baseline; acceptance needs >= 2.0 (wall < 0.5x serial)
            "vs_baseline": key["speedup_vs_serial"],
            "baseline_source": "measured serial baseline: same 32 claims on "
                               "a prepare_workers=1 driver with a zero "
                               "commit window (pre-PR shape: sequential API "
                               "round trips, one whole-file checkpoint "
                               "write per claim), same injected apiserver "
                               "RTT on both sides",
            "apiserver_rtt_ms_injected": ATTACH_APISERVER_RTT_S * 1e3,
            "serial_wall_ms_32": key["serial_wall_ms_p50"],
            "checkpoint_writes_32": key["checkpoint_writes_p50"],
            "serial_checkpoint_writes_32": key["serial_checkpoint_writes"],
            "claims_coalesced_32": key["claims_coalesced_p50"],
            "throughput_claims_per_s_32": key["throughput_claims_per_s"],
            "fragment_read_ratio": frag["fragment_read_ratio"],
            "matrix_file": os.path.relpath(
                out_path, os.path.dirname(os.path.abspath(__file__))),
        }
    finally:
        apiserver.stop()
        shutil.rmtree(root, ignore_errors=True)


def run_fleet(quick=False):
    """`bench.py --fleet` (r11): the fleet-scale simulation matrix
    (tpu_device_plugin/fleetsim.py; make bench-fleet).

    Cells (all counted facts recorded next to the timed ones):

      - BOOT STORM, paced vs unpaced, N in {16,64,256} ({4} quick):
        every node publishes its guarded ResourceSlice simultaneously
        against the load-degrading fabric (service time grows with
        in-flight — the congestion shape RPCAcc targets). Headline:
        apiserver peak in-flight with pacing <= 1/4 of unpaced at N=64,
        plus server-side write p50/p99. Exactly-once asserted from the
        fabric's accepted-write log (no duplicated/regressed pool
        generations).
      - BACKPRESSURE FLIP WAVE at N=64 (16 quick... N=4): a capped
        fabric (max_inflight, 429 beyond) under a per-node health-flip
        storm — adaptive windows + coalescing vs the naive retry herd,
        measured as throttled counts and publish waves; every node's
        final slice state must converge exactly.
      - MASS ATTACH STORM at N=64 (quick N=4), K claims/node in one
        concurrent burst per node: fleet claims/s, checkpoint commits
        (group-commit bound fleet-wide), zero lost claims.
      - ROLLING DRAIN/UPGRADE WAVE: drain -> driver restart against the
        same checkpoint -> restore in waves; prepared claims survive.

    Writes docs/bench_fleet_r11.json ($BENCH_FLEET_OUT overrides).
    """
    from tpu_device_plugin.fleetsim import FleetSim

    out = {"quick": quick, "boot_storms": [], "seed": 11}
    boot_ns = (4,) if quick else (16, 64, 256)
    # base service 20 ms, degrading by 1+inflight/4: an unpaced N-node
    # herd makes every write pay ~N/4 x the base; the paced fleet spreads
    # over a window scaled with N so the fabric stays near its base
    latency_s, congestion_k = 0.02, 4
    for n in boot_ns:
        window_s = max(0.5, n * 0.0625)
        cell = {"nodes": n, "latency_ms": latency_s * 1e3,
                "congestion_k": congestion_k,
                "pace_window_s": window_s}
        for pace in (False, True):
            sim = FleetSim(n_nodes=n, devices_per_node=4,
                           latency_s=latency_s, max_inflight=0,
                           congestion_k=congestion_k, pace=pace,
                           pace_base_s=window_s,
                           pace_max_s=2 * window_s, seed=11)
            try:
                boot = sim.boot_storm()
            finally:
                sim.stop()
            assert boot["published_ok"] == n, boot
            assert boot["exactly_once"], boot["audit"]
            key = "paced" if pace else "unpaced"
            cell[key] = {
                "wall_s": boot["wall_s"],
                "peak_inflight": boot["apiserver"]["peak_inflight"],
                "write_wall_p50_ms":
                    boot["apiserver"].get("write_wall_p50_ms"),
                "write_wall_p99_ms":
                    boot["apiserver"].get("write_wall_p99_ms"),
                "requests_total": boot["apiserver"]["requests_total"],
                "pacing": boot["pacing"],
                "exactly_once": boot["exactly_once"],
            }
        cell["peak_inflight_ratio"] = round(
            cell["unpaced"]["peak_inflight"]
            / max(1, cell["paced"]["peak_inflight"]), 2)
        out["boot_storms"].append(cell)
        print(f"  boot N={n:3d}: unpaced peak "
              f"{cell['unpaced']['peak_inflight']:3d} "
              f"(p99 {cell['unpaced']['write_wall_p99_ms']} ms) | paced "
              f"peak {cell['paced']['peak_inflight']:3d} "
              f"(p99 {cell['paced']['write_wall_p99_ms']} ms) | ratio "
              f"{cell['peak_inflight_ratio']}x", file=sys.stderr)

    # backpressure + attach + drain/upgrade on one fleet at the
    # acceptance scale (N=64; N=4 quick), capped fabric: 429s feed the
    # adaptive windows, coalescing absorbs the per-node flip storms
    n = 4 if quick else 64
    k_claims = 4 if quick else 16
    sim = FleetSim(n_nodes=n, devices_per_node=4, latency_s=0.005,
                   max_inflight=8, pace=True, pace_max_s=2.0, seed=11)
    try:
        sim.boot_storm()
        flip = sim.flip_wave(6)
        assert flip["converged"] and flip["exactly_once"], flip
        attach = sim.attach_storm(k_claims)
        assert attach["errors"] == [], attach["errors"]
        assert attach["prepared_total"] == n * k_claims, attach
        wave = sim.drain_upgrade_wave(max(1, n // 4))
        assert wave["converged"] and wave["exactly_once"], wave
        out["flip_wave"] = flip
        out["attach_storm"] = attach
        out["drain_upgrade"] = wave
        out["pacing_totals"] = sim.pacer_totals()
    finally:
        sim.stop()
    print(f"  flip wave N={n}: {flip['accepted_writes']} accepted writes "
          f"for {n * 6} flips, converged={flip['converged']} | attach "
          f"{attach['claims_total']} claims @ "
          f"{attach['claims_per_s']:.0f}/s, "
          f"{attach['checkpoint_commits']} commits | upgrade waves "
          f"{wave['waves']}, claims kept {wave['prepared_total']}",
          file=sys.stderr)

    # --quick must never clobber the COMMITTED artifact the r11 honesty
    # pins read (a quick matrix has no N=64 cell): it defaults to a
    # sibling *_quick file unless $BENCH_FLEET_OUT says otherwise
    default_name = ("bench_fleet_r11_quick.json" if quick
                    else "bench_fleet_r11.json")
    out_path = os.environ.get("BENCH_FLEET_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    key_cell = next(c for c in out["boot_storms"]
                    if c["nodes"] == (4 if quick else 64))
    return {
        "metric": "fleet_boot_peak_inflight_ratio_64n"
                  if not quick else "fleet_boot_peak_inflight_ratio_4n",
        "value": key_cell["peak_inflight_ratio"],
        "unit": "x",
        # acceptance: paced peak <= 1/4 of unpaced at N=64
        "vs_baseline": round(key_cell["peak_inflight_ratio"] / 4.0, 3),
        "baseline_source": "ISSUE 9 acceptance: apiserver peak in-flight "
                           "with pacing <= 1/4 of unpaced at N=64 "
                           "(unpaced control = same fleet, zero-window "
                           "immediate-retry pacer), exactly-once "
                           "asserted from the fabric's accepted-write "
                           "generation log",
        "unpaced_peak_inflight": key_cell["unpaced"]["peak_inflight"],
        "paced_peak_inflight": key_cell["paced"]["peak_inflight"],
        "unpaced_write_p99_ms": key_cell["unpaced"]["write_wall_p99_ms"],
        "paced_write_p99_ms": key_cell["paced"]["write_wall_p99_ms"],
        "attach_claims_per_s": out["attach_storm"]["claims_per_s"],
        "attach_checkpoint_commits":
            out["attach_storm"]["checkpoint_commits"],
        "flip_converged": out["flip_wave"]["converged"],
        "exactly_once": key_cell["paced"]["exactly_once"],
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def run_scale(quick=False):
    """`bench.py --scale` (r11): the single-daemon 4096-device /
    1024-partition ceiling (make bench-scale).

    Counted facts first (load-insensitive), walls recorded alongside:

      - DISCOVERY: cold full scan vs warm dirty-set rescan READ COUNTS
        at 4096 chips + 1024 partitions — the PR 2 floor guard (>= 5x)
        re-pinned at fleet scale.
      - EPOCH ISOLATION: 16 resources; ONE health flip in one resource
        must build exactly ONE epoch fleet-wide (counted via the
        per-plugin epoch_builds counter) and leave every other
        resource's pre-serialized ListAndWatch payload IDENTITY-reused
        (`is`), plus the one-flip epoch build wall on a single
        4096-device table (what a rebuild costs when it is real).
      - SCRAPE: /metrics + /status assembly at 4096 devices — the
        byte-accounting invariant (every byte materialized once:
        bytes_joined == bytes_rendered) and the wall scaling ratio vs a
        4x smaller rig (linear assembly stays ~4x, quadratic concat
        would be ~16x); diagnostics-TTL warm scrape recorded next to
        the cold one.
      - CHECKPOINT: a 1024-claim burst — commits COUNTED at the
        group-commit bound, checkpoint_bytes (compact separators)
        recorded per claim, with the indent=1 size it replaced.

    Writes docs/bench_scale_r11.json ($BENCH_SCALE_OUT overrides).
    """
    import types

    from tests.fakehost import FakeChip, FakeHost
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin import status as status_mod
    from tpu_device_plugin.discovery import HostSnapshot
    from tpu_device_plugin.dra import (DraDriver, _dump_compact,
                                       slice_device_name)
    from tpu_device_plugin.kubeapi import ApiClient
    from tpu_device_plugin.kubeletapi import drapb

    n_devices = 512 if quick else 4096
    n_parts = 128 if quick else 1024
    n_claims = 128 if quick else 1024
    n_resources = 16
    out = {"quick": quick, "n_devices": n_devices,
           "n_partitions": n_parts, "n_claims": n_claims}
    root = tempfile.mkdtemp(prefix="tdpscale-")
    try:
        host = FakeHost(root)
        for i in range(n_devices):
            host.add_chip(FakeChip(
                f"{1 + i // 8192:04x}:{(i // 32) % 256:02x}"
                f":{4 + i % 32:02x}.0",
                device_id="0063", iommu_group=str(11 + i),
                numa_node=(i * 2) // n_devices))
        bdfs = [f"{1 + i // 8192:04x}:{(i // 32) % 256:02x}"
                f":{4 + i % 32:02x}.0" for i in range(n_devices)]
        for p in range(n_parts):
            host.add_mdev(f"scale-uuid-{p:04d}", "TPU vhalf",
                          bdfs[p % n_devices],
                          iommu_group=str(100000 + p))
        gen_path = os.path.join(root, "genmap.json")
        with open(gen_path, "w") as f:
            json.dump({"0063": {"name": "v5e",
                                "chips_per_host": n_devices,
                                "host_topology": [64, n_devices // 64],
                                "cores_per_chip": 1}}, f)
        from dataclasses import replace
        cfg = replace(Config().with_root(root),
                      generation_map_path=gen_path,
                      diagnostics_ttl_s=60.0, lw_debounce_s=0.0)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)

        # ---- discovery floor at scale (counted) -------------------------
        snap = HostSnapshot(cfg)
        with count_reads() as cold:
            t0 = time.perf_counter()
            registry, generations = snap.rescan()
            cold_wall_ms = (time.perf_counter() - t0) * 1e3
        assert len(registry.all_devices()) == n_devices
        with count_reads() as warm:
            t0 = time.perf_counter()
            snap.rescan(dirty={bdfs[0]})
            warm_wall_ms = (time.perf_counter() - t0) * 1e3
        out["discovery"] = {
            "cold_reads": cold.reads,
            "warm_reads": warm.reads,
            "read_ratio": round(cold.reads / max(1, warm.reads), 1),
            "cold_wall_ms": round(cold_wall_ms, 1),
            "warm_wall_ms": round(warm_wall_ms, 1),
        }
        assert cold.reads >= 5 * warm.reads, out["discovery"]
        print(f"  discovery {n_devices}+{n_parts}: cold {cold.reads} "
              f"reads ({cold_wall_ms:.0f} ms) vs warm {warm.reads} "
              f"({warm_wall_ms:.1f} ms) = "
              f"{out['discovery']['read_ratio']}x", file=sys.stderr)

        # ---- epoch flip isolation across 16 resources (counted) ---------
        devices = registry.devices_by_model["0063"]
        per_res = n_devices // n_resources

        def build_plugins(count, width):
            return [TpuDevicePlugin(cfg, f"v5e-r{i:02d}", registry,
                                    devices[i * width:(i + 1) * width])
                    for i in range(count)]

        t0 = time.perf_counter()
        plugins = build_plugins(n_resources, per_res)
        build_all_ms = (time.perf_counter() - t0) * 1e3
        payloads_before = [p._store.current.lw_payload for p in plugins]
        builds_before = sum(p._epoch_builds.value for p in plugins)
        flip_dev = devices[0].bdf
        t0 = time.perf_counter()
        plugins[0].set_devices_health([flip_dev], healthy=False)
        flip_wall_us = (time.perf_counter() - t0) * 1e6
        builds_delta = sum(p._epoch_builds.value
                           for p in plugins) - builds_before
        identity_reused = sum(
            1 for p, before in zip(plugins[1:], payloads_before[1:])
            if p._store.current.lw_payload is before)
        assert builds_delta == 1, builds_delta
        assert identity_reused == n_resources - 1, identity_reused
        # what a REAL rebuild costs at the full table width: one flip on
        # a single-resource 4096-device plugin re-serializes everything
        big = TpuDevicePlugin(cfg, "v5e-all", registry, devices)
        t0 = time.perf_counter()
        big.set_devices_health([flip_dev], healthy=False)
        big_flip_ms = (time.perf_counter() - t0) * 1e3
        out["epoch"] = {
            "resources": n_resources,
            "devices_per_resource": per_res,
            "plugin_build_all_ms": round(build_all_ms, 1),
            "one_flip_epoch_builds": builds_delta,
            "payloads_identity_reused": identity_reused,
            "one_flip_wall_us": round(flip_wall_us, 1),
            "full_table_flip_rebuild_ms": round(big_flip_ms, 2),
        }
        print(f"  epoch: 1 flip -> {builds_delta} build, "
              f"{identity_reused}/{n_resources - 1} payloads identity-"
              f"reused | full-table rebuild {big_flip_ms:.1f} ms",
              file=sys.stderr)

        # ---- /status + /metrics scrape at scale -------------------------
        def scrape_rig(plgs):
            manager = types.SimpleNamespace(
                plugins=plgs, pending=[], native_info={}, draining=False,
                running=threading.Event())
            return status_mod.StatusServer(manager, port=0)

        def scrape_walls(server, rounds=3):
            metrics_walls, status_walls = [], []
            server.metrics()            # cold: pays the diagnostics reads
            for _ in range(rounds):
                t0 = time.perf_counter()
                text = server.metrics()
                metrics_walls.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                json.dumps(server.status(), sort_keys=True)
                status_walls.append((time.perf_counter() - t0) * 1e3)
            return (statistics.median(metrics_walls),
                    statistics.median(status_walls), text)

        full_rig = scrape_rig(plugins)
        t0 = time.perf_counter()
        full_rig.metrics()
        cold_scrape_ms = (time.perf_counter() - t0) * 1e3
        metrics_ms, status_ms, text = scrape_walls(full_rig)
        stats_full = dict(full_rig.scrape_stats)
        quarter = build_plugins(n_resources // 4, per_res)
        quarter_rig = scrape_rig(quarter)
        q_metrics_ms, q_status_ms, _ = scrape_walls(quarter_rig)
        stats_quarter = dict(quarter_rig.scrape_stats)
        full_rig._httpd.server_close()
        quarter_rig._httpd.server_close()
        out["scrape"] = {
            "devices": n_devices,
            "metrics_bytes": len(text),
            "scrape_stats": stats_full,
            "bytes_once": stats_full["bytes_joined"]
            == stats_full["bytes_rendered"],
            "cold_metrics_wall_ms": round(cold_scrape_ms, 1),
            "warm_metrics_wall_ms": round(metrics_ms, 2),
            "status_wall_ms": round(status_ms, 2),
            "quarter_metrics_wall_ms": round(q_metrics_ms, 2),
            "quarter_status_wall_ms": round(q_status_ms, 2),
            # linear assembly: ~4x for 4x devices; quadratic: ~16x
            "metrics_wall_ratio_4x": round(
                metrics_ms / max(0.001, q_metrics_ms), 2),
            "status_wall_ratio_4x": round(
                status_ms / max(0.001, q_status_ms), 2),
            "parts_ratio_4x": round(stats_full["parts"]
                                    / max(1, stats_quarter["parts"]), 2),
        }
        assert out["scrape"]["bytes_once"], stats_full
        print(f"  scrape: /metrics {metrics_ms:.1f} ms warm "
              f"({cold_scrape_ms:.0f} ms cold w/ diagnostics), /status "
              f"{status_ms:.1f} ms | 4x-devices wall ratio "
              f"{out['scrape']['metrics_wall_ratio_4x']}x (linear ~4)",
              file=sys.stderr)

        # ---- checkpoint: 1024-claim burst (counted) ---------------------
        apiserver = FakeApiServer()
        try:
            ck_cfg = replace(cfg, prepare_workers=32)
            driver = DraDriver(ck_cfg, registry, generations,
                               node_name="scale-node",
                               api=ApiClient(apiserver.url,
                                             token_path="/nonexistent"))
            driver.checkpoint_commit_window_s = 0.25
            names = [slice_device_name(b) for b in bdfs[:64]]
            uids = [f"scale-{i:04d}" for i in range(n_claims)]
            for i, uid in enumerate(uids):
                apiserver.add_claim("scale", uid, uid,
                                    driver.driver_name,
                                    [{"device": names[i % len(names)]}])
            claims = [drapb.Claim(namespace="scale", name=uid, uid=uid)
                      for uid in uids]
            c0 = driver.checkpoint_stats()
            t0 = time.perf_counter()
            resp = driver.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=claims), None)
            burst_wall_s = time.perf_counter() - t0
            for uid in uids:
                assert resp.claims[uid].error == "", resp.claims[uid].error
            c1 = driver.checkpoint_stats()
            commits = (c1["checkpoint_commits_total"]
                       - c0["checkpoint_commits_total"])
            coalesced = (c1["checkpoint_claims_coalesced_total"]
                         - c0["checkpoint_claims_coalesced_total"])
            ckpt_bytes = c1["checkpoint_bytes"]
            # the group-commit bound at this window: one write per open
            # window over the burst, plus the lone leading/trailing ones
            bound = int(burst_wall_s
                        / driver.checkpoint_commit_window_s) + 3
            with driver._lock:
                snapshot = {"version": 1,
                            "claims": dict(driver._checkpoint),
                            "handoffs": dict(driver._handoffs)}
            indent_bytes = len(json.dumps(snapshot, indent=1,
                                          sort_keys=True).encode())
            driver.stop()
            out["checkpoint"] = {
                "claims": n_claims,
                "burst_wall_s": round(burst_wall_s, 2),
                "commits": commits,
                "claims_coalesced": coalesced,
                "commit_window_s": 0.25,
                "group_commit_bound": bound,
                "checkpoint_bytes": ckpt_bytes,
                "bytes_per_claim": round(ckpt_bytes / n_claims, 1),
                "indent1_bytes": indent_bytes,
                "compact_saving_pct": round(
                    100 * (1 - ckpt_bytes / indent_bytes), 1),
            }
            assert coalesced == n_claims, out["checkpoint"]
            assert commits <= bound, out["checkpoint"]
            assert commits * 8 <= n_claims, out["checkpoint"]
            print(f"  checkpoint: {n_claims} claims -> {commits} commits "
                  f"(bound {bound}) in {burst_wall_s:.1f} s | "
                  f"{ckpt_bytes} bytes compact "
                  f"({out['checkpoint']['compact_saving_pct']}% under "
                  f"indent=1)", file=sys.stderr)
        finally:
            apiserver.stop()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # same clobber guard as run_fleet: --quick records a 512-device
    # matrix that would break the committed 4096-device pins
    default_name = ("bench_scale_r11_quick.json" if quick
                    else "bench_scale_r11.json")
    out_path = os.environ.get("BENCH_SCALE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    return {
        "metric": "scale_4096dev_one_flip_epoch_builds"
                  if not quick else "scale_512dev_one_flip_epoch_builds",
        "value": out["epoch"]["one_flip_epoch_builds"],
        "unit": "builds",
        "vs_baseline": 1.0,
        "baseline_source": "ISSUE 9 acceptance at 4096 devices / 1024 "
                           "partitions: one health flip = ONE epoch "
                           "build fleet-wide (counted), other resources' "
                           "payloads identity-reused; warm discovery "
                           "within the PR 2 read floor; scrape bytes "
                           "materialized once; 1024-claim checkpoint "
                           "burst at the group-commit bound",
        "discovery_read_ratio": out["discovery"]["read_ratio"],
        "payloads_identity_reused": out["epoch"]["payloads_identity_reused"],
        "metrics_wall_ratio_4x": out["scrape"]["metrics_wall_ratio_4x"],
        "checkpoint_commits_1024": out["checkpoint"]["commits"],
        "checkpoint_bytes_per_claim": out["checkpoint"]["bytes_per_claim"],
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def run_placement(quick=False):
    """`bench.py --placement` (r12): slice-placement quality, engine vs
    naive, at N in {4,16} fleetsim nodes (quick: {4}) under seeded claim
    churn (tpu_device_plugin/placement.py; make bench-placement).

    Per cell, against one churned fleet state:

      - PLACEMENT QUALITY: R four-chip (2x2) slice requests. For each,
        BOTH plans are computed on the same fleet state — the engine's
        (contiguous-first plan_slice) and the naive baseline's (first
        free chips in node/kubelet order, exactly what a topology-blind
        allocator hands out) — scored by ICI contiguity
        (placement.selection_score), then the engine's plan is applied
        through the full multi-host prepare path (fabric multiclaim
        record + per-node sub-claims). Headline: fraction of requests
        landing on ONE ICI ring (score 1.0), engine vs naive.
      - DEFRAG: churn until a 2x2 is unplaceable-but-satisfiable, then
        propose + APPLY the advisory (unprepare -> handoff -> re-prepare
        per migration) and re-plan: placeable_after must flip true.
        Moves and fragmentation before/after recorded.
      - AUDITS: the fabric's accepted-write generation log and the
        multi-node claim commit log both exactly-once in every cell.

    All facts are counted, not timed — placement quality is a property,
    not a race. Writes docs/bench_placement_r12.json
    ($BENCH_PLACEMENT_OUT overrides).
    """
    import random as _random

    from tpu_device_plugin import placement
    from tpu_device_plugin.fleetsim import FleetSim

    seed = 12
    out = {"quick": quick, "seed": seed, "cells": []}

    def naive_plan(views, need):
        """First `need` free chips in node order — the topology-blind
        baseline — scored with the same honesty as the engine's."""
        chosen = []
        for view in sorted(views, key=lambda v: v.node):
            free_sorted = sorted((view.coords[r], r) for r in view.free
                                 if r in view.coords)
            for _c, raw in free_sorted:
                chosen.append((view, raw))
                if len(chosen) == need:
                    break
            if len(chosen) == need:
                break
        if len(chosen) < need:
            return None
        by_view = {}
        for view, raw in chosen:
            by_view.setdefault(view.node, (view, []))[1].append(raw)
        # scored with the ENGINE's own scatter formula
        # (placement.scatter_score) so the comparison can never drift
        # onto two definitions of contiguity
        return placement.scatter_score(
            [(view.dims, [view.coords[r] for r in raws])
             for view, raws in by_view.values()],
            need, max(placement.volume(v.dims) for v in views))

    for n_nodes in ((4,) if quick else (4, 16)):
        rng = _random.Random((seed << 8) ^ n_nodes)
        sim = FleetSim(n_nodes=n_nodes, devices_per_node=8,
                       latency_s=0.0, max_inflight=0, seed=seed)
        try:
            fillers = []      # live (node, uid) single-chip churn claims
            serial = [0]

            def churn(steps, sim=sim, rng=rng, fillers=fillers,
                      serial=serial):
                for _ in range(steps):
                    if fillers and rng.random() < 0.35:
                        node, uid = fillers.pop(
                            rng.randrange(len(fillers)))
                        node.detach([uid])
                        continue
                    node = sim.nodes[rng.randrange(len(sim.nodes))]
                    free = sorted(node.host_view().free)
                    if not free:
                        continue
                    serial[0] += 1
                    uid = f"churn-{serial[0]}"
                    node.claim_devices(uid, [rng.choice(free)])
                    fillers.append((node, uid))

            churn_steps = 6 * n_nodes
            churn(churn_steps)
            requests = 8 if quick else 16
            engine = {"placed": 0, "contiguous": 0, "scores": []}
            naive = {"contiguous": 0, "scores": []}
            for i in range(requests):
                views = sim.host_views()
                nscore = naive_plan(views, 4)
                if nscore is not None:
                    naive["scores"].append(nscore)
                    naive["contiguous"] += nscore == 1.0
                res = sim.prepare_slice("2x2", f"req-{n_nodes}-{i}",
                                        best_effort=True)
                if res.get("placed"):
                    engine["placed"] += 1
                    engine["scores"].append(res["score"])
                    engine["contiguous"] += res["score"] == 1.0
                churn(2)
            # defrag: fragment until a 2x2 is unplaceable but satisfiable
            defrag = {"attempted": False}
            for _ in range(12 * n_nodes):
                prop = sim.propose_defrag("2x2")
                if not prop["placeable"] and prop["satisfiable"] \
                        and prop["moves"] > 0 \
                        and all(m["target_node"] is not None
                                for m in prop["migrations"]):
                    frag_before = {
                        n.name: n.driver.fragmentation_stats()
                        for n in sim.nodes}
                    moves = sim.apply_defrag(prop)
                    plan = placement.plan_slice((2, 2), sim.host_views())
                    defrag = {
                        "attempted": True,
                        "moves": moves,
                        "placeable_after": plan is not None
                        and plan.score == 1.0,
                        "frag_max_before": max(
                            rec["fragmentation"]
                            for stats in frag_before.values()
                            for rec in stats.values()),
                    }
                    break
                churn(1)
            def mean(xs):
                return round(sum(xs) / len(xs), 4) if xs else 0.0
            out["cells"].append({
                "nodes": n_nodes,
                "chips": n_nodes * 8,
                "churn_steps": churn_steps,
                "requests": requests,
                "engine": {"placed": engine["placed"],
                           "contiguous": engine["contiguous"],
                           "mean_score": mean(engine["scores"])},
                "naive": {"contiguous": naive["contiguous"],
                          "mean_score": mean(naive["scores"])},
                "defrag": defrag,
                "exactly_once":
                    sim.apiserver.exactly_once_audit()["exactly_once"],
                "multiclaim_exactly_once":
                    sim.apiserver.multiclaim_audit()["exactly_once"],
            })
        finally:
            sim.stop()

    # a --quick run must never overwrite the committed r12 artifact the
    # perf-honesty pins read: it lands in a sibling *_quick file unless
    # $BENCH_PLACEMENT_OUT says otherwise
    default_name = ("bench_placement_r12_quick.json" if quick
                    else "bench_placement_r12.json")
    out_path = os.environ.get("BENCH_PLACEMENT_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    cell = out["cells"][0]
    return {
        "benchmark": "slice placement quality, engine vs naive (r12)",
        "value": cell["engine"]["contiguous"],
        "unit": f"of {cell['requests']} 4-chip requests on one ICI ring",
        "vs_baseline": (cell["engine"]["contiguous"]
                        / max(1, cell["naive"]["contiguous"])),
        "baseline_source": "naive first-free placement on the same "
                           "churned fleet state; defrag advisory applied "
                           "via migration handoff flips an unplaceable "
                           "2x2 placeable; fabric + multiclaim logs "
                           "exactly-once in every cell",
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def _fleetsched_storm(fleet, scheds, claims_total, shape="1x2",
                      per_claim=False):
    """Drive a claim storm through N schedulers concurrently (one
    thread per shard, round-robin claim assignment) and collect every
    decision result. `per_claim=True` is the unbatched baseline: each
    claim is submitted and pumped alone (a lone claim fires an
    immediate wave of one — one commit round per decision)."""
    import threading as _threading
    results = [None] * len(scheds)
    barrier = _threading.Barrier(len(scheds))

    def work(i):
        s = scheds[i]
        out = []
        barrier.wait(timeout=120)
        if per_claim:
            for j in range(i, claims_total, len(scheds)):
                s.submit(shape, f"c{j:06d}")
                out.extend(s.pump())
            out.extend(s.drain())
        else:
            for j in range(i, claims_total, len(scheds)):
                s.submit(shape, f"c{j:06d}")
            out = s.drain()
        results[i] = out

    threads = [_threading.Thread(target=work, args=(i,))
               for i in range(len(scheds))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t0
    flat = [r for shard in results for r in (shard or [])]
    return wall_s, flat


def _fleetsched_cell(n_nodes, devices, claims_total, n_sched,
                     crossing_s, wave_max, partition=True,
                     per_claim=False, shape="1x2"):
    """One bench cell: a fresh SyntheticFleet fabric, N watch-fed
    scheduler shards, one claim storm — returns the counted facts plus
    the triple exactly-once verdict (multiclaim commit log, per-slice
    write log, checkpoint log) from fleetplace.fleet_audit."""
    from tpu_device_plugin.fleetsim import SyntheticFleet
    from tpu_device_plugin import fleetplace

    fleet = SyntheticFleet(n_nodes, devices_per_node=devices,
                           commit_crossing_s=crossing_s)
    scheds = [fleet.scheduler(shard_index=i, shard_count=n_sched,
                              partition=partition, wave_max=wave_max)
              for i in range(n_sched)]
    try:
        for s in scheds:
            s.start()
        for s in scheds:
            s.wait_synced(timeout_s=120)
        wall_s, results = _fleetsched_storm(
            fleet, scheds, claims_total, shape=shape,
            per_claim=per_claim)
        assert len(results) == claims_total, (len(results), claims_total)
        lat = sorted(r["latency_ms"] for r in results)
        placed = sum(1 for r in results if r.get("placed"))
        audit = fleetplace.fleet_audit(
            scheds,
            fabric_audit=fleet.apiserver.multiclaim_audit(),
            placement_audit=fleet.apiserver.placement_audit(),
            checkpoint_audit=fleet.checkpoint_audit())
        assert audit["exactly_once"], audit
        api_stats = dict(fleet.apiserver.stats)
        sched_totals = {
            key: sum(s.stats[key].value for s in scheds)
            for key in ("decisions_total", "decision_waves_total",
                        "commit_conflicts_total", "replans_total",
                        "placed_total", "unplaceable_total")}
        acct = scheds[0].cache.accountant.snapshot()
        decisions = len(results)
        return {
            "nodes": n_nodes, "devices_per_node": devices,
            "claims": claims_total, "schedulers": n_sched,
            "partition": partition, "wave_max": wave_max,
            "per_claim_commits": per_claim,
            "commit_crossing_ms": crossing_s * 1e3,
            "wall_s": round(wall_s, 3),
            "decisions_per_s": round(decisions / wall_s, 1),
            "placed": placed,
            "unplaceable": sched_totals["unplaceable_total"],
            "decision_p50_ms": lat[len(lat) // 2],
            "decision_p99_ms": lat[max(0, math.ceil(0.99 * len(lat)) - 1)],
            "decision_waves": sched_totals["decision_waves_total"],
            "commit_conflicts": sched_totals["commit_conflicts_total"],
            "replans": sched_totals["replans_total"],
            "conflict_abort_rate": round(
                sched_totals["commit_conflicts_total"]
                / max(1, decisions), 4),
            "fabric_commit_rounds": api_stats["commit_rounds_total"],
            "fabric_conflicts": api_stats["placement_conflicts_total"],
            "frag_delta_applies": acct["frag_delta_applies_total"],
            "frag_full_recomputes": acct["frag_full_recomputes_total"],
            "exactly_once": audit["exactly_once"],
            "exactly_once_logs": {
                "multiclaim": audit["fabric_agrees"],
                "write_log": fleet.apiserver.exactly_once_audit()[
                    "exactly_once"],
                "placement": audit["placement_exactly_once"],
                "checkpoint": audit["checkpoint_exactly_once"]},
        }
    finally:
        fleet.stop()


def run_fleetsched(quick=False):
    """`bench.py --fleetsched` (r19): the sharded fleet scheduler at
    4096 nodes / 16k-claim storm (make bench-fleetsched).

    Cells (every cell exactly-once on ALL THREE audit logs —
    multiclaim commit log, per-slice write-generation log, checkpoint
    log — via fleetplace.fleet_audit; a violation asserts the bench
    red):

      - SINGLE: one scheduler, one commit round per decision (the
        lone-claim immediate-wave rule = the pre-r19 per-claim
        protocol), on a 2048-claim sample of the storm — the rate
        baseline. Decision planning already rides the incremental
        accountant; what this cell lacks is batching and sharding.
      - SHARDED: N=4 partitioned schedulers over ONE fabric, full
        16384-claim storm, 64-claim decision waves, optimistic CAS
        commits. Headline: decisions/sec >= 4x the single cell
        (pinned by tests/test_perf_honesty.py), p99 decision latency
        reported honestly (batching trades per-claim latency for
        throughput).
      - CONTENDED: 2 UNPARTITIONED schedulers racing the same small
        fleet — the conflict-abort/replan path under real contention;
        records the conflict-abort rate and proves zero
        double-placements when CAS does the arbitration.

    Writes docs/bench_fleetsched_r19.json ($BENCH_FLEETSCHED_OUT
    overrides; --quick (N=2, 64 nodes) lands in a sibling *_quick
    file so the committed artifact the perf-honesty pin reads is
    never clobbered).
    """
    out = {"quick": quick, "shape": "1x2"}
    if quick:
        single = _fleetsched_cell(64, 8, 64, 1, 0.002, 64,
                                  partition=False, per_claim=True)
        sharded = _fleetsched_cell(64, 8, 256, 2, 0.002, 64,
                                   partition=True)
        contended = _fleetsched_cell(32, 8, 64, 2, 0.002, 16,
                                     partition=False)
    else:
        single = _fleetsched_cell(4096, 16, 2048, 1, 0.01, 64,
                                  partition=False, per_claim=True)
        sharded = _fleetsched_cell(4096, 16, 16384, 4, 0.01, 64,
                                   partition=True)
        contended = _fleetsched_cell(256, 8, 512, 2, 0.005, 16,
                                     partition=False)
    out["single"] = single
    out["sharded"] = sharded
    out["contended"] = contended
    speedup = round(sharded["decisions_per_s"]
                    / max(1e-9, single["decisions_per_s"]), 2)
    out["speedup_n4_vs_single"] = speedup
    for name, cell in (("single", single), ("sharded", sharded),
                       ("contended", contended)):
        print(f"  {name}: N={cell['schedulers']} "
              f"{cell['nodes']}n/{cell['claims']}c -> "
              f"{cell['decisions_per_s']}/s "
              f"(p99 {cell['decision_p99_ms']} ms, "
              f"conflicts {cell['commit_conflicts']}, "
              f"waves {cell['decision_waves']}, "
              f"exactly_once {cell['exactly_once']})",
              file=sys.stderr)
    print(f"  speedup N=4 vs single: {speedup}x", file=sys.stderr)
    if not quick:
        assert speedup >= 4.0, (
            f"sharded speedup {speedup}x < 4x acceptance floor")
    default_name = ("bench_fleetsched_r19_quick.json" if quick
                    else "bench_fleetsched_r19.json")
    out_path = os.environ.get("BENCH_FLEETSCHED_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs",
        default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    return {
        "metric": "fleetsched_speedup_n4_vs_single",
        "value": speedup,
        "unit": "x",
        "vs_baseline": round(speedup / 4.0, 3),
        "baseline_source": "ISSUE 17 acceptance: N=4 sharded "
                           "schedulers >= 4x decisions/sec vs a single "
                           "scheduler committing one round per "
                           "decision, same fabric and crossing cost; "
                           "exactly-once on multiclaim, write, and "
                           "checkpoint logs in every cell",
        "single_decisions_per_s": single["decisions_per_s"],
        "sharded_decisions_per_s": sharded["decisions_per_s"],
        "sharded_p99_ms": sharded["decision_p99_ms"],
        "conflict_abort_rate": contended["conflict_abort_rate"],
        "exactly_once_all_cells": all(
            c["exactly_once"] for c in (single, sharded, contended)),
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def run_fleet_placement(quick=False):
    """`bench.py --fleet-placement` (r16): the r12 placement-quality
    bench rerun THROUGH the fleet placement control plane
    (tpu_device_plugin/fleetplace.py) at 256 simulated nodes (quick:
    16) with CROSS-HOST slices — make bench-fleet-placement.

    Two identical fleets per cell, same seeded request/release stream:

      - ENGINE: every decision goes through fleetplace.FleetScheduler —
        selector-filtered views consumed from the PR 12 watch-stream
        Reflector's slice cache (published topology attributes rebuild
        the host grids), cross-host meshes constrained to pod-grid
        wrap-around windows, committed through the multiclaim fabric
        with the ONE scheduler commit log.
      - NAIVE: the same requests placed first-free in node order (the
        topology-blind allocator), executed through the SAME fabric
        path (execute_plan), scored with the engine's own scatter/mesh
        formulas so the comparison cannot drift.

    Recorded per cell: engine-vs-naive contiguity (single-host AND
    cross-host requests), fragmentation-over-churn curves for both
    fleets (fleetplace.cluster_fragmentation rollups), a globally
    planned defrag wave applied node-by-node via the migration-handoff
    machinery, and EVERY audit exactly-once — fabric write log,
    fabric multiclaim log, and the cluster-wide scheduler commit log
    cross-checked against the fabric. All facts counted, not timed.

    Writes docs/bench_fleetplace_r16.json ($BENCH_FLEETPLACE_OUT
    overrides; --quick lands in a sibling *_quick file so the committed
    artifact the perf-honesty pins read is never clobbered).
    """
    import random as _random

    from tpu_device_plugin import placement
    from tpu_device_plugin.fleetsim import FleetSim
    from tpu_device_plugin.placement import SlicePlan

    seed = 16
    out = {"quick": quick, "seed": seed, "cells": []}
    selector = 'topology.generation == "v5e" && topology.ring_size >= 4'

    def naive_slice_plan(nodes, shape):
        """First free chips in node order — the topology-blind
        baseline — as an executable SlicePlan scored with the engine's
        own formulas (scatter_score). Views are built LAZILY node by
        node: the baseline stops at the first nodes that satisfy it,
        exactly like a first-fit allocator walks its list (and so the
        256-node arm never rebuilds 256 views per single-chip claim)."""
        need = placement.volume(shape)
        shards, scored, taken = [], [], 0
        host_volume = 0
        for node in nodes:           # FleetSim keeps name order
            if taken >= need:
                break
            view = node.host_view()
            host_volume = max(host_volume, placement.volume(view.dims))
            free_sorted = sorted((view.coords[r], r) for r in view.free
                                 if r in view.coords)
            raws = tuple(r for _c, r in free_sorted[:need - taken])
            if not raws:
                continue
            shards.append((view.node, raws))
            scored.append((view.dims, [view.coords[r] for r in raws]))
            taken += len(raws)
        if taken < need:
            return None
        score = placement.scatter_score(scored, need, host_volume)
        return SlicePlan(shape=shape, shards=tuple(shards), score=score,
                         hosts=len(shards))

    n_nodes = 16 if quick else 256
    requests = 16 if quick else 64
    rng = _random.Random((seed << 8) ^ n_nodes)
    # shapes: single-host boxes + true cross-host meshes (2x8 = two
    # full 2x4 tori side by side on the pod grid)
    shapes = ["2x2", "2x2", "1x4", "2x8"]

    engine_sim = FleetSim(n_nodes=n_nodes, devices_per_node=8,
                          latency_s=0.0, max_inflight=0, seed=seed)
    naive_sim = FleetSim(n_nodes=n_nodes, devices_per_node=8,
                         latency_s=0.0, max_inflight=0, seed=seed)
    sched = None
    try:
        for sim in (engine_sim, naive_sim):
            for node in sim.nodes:
                node.driver.publish_resource_slices()
        # decisions consume the PR 12 watch-stream Reflector's slice
        # cache: LIST seeds it, published topology attributes rebuild
        # the host grids
        sched = engine_sim.scheduler(watch=True, resync_s=30.0)
        sched.start()
        assert sched.wait_synced(timeout_s=60, min_slices=n_nodes), \
            "slice cache never synced"

        engine = {"placed": 0, "contiguous": 0, "scores": [],
                  "cross_host_requests": 0, "cross_host_contiguous": 0}
        naive = {"placed": 0, "contiguous": 0, "scores": []}
        # live claim registry shared across arms: the SAME workload
        # (same uids, same release choices) placed by each arm's own
        # policy — who fragments the fleet less is the curve
        live = []           # uid -> placed-by-engine, naive shards
        naive_shards = {}
        curve = []
        serial = [0]

        def fleetplace_rollup(sim):
            from tpu_device_plugin.fleetplace import cluster_fragmentation
            return cluster_fragmentation(
                sim._views_by_gen(), pod_dims=sim.pod_dims).get("v5e", {})

        def frag_point(step):
            eng = sched.fragmentation().get("v5e", {})
            nai = fleetplace_rollup(naive_sim)
            curve.append({
                "step": step,
                "engine_fragmentation": eng.get("fragmentation", 0.0),
                "engine_largest_free_mesh":
                    eng.get("largest_free_mesh", 0),
                "naive_fragmentation": nai.get("fragmentation", 0.0),
                "naive_largest_free_mesh":
                    nai.get("largest_free_mesh", 0),
            })

        def place_both(shape_text, uid, measured=False):
            shape = placement.parse_shape(shape_text)
            res = sched.schedule(shape_text, uid,
                                 selector=selector if measured else "",
                                 best_effort=True)
            placed_engine = bool(res.get("placed"))
            if measured and placed_engine:
                cross = placement.volume(shape) > 8
                engine["placed"] += 1
                engine["scores"].append(res["score"])
                engine["contiguous"] += res["score"] == 1.0
                if cross:
                    engine["cross_host_requests"] += 1
                    engine["cross_host_contiguous"] += \
                        res["score"] == 1.0
            nplan = naive_slice_plan(naive_sim.nodes, shape)
            placed_naive = False
            if nplan is not None:
                nres = naive_sim.execute_plan(nplan, uid)
                placed_naive = bool(nres.get("placed"))
                if measured and placed_naive:
                    naive["placed"] += 1
                    naive["scores"].append(nplan.score)
                    naive["contiguous"] += nplan.score == 1.0
            if placed_engine or placed_naive:
                live.append((uid, placed_engine))
                if placed_naive:
                    naive_shards[uid] = nplan.shards
            return placed_engine

        def release(uid, placed_engine):
            if placed_engine:
                sched.release(uid)
            shards = naive_shards.pop(uid, None)
            if shards is not None:
                naive_sim.release_plan(uid, shards)

        def churn(steps):
            """Single-chip tenant churn, both arms placing the SAME
            workload by their own policy — the r12 fragmentation
            pressure at fleet scale."""
            for _ in range(steps):
                if live and rng.random() < 0.35:
                    uid, placed_engine = live.pop(
                        rng.randrange(len(live)))
                    release(uid, placed_engine)
                    continue
                serial[0] += 1
                place_both("1", f"churn-{n_nodes}-{serial[0]}")

        churn_steps = 6 * n_nodes
        churn(churn_steps)
        frag_point(0)
        for i in range(requests):
            serial[0] += 1
            place_both(shapes[i % len(shapes)],
                       f"req-{n_nodes}-{serial[0]}", measured=True)
            churn(2)
            if (i + 1) % max(1, requests // 10) == 0:
                frag_point(i + 1)

        def mean(xs):
            return round(sum(xs) / len(xs), 4) if xs else 0.0

        sched_audit = sched.audit(
            fabric_audit=engine_sim.apiserver.multiclaim_audit())
        compiled = sched.selector(selector)
        out["cells"].append({
            "nodes": n_nodes,
            "chips": n_nodes * 8,
            "pod_dims": list(engine_sim.pod_dims),
            "churn_steps": churn_steps,
            "requests": requests,
            "engine": {
                "placed": engine["placed"],
                "contiguous": engine["contiguous"],
                "mean_score": mean(engine["scores"]),
                "cross_host_requests": engine["cross_host_requests"],
                "cross_host_contiguous":
                    engine["cross_host_contiguous"],
            },
            "naive": {
                "placed": naive["placed"],
                "contiguous": naive["contiguous"],
                "mean_score": mean(naive["scores"]),
            },
            "fragmentation_over_churn": curve,
            "selector": {"text": selector, **compiled.snapshot()},
            "watch": {k: v for k, v in sched.snapshot().items()
                      if k.startswith("cache_")},
            "scheduler_audit_exactly_once": sched_audit["exactly_once"],
            "fabric_agrees": sched_audit.get("fabric_agrees", False),
            "exactly_once":
                engine_sim.apiserver.exactly_once_audit()
                ["exactly_once"],
            "multiclaim_exactly_once":
                engine_sim.apiserver.multiclaim_audit()["exactly_once"],
            "naive_multiclaim_exactly_once":
                naive_sim.apiserver.multiclaim_audit()["exactly_once"],
        })
    finally:
        if sched is not None:
            sched.stop()
        engine_sim.stop()
        naive_sim.stop()

    # --- global defrag wave cell (deterministic, counted): fill seven
    # hosts through the scheduler's multiclaim path, checkerboard the
    # eighth so a 2x2 is unplaceable-but-satisfiable, plan ONE wave
    # over every host's view, apply it node-by-node via the PR 7
    # migration-handoff machinery, and verify placeability flips with
    # all audits exactly-once
    defrag_sim = FleetSim(n_nodes=8, devices_per_node=8, latency_s=0.0,
                          max_inflight=0, seed=seed + 1)
    try:
        for node in defrag_sim.nodes:
            node.driver.publish_resource_slices()
        dsched = defrag_sim.scheduler(watch=False)
        for i in range(len(defrag_sim.nodes) - 1):
            res = dsched.schedule("2x4", f"fill-{i}")
            assert res.get("placed"), res
        board = defrag_sim.nodes[-1]       # the one host left pristine
        raw_at = {c: r for r, c in board.host_view().coords.items()}
        for i, c in enumerate([(0, 1), (1, 0), (0, 3), (1, 2)]):
            board.claim_devices(f"pin-{i}", [raw_at[c]])
        handoffs_before = sum(
            n.driver.handoff_stats["handoffs_completed_total"]
            for n in defrag_sim.nodes)
        prop = dsched.plan_defrag_wave("2x2")
        assert not prop["placeable"] and prop["satisfiable"], prop
        report = dsched.apply_defrag_wave(prop)
        views_after, _idx = dsched.views_by_generation()
        plan_after = placement.plan_slice((2, 2), views_after["v5e"])
        daudit = dsched.audit(
            fabric_audit=defrag_sim.apiserver.multiclaim_audit())
        out["cells"].append({
            "cell": "global_defrag_wave",
            "nodes": len(defrag_sim.nodes),
            "moves_planned": report["moves_planned"],
            "moves_applied": report["moves_applied"],
            "handoffs_completed": sum(
                n.driver.handoff_stats["handoffs_completed_total"]
                for n in defrag_sim.nodes) - handoffs_before,
            "placeable_before": False,
            "placeable_after": plan_after is not None
            and plan_after.score == 1.0,
            "fragmentation_before":
                prop["cluster_fragmentation"]["fragmentation"],
            "fragmentation_after":
                dsched.fragmentation()["v5e"]["fragmentation"],
            "scheduler_audit_exactly_once": daudit["exactly_once"],
            "fabric_agrees": daudit["fabric_agrees"],
            "exactly_once":
                defrag_sim.apiserver.exactly_once_audit()
                ["exactly_once"],
            "multiclaim_exactly_once":
                defrag_sim.apiserver.multiclaim_audit()["exactly_once"],
        })
    finally:
        defrag_sim.stop()

    default_name = ("bench_fleetplace_r16_quick.json" if quick
                    else "bench_fleetplace_r16.json")
    out_path = os.environ.get("BENCH_FLEETPLACE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    cell = out["cells"][0]
    return {
        "benchmark": "fleet placement control plane, engine vs naive "
                     "at cluster scale (r16)",
        "value": cell["engine"]["contiguous"],
        "unit": f"of {cell['engine']['placed']} placed requests fully "
                f"ICI-contiguous at {cell['nodes']} nodes",
        "vs_baseline": round(
            cell["engine"]["contiguous"]
            / max(1, cell["naive"]["contiguous"]), 3),
        "baseline_source": "naive first-free placement of the same "
                           "request stream on an identical fleet; "
                           "decisions consumed the watch-stream slice "
                           "cache; global defrag wave applied via "
                           "migration handoff; scheduler commit log + "
                           "fabric audits exactly-once in every cell",
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def run_broker(quick=False):
    """`bench.py --broker` (r13): the privilege-separation overhead.

    Measures the attach critical path (GetPreferredAllocation cold memo +
    Allocate, direct servicer calls — the r09 composition) in BOTH broker
    modes over the same 8-chip host:

      - `crossings_per_attach_*` (HEADLINE, COUNTED): privilege-boundary
        crossings per steady-state attach, counted live from the broker
        client's AtomicCounter — load-insensitive, pinned at <= 2 by
        tests/test_perf_honesty.py (one batched TOCTOU revalidation, at
        most one TTL-expired iommufd probe). Counting them away (caching
        the revalidation) would be the dishonest speedup.
      - `attach_wall_p50_us_inproc` vs `attach_wall_p50_us_spawn`: the
        same path with the in-process seam and with a REAL spawned
        broker process; `crossing_overhead_p50_us` is the difference —
        the price of running the serving daemon unprivileged, dominated
        by the unix-socket RTT per crossing (environment-sensitive, so
        the counted crossings are what the guard pins).

    Writes docs/bench_broker_r13.json ($BENCH_BROKER_OUT overrides).
    """
    from tpu_device_plugin import broker as broker_mod

    iters = 150 if quick else 600
    warm = 20 if quick else 60
    root = tempfile.mkdtemp(prefix="tdpbroker-")
    try:
        _build_host(root, 8)
        from dataclasses import replace as dc_replace
        cfg = dc_replace(Config().with_root(root), shared_scan_ttl_s=60.0)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        devices = registry.devices_by_model["0063"]
        torus = generations["0063"].host_topology
        all_ids = [d.bdf for d in devices]
        pref_req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=all_ids, allocation_size=4)])

        def attach_once(plg):
            plg._pref_cache.clear()
            t0 = time.perf_counter()
            pref = plg.GetPreferredAllocation(pref_req, None)
            alloc_req = pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(
                    devices_ids=list(
                        pref.container_responses[0].deviceIDs))])
            plg.Allocate(alloc_req, None)
            return (time.perf_counter() - t0) * 1e6

        def measure(client):
            prev = broker_mod.set_client(client)
            try:
                plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                         torus_dims=torus)
                walls = []
                for i in range(iters + warm):
                    if i == warm:
                        c0 = client.crossings.value
                    wall = attach_once(plugin)
                    if i >= warm:
                        walls.append(wall)
                crossings = (client.crossings.value - c0) / iters
                return statistics.median(walls), crossings
            finally:
                broker_mod.set_client(prev)

        inproc_p50, inproc_crossings = measure(
            broker_mod.InProcessBroker())

        sock_path = cfg.broker_socket_path
        proc = broker_mod.spawn_broker(sock_path, root=root)
        try:
            spawn_client = broker_mod.SocketBrokerClient(sock_path)
            spawn_p50, spawn_crossings = measure(spawn_client)
            spawn_client.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)

        out = {
            "metric": "broker_crossings_per_attach",
            "value": round(max(inproc_crossings, spawn_crossings), 3),
            "unit": "crossings",
            "vs_baseline": 1.0,
            "baseline_source": (
                "r13 introduces the privilege boundary; the pinned claim "
                "is the COUNTED crossing budget (<= 2 per steady-state "
                "attach: one batched TOCTOU revalidation + at most one "
                "TTL-expired iommufd probe), not the wall overhead — the "
                "IPC RTT is an environment property like the r09 syscall "
                "floor"),
            "crossings_per_attach_inproc": round(inproc_crossings, 3),
            "crossings_per_attach_spawn": round(spawn_crossings, 3),
            "attach_wall_p50_us_inproc": round(inproc_p50, 1),
            "attach_wall_p50_us_spawn": round(spawn_p50, 1),
            "crossing_overhead_p50_us": round(spawn_p50 - inproc_p50, 1),
            "devices_advertised": len(devices),
            "allocation_size": 4,
            "iterations": iters,
            "quick": quick,
        }
        out_path = os.environ.get("BENCH_BROKER_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "bench_broker_r13.json")
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        out["matrix_file"] = os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__)))
        print(f"  broker crossings/attach inproc {inproc_crossings:.2f} "
              f"spawn {spawn_crossings:.2f} | attach p50 inproc "
              f"{inproc_p50:7.1f} us spawn {spawn_p50:7.1f} us "
              f"(crossing overhead {out['crossing_overhead_p50_us']:+.1f} "
              f"us)", file=sys.stderr)
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_brokeripc(quick=False):
    """`bench.py --brokeripc` (r20): the broker crossing fast path.

    Three claims, each measured the honest way (and each pinned by
    tests/test_perf_honesty.py on the axis that is actually
    load-insensitive):

      - `framing_overhead_reduction_vs_json` (HEADLINE, DETERMINISTIC):
        framing overhead in BYTES — frame length minus the operand
        floor (UTF-8 operands + minimal varints the op actually
        carries; the floor is identical for both framings) — summed
        over a request+reply corpus of the hot crossing mix, binary v2
        (RequestEncoder) vs JSON v1 SAME-RUN. Pinned >= 3x. The
        wall-clock framing costs are reported alongside UNPINNED: the
        varint codec is pure Python, so the cached client encode wins
        modestly and decode LOSES to C json.loads — recorded, not
        hidden. The crossing wins are the batch and the ring, which
        remove whole round trips, not per-frame CPU.
      - `batched_claim_crossings` (COUNTED): privilege crossings per
        multi-group claim revalidation batch (the dra prefetch shape —
        read_attr + read_link per partition), counted live from the
        client AtomicCounter for group sizes {1,2,4,8} — pinned == 1.
        Ditto `chip_alive_batch_crossings` for a health-cycle batch of
        8 probes. Counting these away (skipping the revalidation)
        would be the dishonest speedup.
      - `ring_hits` (COUNTED, pinned > 0) + `ring_hit_p50_us`: the hot
        read_attr served from the shared-memory response ring with NO
        syscall, vs `crossing_rtt_p50_us_bin` over the socket.

    The crossing breakdown is calibrated in-run on the same box:
    `syscall_floor_p50_us` (socketpair self-ping — kernel copy cost,
    no wakeup), `wakeup_p50_us` (echo-thread ping-pong minus the
    self-ping — the scheduler handoff), and the end-to-end
    `crossing_rtt_p50_us_{json,bin}` against a REAL spawned broker
    (the remainder over floor+wakeup is dispatch + framing CPU).

    Writes docs/bench_brokeripc_r20.json ($BENCH_BROKERIPC_OUT
    overrides).
    """
    from tpu_device_plugin import broker as broker_mod
    from tpu_device_plugin import brokeripc
    from tpu_device_plugin.epoch import encode_varint

    iters = 60 if quick else 300
    warm = 10 if quick else 30

    # ---- framing: byte overhead (deterministic) + wall cost (honest)
    span = {"op": "dra.prepare", "seq": 7,
            "trace_id": "c0ffee0ddeadbeefc0ffee0ddeadbeef",
            "span_id": "beefc0ffee0ddead"}
    pci_base = "/sys/bus/pci/devices"
    corpus = [
        ({"op": "read_attr", "seq": 101, "span": span,
          "path": pci_base + "/0000:00:04.0/vendor"},
         {"ok": True, "seq": 101, "data": "0x1ae0"}),
        ({"op": "read_link", "seq": 102, "span": span,
          "path": pci_base + "/0000:00:04.0/iommu_group"},
         {"ok": True, "seq": 102,
          "target": "../../../kernel/iommu_groups/11"}),
        ({"op": "probe_config", "seq": 103, "span": span, "bits": 16,
          "path": pci_base + "/0000:00:04.0/config"},
         {"ok": True, "seq": 103, "data": "1ae0"}),
        ({"op": "chip_alive", "seq": 104, "span": span,
          "pci_base": pci_base, "bdf": "0000:00:04.0",
          "node": "/dev/vfio/11"},
         {"ok": True, "seq": 104, "alive": True}),
        ({"op": "node_exists", "seq": 105, "span": span,
          "path": "/dev/vfio/11"},
         {"ok": True, "seq": 105, "exists": True}),
    ]

    def _floor(value):
        # the information floor both framings must carry: operand
        # strings verbatim, ints as minimal varints, bools as one byte
        if isinstance(value, bool):
            return 1
        if isinstance(value, int):
            return len(encode_varint(brokeripc._zigzag(value)))
        if isinstance(value, str):
            return len(value.encode("utf-8"))
        if isinstance(value, dict):
            return sum(_floor(v) for v in value.values() if v is not None)
        if isinstance(value, (list, tuple)):
            return sum(_floor(v) for v in value)
        return 0

    encoder = brokeripc.RequestEncoder()
    for req, _rep in corpus:       # warm the static-frame cache
        encoder.encode_frame(req)
    floor_total = json_overhead = bin_overhead = 0
    for req, rep in corpus:
        for obj, is_req in ((req, True), (rep, False)):
            fl = _floor(obj)
            jlen = len(brokeripc._encode(obj, binary=False))
            blen = len(encoder.encode_frame(obj) if is_req
                       else brokeripc._encode(obj, binary=True))
            floor_total += fl
            json_overhead += jlen - fl
            bin_overhead += blen - fl
    overhead_ratio = json_overhead / max(bin_overhead, 1)

    reqs = [dict(r, seq=0) for r, _ in corpus]
    box = {"i": 0}

    def _enc_json():
        box["i"] += 1
        brokeripc._encode(dict(reqs[box["i"] % 5], seq=box["i"],
                               span=span), binary=False)

    def _enc_bin():
        box["i"] += 1
        encoder.encode_frame(dict(reqs[box["i"] % 5], seq=box["i"],
                                  span=span))

    hdr = brokeripc._HEADER_SIZE
    jframe = brokeripc._encode(corpus[0][0], binary=False)
    bframe = encoder.encode_frame(corpus[0][0])
    enc_json_us = _timed_median_us(_enc_json, iters * 10, warm)
    enc_bin_us = _timed_median_us(_enc_bin, iters * 10, warm)
    dec_json_us = _timed_median_us(
        lambda: json.loads(jframe[hdr:]), iters * 10, warm)
    dec_bin_us = _timed_median_us(
        lambda: brokeripc.decode_body(bframe[hdr:]), iters * 10, warm)

    # ---- in-run calibration: syscall floor and wakeup cost
    import socket as socket_mod
    left, right = socket_mod.socketpair()
    try:
        def _selfping():
            left.sendall(bframe)
            right.recv(65536)
        syscall_floor_us = _timed_median_us(_selfping, iters, warm)

        def _echo():
            while True:
                try:
                    data = right.recv(65536)
                except OSError:
                    return
                if not data:
                    return
                right.sendall(data)
        echo_thread = threading.Thread(target=_echo, daemon=True)
        echo_thread.start()

        def _pingpong():
            left.sendall(bframe)
            left.recv(65536)
        pingpong_us = _timed_median_us(_pingpong, iters, warm)
    finally:
        left.close()
        right.close()
    wakeup_us = max(pingpong_us - syscall_floor_us, 0.0)

    # ---- real spawned broker: RTT, counted batches, ring hits
    root = tempfile.mkdtemp(prefix="tdpbrokeripc-")
    try:
        _build_host(root, 8)
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        bdfs = [f"0000:00:{4 + i:02x}.0" for i in range(8)]
        vendor_paths = [os.path.join(cfg.pci_base_path, b, "vendor")
                        for b in bdfs]
        group_paths = [os.path.join(cfg.pci_base_path, b, "iommu_group")
                       for b in bdfs]
        nodes = [os.path.join(root, "dev/vfio", str(11 + i))
                 for i in range(8)]
        sock_path = cfg.broker_socket_path
        proc = broker_mod.spawn_broker(sock_path, root=root)
        try:
            # v1 peer: JSON framing, no ring (the broker serves ONE
            # connection at a time — close each client before the next)
            json_client = broker_mod.SocketBrokerClient(
                sock_path, protocol_version=1)
            rtt_json_us = _timed_median_us(
                lambda: json_client.read_attr(bdfs[0], vendor_paths[0]),
                iters, warm)
            json_peer_version = json_client.negotiated_version
            json_client.close()

            # v2 peer, ring off: every call is a genuine socket crossing
            bin_client = broker_mod.SocketBrokerClient(
                sock_path, ring=False)
            rtt_bin_us = _timed_median_us(
                lambda: bin_client.read_attr(bdfs[0], vendor_paths[0]),
                iters, warm)
            bin_peer_version = bin_client.negotiated_version

            group_sizes = [1, 2, 4, 8]
            claim_crossings = []
            for g in group_sizes:
                subs = []
                for i in range(g):
                    subs.append({"op": "read_attr",
                                 "path": vendor_paths[i]})
                    subs.append({"op": "read_link",
                                 "path": group_paths[i]})
                c0 = bin_client.crossings.value
                results = bin_client.run_batch(subs)
                assert all(r.get("ok") for r in results), results
                claim_crossings.append(bin_client.crossings.value - c0)
            c0 = bin_client.crossings.value
            alive = bin_client.chip_alive_batch(
                cfg.pci_base_path, list(zip(bdfs, nodes)))
            chip_alive_crossings = bin_client.crossings.value - c0
            assert all(alive.values()), alive
            bin_stats = bin_client.stats()
            bin_client.close()

            # v2 peer with the response ring: repeated hot reads hit
            # shared memory, zero syscalls (long TTL keeps them hot
            # for the duration of the timing loop)
            ring_client = broker_mod.SocketBrokerClient(
                sock_path, ring_ttl_s=60.0)
            for b, p in zip(bdfs, vendor_paths):
                ring_client.read_attr(b, p)   # first read publishes
            box["i"] = 0

            def _ring_read():
                box["i"] += 1
                i = box["i"] % 8
                ring_client.read_attr(bdfs[i], vendor_paths[i])
            ring_hit_us = _timed_median_us(_ring_read, iters * 4, warm)
            ring_hits = ring_client.ring_hits.value
            ring_fallbacks = ring_client.ring_fallbacks.value
            ring_attached = ring_client.stats().get("ring_attached")
            ring_client.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    out = {
        "metric": "brokeripc_framing_overhead_reduction",
        "value": round(overhead_ratio, 2),
        "unit": "x_vs_json",
        "vs_baseline": round(overhead_ratio / 3.0, 2),
        "baseline_source": (
            "r20 rebuilds the broker hot path; the pinned claims are "
            "the DETERMINISTIC ones — byte framing overhead (frame "
            "minus operand floor, same corpus, same run) >= 3x smaller "
            "than JSON, one counted crossing per batched claim/probe "
            "cycle, live ring hits > 0 — because wall RTT on a shared "
            "core is an environment property like the r09 syscall "
            "floor"),
        "framing_overhead_json_bytes": json_overhead,
        "framing_overhead_bin_bytes": bin_overhead,
        "framing_corpus_floor_bytes": floor_total,
        "framing_corpus_frames": len(corpus) * 2,
        "framing_encode_json_us": round(enc_json_us, 2),
        "framing_encode_bin_us": round(enc_bin_us, 2),
        "framing_decode_json_us": round(dec_json_us, 2),
        "framing_decode_bin_us": round(dec_bin_us, 2),
        "framing_wallclock_note": (
            "pure-Python varint decode loses to C json.loads and the "
            "cached encode wins only modestly — recorded unpinned; the "
            "latency wins are batching and the ring, which remove "
            "whole round trips"),
        "syscall_floor_p50_us": round(syscall_floor_us, 1),
        "wakeup_p50_us": round(wakeup_us, 1),
        "crossing_rtt_p50_us_json": round(rtt_json_us, 1),
        "crossing_rtt_p50_us_bin": round(rtt_bin_us, 1),
        "crossing_dispatch_and_framing_p50_us_json": round(
            max(rtt_json_us - pingpong_us, 0.0), 1),
        "crossing_dispatch_and_framing_p50_us_bin": round(
            max(rtt_bin_us - pingpong_us, 0.0), 1),
        "negotiated_version_json_peer": json_peer_version,
        "negotiated_version_bin_peer": bin_peer_version,
        "batched_claim_crossings": float(max(claim_crossings)),
        "batched_claim_group_sizes": group_sizes,
        "batched_claim_unbatched_equiv": 2 * max(group_sizes),
        "chip_alive_batch_crossings": float(chip_alive_crossings),
        "chip_alive_batch_probes": len(bdfs),
        "frame_cache_hits": bin_stats.get("frame_cache_hits_total", 0),
        "ring_attached": bool(ring_attached),
        "ring_hits": int(ring_hits),
        "ring_fallbacks": int(ring_fallbacks),
        "ring_hit_p50_us": round(ring_hit_us, 2),
        "ring_hit_vs_socket_speedup": round(
            rtt_bin_us / max(ring_hit_us, 1e-9), 1),
        "iterations": iters,
        "quick": quick,
    }
    out_path = os.environ.get("BENCH_BROKERIPC_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "bench_brokeripc_r20.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    out["matrix_file"] = os.path.relpath(
        out_path, os.path.dirname(os.path.abspath(__file__)))
    print(f"  brokeripc framing overhead json {json_overhead}B bin "
          f"{bin_overhead}B ({overhead_ratio:.1f}x) | crossing rtt "
          f"json {rtt_json_us:.0f} us bin {rtt_bin_us:.0f} us (floor "
          f"{syscall_floor_us:.0f} + wakeup {wakeup_us:.0f}) | claim "
          f"batch {max(claim_crossings)} crossing(s) | ring hit "
          f"{ring_hit_us:.1f} us x{ring_hits}", file=sys.stderr)
    return out


def run_autopilot(quick=False):
    """`bench.py --autopilot` (r14): the continuous fleet autopilot soak
    (tpu_device_plugin/autopilot.py; make soak-autopilot / the CI smoke
    leg).

    Two phases, both counted facts:

      - SOAK: N in-process nodes under OVERLAPPING storms — claim
        batches, multi-host slices, health-flip waves, hot-unplugs with
        orphan cleanup + replug readmission, handoff migrations, defrag
        advisories, rolling upgrade waves, republish (boot) waves —
        with the fabric's watch-stream chaos (breaks / duplicate
        deliveries) AND the kubeapi.watch fault sites firing
        throughout, and the soak invariants (exactly-once fabric +
        multiclaim audits, zero lost claims, zero orphaned specs,
        checkpoint/fabric agreement) checked CONTINUOUSLY by a
        dedicated thread, then once more after quiesce (0 orphans
        left). Full shape: 256 nodes / >= 100k claim events. Quick
        (CI): 8 nodes, ~25 s, every storm type still enabled.
      - READ/REPAIR: the steady-state fabric-read comparison — a
        polling fleet pays one liveness GET per node per reconcile
        tick, the watch fleet's established streams cover wipe
        detection (reads ~0; the one-time seeding relists reported
        separately) — and the watch fleet must still HEAL a slice
        wiped behind its driver. Acceptance: >= 5x fewer steady-state
        reads, pinned by test_perf_honesty on the committed artifact.

    Writes docs/bench_autopilot_r14.json ($BENCH_AUTOPILOT_OUT
    overrides; --quick defaults to the sibling *_quick file so the
    committed acceptance artifact is never clobbered by a smoke run).
    """
    from tpu_device_plugin import faults
    from tpu_device_plugin.autopilot import (AutopilotConfig,
                                             FleetAutopilot,
                                             measure_read_repair)

    if quick:
        cfg = AutopilotConfig(
            nodes=8, duration_s=25.0, claim_event_target=0, seed=1337,
            claim_workers=4, multiclaim_workers=1, flip_workers=1,
            unplug_workers=1, migration_workers=1, defrag_workers=1,
            upgrade_workers=1, upgrade_wave_size=2,
            boot_workers=1, boot_wave_size=4,
            pinned_per_nodes=4, invariant_interval_s=2.0)
    else:
        cfg = AutopilotConfig(
            # the storm runs until BOTH bounds are met: ≥30 min of
            # overlapping chaos AND ≥100k claim events — the duration
            # floor keeps the continuous invariant checker (one
            # full-fleet sweep is minutes at 256 nodes under storm
            # load) doing several passes DURING the run
            nodes=256, duration_s=1800.0, claim_event_target=100_000,
            # wall budget sized for the 100k-event target on a small
            # shared box, not a latency claim — the soak runs until
            # the event target lands
            max_wall_s=3300.0, seed=1337,
            # worker pools sized so the single GIL serves BOTH the
            # storm (48 claim workers landed ~190 events/s — 2.9x the
            # target, starving the checker to ~1 sweep / 5 min) and
            # the continuous invariant checker's full-fleet sweeps
            claim_workers=24, claims_per_batch=4,
            multiclaim_workers=2, flip_workers=4,
            unplug_workers=2, migration_workers=2, defrag_workers=2,
            upgrade_workers=2, upgrade_wave_size=8,
            boot_workers=2, boot_wave_size=16,
            pinned_per_nodes=8, invariant_interval_s=5.0,
            # production-shaped idle cost at 256 nodes: long-poll
            # rotations every 25 s and bookmarks every 5 s, so the GIL
            # serves claim events instead of stream-churn overhead
            watch_timeout_s=25.0, watch_resync_s=60.0,
            bookmark_interval_s=5.0)
    # CI's autopilot-smoke leg opts the self-heal drill into the soak
    # run (ISSUE 16): after the storms quiesce, the SAME fleet runs the
    # ramped-fault breach -> remediation -> rollback loop and the
    # report's selfheal_story carries the one-query reconstruction.
    if os.environ.get("BENCH_AUTOPILOT_SELFHEAL") == "1":
        cfg.selfheal = True
        cfg.selfheal_fault_ramp_s = 1.0
    pilot = FleetAutopilot(cfg)
    try:
        report = pilot.run(raise_on_violation=False)
    finally:
        faults.reset()
    read_repair = measure_read_repair(n_nodes=8 if quick else 16,
                                      rounds=12)
    out = {"quick": quick, "soak": report, "read_repair": read_repair}
    print(f"autopilot soak: nodes={cfg.nodes} "
          f"claim_events={report['counters']['claim_events']} "
          f"ok={report['ok']} violations={len(report['violations'])} | "
          f"read/repair {read_repair['poll_reads']} poll vs "
          f"{read_repair['watch_reads']} watch reads "
          f"({read_repair['read_reduction_x']}x)", file=sys.stderr)
    default_name = ("bench_autopilot_r14_quick.json" if quick
                    else "bench_autopilot_r14.json")
    out_path = os.environ.get("BENCH_AUTOPILOT_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    return {
        "metric": "autopilot_watch_read_reduction",
        "value": read_repair["read_reduction_x"],
        "unit": "x",
        # acceptance: >= 5x fewer steady-state fabric reads with the
        # watch plane, soak green under overlapping chaos
        "vs_baseline": round(read_repair["read_reduction_x"] / 5.0, 3),
        "baseline_source": "ISSUE 12 acceptance: autopilot soak "
                           "completes with every continuous invariant "
                           "green while kubeapi.watch faults fire, and "
                           "watch-driven convergence pays >= 5x fewer "
                           "steady-state fabric reads than guarded-PUT "
                           "read/repair polling",
        "soak_ok": report["ok"],
        "claim_events": report["counters"]["claim_events"],
        "invariant_checks": report["counters"]["invariant_checks"],
        "nodes": cfg.nodes,
        "matrix_file": out_path,
    }


def run_trace_fleet(quick=False):
    """`bench.py --trace-fleet` (r17): the fleet trace-propagation + SLO
    plane, end to end — make bench-trace-fleet.

    Three counted cells against ONE 256-node fleet (quick: 16):

      - SOAK: an autopilot soak (all storm types, watch chaos +
        kubeapi.watch faults firing) whose migrated pinned claim's
        cross-node story is reconstructed PURELY from the fleet trace
        query (fleetplace.FleetFlight — the /debug/fleet/trace?trace=
        body) at migration time, not stitched ad hoc.
      - WATERFALL: after quiesce, a scheduler-placed MULTI-HOST slice
        (fleetplace.FleetScheduler over the pod mesh) has one shard
        migrated cross-host via the PR 7 handoff machinery; a SINGLE
        trace= query must then replay every stage — scheduler decision,
        per-shard prepare, broker crossing, handoff, destination
        prepare — across >= 3 nodes plus the scheduler, time-ordered.
      - SLO: the publish_rtt burn-rate gauge provably moves under an
        injected kubeapi latency fault (the r17 faults kind "delay"),
        latches a multiwindow breach, and its exemplar trace id
        resolves to real spans on the same fleet trace query.

    Everything asserted here is COUNTED (ops present, nodes answering,
    burn deltas) — no wall-clock claims. Writes
    docs/bench_tracefleet_r17.json ($BENCH_TRACEFLEET_OUT overrides;
    --quick defaults to the sibling *_quick file)."""
    from tpu_device_plugin import faults, slo, trace
    from tpu_device_plugin.autopilot import AutopilotConfig, FleetAutopilot
    from tpu_device_plugin.fleetsim import FleetSim

    n_nodes = 16 if quick else 256
    sim = FleetSim(
        n_nodes=n_nodes, devices_per_node=8, latency_s=0.0,
        max_inflight=0, seed=17, watch=True,
        watch_resync_s=60.0, watch_poll_s=0.5,
        watch_timeout_s=2.0 if quick else 25.0,
        bookmark_interval_s=0.5 if quick else 5.0)
    try:
        trace.reset()
        # ---- cell 1: the autopilot soak, story from the fleet trace
        cfg = AutopilotConfig(
            nodes=n_nodes, devices_per_node=8, seed=17,
            duration_s=10.0 if quick else 60.0,
            claim_event_target=0 if quick else 2000,
            max_wall_s=120.0 if quick else 900.0,
            claim_workers=4 if quick else 16, claims_per_batch=4,
            multiclaim_workers=1, flip_workers=1 if quick else 2,
            unplug_workers=1, migration_workers=2, defrag_workers=1,
            upgrade_workers=1, upgrade_wave_size=2 if quick else 8,
            boot_workers=1, boot_wave_size=4 if quick else 16,
            pinned_per_nodes=2 if quick else 8,
            invariant_interval_s=2.0 if quick else 5.0,
            watch_timeout_s=2.0 if quick else 25.0,
            watch_resync_s=60.0,
            bookmark_interval_s=0.5 if quick else 5.0)
        pilot = FleetAutopilot(cfg, sim=sim)
        try:
            soak = pilot.run(raise_on_violation=False)
        finally:
            faults.reset()
        story = soak.get("claim_story")
        # ---- cell 2: the scheduler waterfall on the quiesced fleet
        trace.reset()        # a fresh ring: the waterfall must stand alone
        sched = sim.scheduler(watch=False)
        shape = "2x8"        # two whole (2,4) host tori on the pod mesh
        res = sched.schedule(shape, "wf-r17")
        if not res.get("placed"):
            raise AssertionError(
                f"waterfall claim unplaceable after quiesce: {res}")
        tid = res["trace_id"]
        shards = list(sched._claims["wf-r17"])
        sub_uid, src_name, raws = shards[0]
        used = {node for _s, node, _r in shards}
        dst = next(n for n in sim.nodes
                   if n.name not in used
                   and len(n.host_view().free) >= len(raws))
        sched.apply_defrag_wave({"migrations": [{
            "claim": sub_uid, "source_node": src_name,
            "target_node": dst.name, "devices": list(raws),
            "target_devices": sorted(dst.host_view().free)[:len(raws)]}]})
        waterfall = sim.fleet_flight().trace(tid)
        ops = set(waterfall["ops"])
        hosts = [n for n in waterfall["nodes"] if n != "scheduler"]
        prep_nodes = {r["node"] for r in waterfall["spans"]
                      if r["op"] == "dra.prepare.claim"}
        stages = {
            "scheduler_decision": "fleetplace.schedule" in ops,
            "per_shard_prepare": set(n for _s, n, _r in shards)
            <= prep_nodes,
            "broker_crossing": "broker.ipc" in ops,
            "source_release": "dra.unprepare.claim" in ops,
            "handoff": "dra.handoff.completed" in ops,
            "destination_prepare": dst.name in prep_nodes,
        }
        ts = [r["ts"] for r in waterfall["spans"]]
        wf_cell = {
            "trace_id": tid, "shape": shape,
            "hosts_planned": res["hosts"],
            "migrated_shard": sub_uid,
            "migration": f"{src_name} -> {dst.name}",
            "nodes": waterfall["nodes"],
            "host_count": len(hosts),
            "spans": len(waterfall["spans"]),
            "ops": sorted(ops),
            "stages": stages,
            "time_ordered": ts == sorted(ts),
            "single_query": f"/debug/fleet/trace?trace={tid}",
        }
        # ---- cell 3: SLO burn under injected latency, exemplar resolves
        clock = time.monotonic
        eng = slo.SLOEngine([slo.Objective(
            "publish_rtt", "tdp_kubeapi_rtt_ms", threshold_ms=100.0,
            target=0.99, fast_window_s=120.0, slow_window_s=600.0)],
            now=clock)
        victim = sim.nodes[0]
        victim.driver.publish_resource_slices()      # good baseline RTTs
        eng.evaluate()
        burn_before = eng.snapshot()["objectives"]["publish_rtt"][
            "burn_rate_fast"]
        faults.arm("kubeapi.request", kind="delay", count=6,
                   delay_s=0.15)
        try:
            with trace.span("bench.slow-publish"):
                slow_tid = trace.current_context()["trace_id"]
                victim.driver.api.get_json(
                    f"/api/v1/nodes/{victim.name}")
                victim.driver.api.get_json(
                    f"/api/v1/nodes/{victim.name}")
        finally:
            faults.disarm("kubeapi.request")
        time.sleep(1.1)            # past the engine's sample gap
        rec = eng.evaluate()["publish_rtt"]
        exemplar = (rec.get("exemplar") or {}).get("trace_id")
        resolved = bool(exemplar
                        and sim.fleet_flight().trace(exemplar)["spans"])
        slo_cell = {
            "burn_before": burn_before,
            "burn_after": rec["burn_rate_fast"],
            "bad_total": rec["bad_total"],
            "breached": rec["breached"],
            "breaches_total": eng.snapshot()["breaches_total"],
            "exemplar_trace": exemplar,
            "exemplar_is_injected_request": exemplar == slow_tid,
            "exemplar_resolved_on_fleet_trace": resolved,
        }
        out = {
            "metric": "tracefleet_waterfall_host_count",
            "value": len(hosts),
            "unit": "nodes",
            "baseline_source": (
                "ISSUE 15 acceptance: a 256-node autopilot soak cell "
                "reconstructs a migrated multi-host slice claim's full "
                "waterfall (scheduler decision -> per-shard prepare -> "
                "broker crossing -> handoff -> destination prepare) "
                "from a SINGLE /debug/fleet/trace?trace= query, and an "
                "SLO burn-rate gauge provably moves under an injected "
                "latency fault with its exemplar resolvable on the "
                "same query"),
            "quick": quick,
            "soak": {
                "nodes": n_nodes,
                "ok": soak["ok"],
                "violations": soak["violations"],
                "claim_events": soak["counters"]["claim_events"],
                "migrations": soak["counters"]["migrations"]
                + soak["counters"]["defrag_moves"],
                "claim_story": story,
            },
            "waterfall": wf_cell,
            "slo": slo_cell,
            "propagation": {k: v for k, v in trace.stats().items()
                            if k.startswith("ctx_")},
        }
    finally:
        faults.reset()
        sim.stop()
        trace.reset()
    default_name = ("bench_tracefleet_r17_quick.json" if quick
                    else "bench_tracefleet_r17.json")
    out_path = os.environ.get("BENCH_TRACEFLEET_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    out["matrix_file"] = out_path
    print(f"trace fleet: soak nodes={n_nodes} "
          f"events={out['soak']['claim_events']} ok={out['soak']['ok']} "
          f"story={'yes' if story else 'NO'} | waterfall hosts="
          f"{len(hosts)} stages={sum(stages.values())}/{len(stages)} | "
          f"slo burn {slo_cell['burn_before']} -> "
          f"{slo_cell['burn_after']} breached={slo_cell['breached']} "
          f"exemplar_resolved={slo_cell['exemplar_resolved_on_fleet_trace']}",
          file=sys.stderr)
    return out


def run_selfheal(quick=False):
    """`bench.py --selfheal` (r18): the SLO-closed-loop remediation
    acceptance run (tpu_device_plugin/remediation.py).

    One autopilot soak (256 nodes full / 16 quick) with the self-heal
    drill armed: after the storm quiesces, a RAMPED kubeapi delay fault
    (faults.py jitter_s/ramp_s) burns a publish-RTT SLO against one
    victim node. The report's selfheal_story must show every link of
    the closed loop — counted facts, never wall-clocked:

      - the burn RISES and the breach LATCHES with an exemplar trace;
      - the remediation engine acts through the policy remediate gate
        (call counted): pacer backoff floor on the victim + placement
        bias away from it (exemplar -> node attribution via the fleet
        trace collector);
      - good traffic dilutes the burn below target, the latched
        recovery fires, and EVERY knob rolls back;
      - ONE /debug/fleet/trace?trace=<exemplar> query replays the whole
        chain: the slow node-stamped publish, the remediation.action
        spans, the remediation.rollback spans.

    Writes docs/bench_selfheal_r18.json ($BENCH_SELFHEAL_OUT overrides;
    --quick defaults to the sibling *_quick file so the committed
    acceptance artifact is never clobbered by a smoke run)."""
    from tpu_device_plugin import faults
    from tpu_device_plugin import trace
    from tpu_device_plugin.autopilot import AutopilotConfig, FleetAutopilot

    n_nodes = 16 if quick else 256
    cfg = AutopilotConfig(
        nodes=n_nodes, devices_per_node=4, seed=18,
        duration_s=10.0 if quick else 60.0,
        max_wall_s=120.0 if quick else 900.0,
        claim_workers=4 if quick else 16, claims_per_batch=4,
        multiclaim_workers=1, flip_workers=1 if quick else 2,
        unplug_workers=1, migration_workers=1, defrag_workers=1,
        upgrade_workers=1, upgrade_wave_size=2 if quick else 8,
        boot_workers=1, boot_wave_size=4 if quick else 16,
        pinned_per_nodes=4 if quick else 8,
        invariant_interval_s=2.0 if quick else 5.0,
        watch_timeout_s=2.0 if quick else 25.0, watch_resync_s=60.0,
        bookmark_interval_s=0.5 if quick else 5.0,
        selfheal=True)
    trace.reset()
    pilot = FleetAutopilot(cfg)
    try:
        soak = pilot.run(raise_on_violation=False)
    finally:
        faults.reset()
        trace.reset()
    story = soak.get("selfheal_story") or {}
    chain = {
        "breach_latched": bool(story.get("breached")),
        "action_applied": bool(story.get("actions")),
        "policy_gated": bool(story.get("policy_remediate_calls")),
        "victim_attributed": story.get("victim") in
        (story.get("nodes") or ()),
        "recovered": bool(story.get("recovered")),
        "rolled_back": bool(story.get("rollbacks")),
        "one_query_complete": all(
            op in (story.get("ops") or ())
            for op in ("kubeapi.request", "remediation.action",
                       "remediation.rollback")),
    }
    out = {
        "metric": "selfheal_closed_loop_links",
        "value": sum(chain.values()),
        "unit": "links",
        "vs_baseline": round(sum(chain.values()) / len(chain), 3),
        "baseline_source": (
            "ISSUE 16 acceptance: a 256-node autopilot soak with an "
            "injected ramped delay fault shows burn rise -> breach "
            "latch -> policy-approved audited remediation (pacer "
            "backoff + placement bias via exemplar->node attribution) "
            "-> burn recovery -> knob rollback, the full chain "
            "reconstructed from ONE /debug/fleet/trace?trace= query"),
        "quick": quick,
        "soak": {
            "nodes": n_nodes,
            "ok": soak.get("ok", False),
            "violations": soak.get("violations", ["soak missing"]),
            "claim_events": soak.get("counters", {}).get(
                "claim_events", 0),
        },
        "chain": chain,
        "story": story,
    }
    out_ok = out["soak"]["ok"] and all(chain.values())
    default_name = ("bench_selfheal_r18_quick.json" if quick
                    else "bench_selfheal_r18.json")
    out_path = os.environ.get("BENCH_SELFHEAL_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    out["matrix_file"] = out_path
    print(f"selfheal: soak nodes={n_nodes} "
          f"events={out['soak']['claim_events']} ok={out['soak']['ok']} | "
          f"chain {sum(chain.values())}/{len(chain)} "
          f"(burn {story.get('burn_at_breach')} -> "
          f"{story.get('burn_at_recovery')}, actions="
          f"{story.get('actions')}, rollbacks={story.get('rollbacks')}) | "
          f"closed_loop={'yes' if out_ok else 'NO'}", file=sys.stderr)
    return out


# ------------------------------------------- restart-to-ready (round 21)


def _restart_boot(cfg):
    """One counted PluginManager boot against a live fake kubelet:
    {"wall_ms", "reads", "plugins", ...boot_stats}. The wall clock wraps
    start() itself — everything the daemon pays before its run loop,
    including the cold boot's snapshot seed write (the warm path skips
    the re-save when the cache just validated clean, so the asymmetry is
    the code's, not the harness's)."""
    from tpu_device_plugin.lifecycle import PluginManager
    mgr = PluginManager(cfg)
    t0 = time.monotonic()
    with count_reads() as counter:
        mgr.start()
    wall_ms = round((time.monotonic() - t0) * 1e3, 3)
    cell = dict(mgr.boot_stats)
    cell["wall_ms"] = wall_ms
    cell["reads"] = counter.reads
    cell["plugins"] = len(mgr.plugins)
    mgr.stop()
    return cell


def _restart_host(n_devices, build=None):
    """(root, cfg, kubelet) for one restart cell; caller cleans up."""
    from tests.fakehost import FakeKubelet
    root = tempfile.mkdtemp(prefix="tdp-restart-")
    if build is None:
        _build_host(root, n_devices)
    else:
        build(root)
    cfg = Config().with_root(root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    return root, cfg, FakeKubelet(cfg.kubelet_socket)


def _restart_single_cell(n_devices, cold_runs=2, warm_runs=3):
    """Cold vs snapshot-warm restart at one device count. Each cold
    sample deletes the cache first (a real first boot); warm samples
    reuse the cache the last cold run seeded. Medians, plus the counted
    read totals the honesty pin locks."""
    root, cfg, kubelet = _restart_host(n_devices)
    try:
        colds, warms = [], []
        for _ in range(cold_runs):
            try:
                os.unlink(cfg.discovery_snapshot_path)
            except OSError:
                pass
            colds.append(_restart_boot(cfg))
        for _ in range(warm_runs):
            warms.append(_restart_boot(cfg))
        for c in colds:
            assert c["boot_path"] == "cold", c
        for w in warms:
            assert w["boot_path"] == "snapshot" and w["invalidated"] == 0, w
        cold_ms = statistics.median(c["wall_ms"] for c in colds)
        warm_ms = statistics.median(w["wall_ms"] for w in warms)
        return {
            "devices": n_devices,
            "cold_wall_ms": round(cold_ms, 3),
            "warm_wall_ms": round(warm_ms, 3),
            "wall_ratio": round(cold_ms / max(1e-9, warm_ms), 2),
            "cold_reads": colds[-1]["reads"],
            "warm_reads": warms[-1]["reads"],
            "reads_ratio": round(colds[-1]["reads"]
                                 / max(1, warms[-1]["reads"]), 1),
            "cold_ready_ms": round(statistics.median(
                c["restart_ready_ms"] for c in colds), 3),
            "warm_ready_ms": round(statistics.median(
                w["restart_ready_ms"] for w in warms), 3),
            "samples": {"cold": cold_runs, "warm": warm_runs},
        }
    finally:
        kubelet.stop()
        shutil.rmtree(root, ignore_errors=True)


def _restart_two_wave_cell():
    """Two models on one host; after the cache is seeded, one model-B
    chip leaves (membership change — the invalidation the revalidation
    stat pass detects without dirty hints). The warm boot must ship the
    intact model in wave 1 (first-resource-ready) and converge the
    tainted one from cold reads in wave 2 (all-resources-ready),
    STRICTLY later."""
    def build(root):
        host = FakeHost(root)
        for i in range(8):
            host.add_chip(FakeChip(f"0000:01:{4 + i:02x}.0",
                                   device_id="0062",
                                   iommu_group=str(11 + i), numa_node=0))
        for i in range(8):
            host.add_chip(FakeChip(f"0000:02:{4 + i:02x}.0",
                                   device_id="0063",
                                   iommu_group=str(31 + i), numa_node=1))

    root, cfg, kubelet = _restart_host(0, build=build)
    try:
        seed = _restart_boot(cfg)
        assert seed["boot_path"] == "cold" and seed["plugins"] == 2, seed
        shutil.rmtree(os.path.join(cfg.pci_base_path, "0000:02:04.0"))
        warm = _restart_boot(cfg)
        assert warm["boot_path"] == "snapshot", warm
        assert warm["invalidated"] >= 1, warm
        first = warm["first_resource_ready_ms"]
        alldone = warm["all_resources_ready_ms"]
        assert first < alldone, (
            f"wave 1 must strictly precede wave 2: {first} vs {alldone}")
        return {
            "invalidated": warm["invalidated"],
            "first_resource_ready_ms": first,
            "all_resources_ready_ms": alldone,
            "first_strictly_before_all": True,
            "plugins": warm["plugins"],
        }
    finally:
        kubelet.stop()
        shutil.rmtree(root, ignore_errors=True)


def _restart_corrupt_cell(n_devices=64):
    """A torn/garbage cache must NEVER be trusted: boot falls back to
    the counted cold walk, converges, and (because the cold walk
    re-seeds the cache atomically) the NEXT boot goes warm again."""
    root, cfg, kubelet = _restart_host(n_devices)
    try:
        seed = _restart_boot(cfg)
        with open(cfg.discovery_snapshot_path, "w") as f:
            f.write('{"version": 1, "records": {')   # torn mid-write
        corrupt = _restart_boot(cfg)
        assert corrupt["boot_path"] == "cold", corrupt
        assert corrupt["snapshot_outcome"] == "corrupt", corrupt
        assert corrupt["plugins"] == seed["plugins"], corrupt
        healed = _restart_boot(cfg)
        assert healed["boot_path"] == "snapshot", healed
        return {
            "devices": n_devices,
            "fallback_outcome": corrupt["snapshot_outcome"],
            "fallback_reads": corrupt["reads"],
            "fallback_converged": corrupt["plugins"] == seed["plugins"],
            "next_boot_warm": healed["boot_path"] == "snapshot",
        }
    finally:
        kubelet.stop()
        shutil.rmtree(root, ignore_errors=True)


def _restart_claims_cell():
    """Claims across the restart boundary: prepare against a live
    fabric, cold-restart (seeds the cache), warm-restart, replay the
    same claims (idempotent prepare must ride the restored pre-
    serialized ack bytes), then run the full fleet invariant sweep —
    exactly-once on the fabric audit, zero lost claims, zero orphan
    specs."""
    from tpu_device_plugin.fleetsim import FleetSim, fleet_invariants

    sim = FleetSim(n_nodes=1, devices_per_node=8, latency_s=0.0, seed=21)
    try:
        node = sim.nodes[0]
        assert node.boot()
        uids = node.register_claims(4)
        resp = node.attach(uids)
        assert not any(resp.claims[u].error for u in uids), resp
        prepared = node.driver.prepared_claim_count()
        cold = node.restart_with_discovery(warm=True)    # no cache yet
        warm = node.restart_with_discovery(warm=True)
        assert cold["path"] == "cold" and warm["path"] == "snapshot", (
            cold, warm)
        assert node.driver.prepared_claim_count() == prepared
        replay = node.attach(uids)   # kubelet replay after restart
        assert not any(replay.claims[u].error for u in uids), replay
        ack = node.driver.ack_byte_stats()
        inv = fleet_invariants(sim, confirm=lambda: None)
        assert inv["ok"], inv["violations"]
        return {
            "prepared_claims": prepared,
            "cold_restart_reads": cold["reads"],
            "warm_restart_reads": warm["reads"],
            "replay_ack_bytes_reused": ack["reused"],
            "exactly_once": inv["ok"],
            "violations": inv["violations"],
        }
    finally:
        sim.stop()


def _restart_rolling_cell(n_nodes, devices_per_node, batch_size,
                          sysfs_read_cost_s=0.0005):
    """The fleet-operations shape: a rolling daemon upgrade where every
    node pays its restart INCLUDING discovery. Baseline wave = the
    pre-snapshot daemon (full cold walk + identity reads every time);
    then a seeding wave (first warm-path restart per node is cold and
    writes the cache) and the measured FAST wave where every node rides
    the snapshot. Headline: node-seconds-unready, baseline vs fast.

    `sysfs_read_cost_s` (0.5 ms/access) models real-host sysfs/config-
    space IO the same way the fabric models service time (the sim's
    tmpfs reads are ~free); the charge is counted-reads x cost INSIDE
    each node's unready window, so both waves pay for exactly the IO
    they do — the ratio is the read-count ratio doing the work, not a
    thumb on the scale (reads_total is recorded beside it)."""
    from tpu_device_plugin.fleetsim import FleetSim, fleet_invariants

    sim = FleetSim(n_nodes=n_nodes, devices_per_node=devices_per_node,
                   latency_s=0.0, seed=21, build_workers=16)
    try:
        results = sim._storm(lambda n: n.boot())
        assert all(results), "boot storm failed"
        storm = sim.attach_storm(claims_per_node=2)
        assert not storm["errors"], storm["errors"]
        baseline = sim.rolling_upgrade_wave(
            batch_size=batch_size, warm=False,
            sysfs_read_cost_s=sysfs_read_cost_s)
        seeding = sim.rolling_upgrade_wave(
            batch_size=batch_size, warm=True,
            sysfs_read_cost_s=sysfs_read_cost_s)
        fast = sim.rolling_upgrade_wave(
            batch_size=batch_size, warm=True,
            sysfs_read_cost_s=sysfs_read_cost_s)
        assert seeding["paths"] == {"cold": n_nodes}, seeding["paths"]
        assert fast["paths"] == {"snapshot": n_nodes}, fast["paths"]
        inv = fleet_invariants(sim, confirm=lambda: None)
        assert inv["ok"], inv["violations"]
        ratio = round(baseline["node_seconds_unready"]
                      / max(1e-9, fast["node_seconds_unready"]), 2)
        return {
            "nodes": n_nodes,
            "devices_per_node": devices_per_node,
            "batch_size": batch_size,
            "claims_per_node": 2,
            "baseline": baseline,
            "seeding": seeding,
            "fast": fast,
            "unready_ratio": ratio,
            "exactly_once": inv["ok"],
        }
    finally:
        sim.stop()


def run_restart(quick=False):
    """`bench.py --restart` (r21): restart-to-ready — the persisted
    discovery snapshot + parallel boot pipeline vs the classic cold
    walk (make bench-restart).

    Cells (assertions are the acceptance pins; test_perf_honesty locks
    the committed artifact):

      - SINGLE NODE at {64, 4096} devices ({64} quick): counted cold
        boot (full sysfs walk + per-device identity reads + cache seed)
        vs snapshot-warm boot (load + one batched revalidation pass).
        Headline: warm >= 10x fewer counted reads AND >= 3x lower
        restart-to-ready wall at 4096.
      - TWO-WAVE: a membership change under the cache makes wave 1
        register the intact resource straight from the snapshot while
        wave 2 cold-reads only the tainted model —
        first-resource-ready STRICTLY before all-resources-ready.
      - CORRUPT CACHE: torn-mid-write garbage is refused, boot degrades
        to the counted cold walk, converges, and re-seeds (next boot
        warm again).
      - CLAIMS EXACTLY-ONCE: prepared claims survive cold AND warm
        restarts; the kubelet's post-restart replay rides the restored
        pre-serialized ack bytes; full fleet invariant sweep green.
      - ROLLING UPGRADE at 256 nodes x 16 devices (16 x 4 quick),
        batches of 16: node-seconds-unready, pre-snapshot baseline vs
        the fast path — >= 2x better.

    Writes docs/bench_restart_r21.json ($BENCH_RESTART_OUT overrides;
    --quick lands in a sibling *_quick file so the committed artifact
    the perf-honesty pin reads is never clobbered).
    """
    out = {"quick": quick}
    sizes = (64,) if quick else (64, 4096)
    out["single_node"] = [_restart_single_cell(n) for n in sizes]
    for cell in out["single_node"]:
        print(f"  single n={cell['devices']}: cold "
              f"{cell['cold_wall_ms']} ms/{cell['cold_reads']} reads | "
              f"warm {cell['warm_wall_ms']} ms/{cell['warm_reads']} "
              f"reads | wall {cell['wall_ratio']}x reads "
              f"{cell['reads_ratio']}x", file=sys.stderr)
    out["two_wave"] = _restart_two_wave_cell()
    print(f"  two-wave: invalidated={out['two_wave']['invalidated']} "
          f"first {out['two_wave']['first_resource_ready_ms']} ms < all "
          f"{out['two_wave']['all_resources_ready_ms']} ms",
          file=sys.stderr)
    out["corrupt_cache"] = _restart_corrupt_cell()
    print(f"  corrupt: outcome={out['corrupt_cache']['fallback_outcome']}"
          f" converged={out['corrupt_cache']['fallback_converged']} "
          f"next_warm={out['corrupt_cache']['next_boot_warm']}",
          file=sys.stderr)
    out["claims"] = _restart_claims_cell()
    print(f"  claims: prepared={out['claims']['prepared_claims']} "
          f"survive cold+warm, ack reuse="
          f"{out['claims']['replay_ack_bytes_reused']}B, exactly_once="
          f"{out['claims']['exactly_once']}", file=sys.stderr)
    out["rolling_upgrade"] = (_restart_rolling_cell(16, 4, 8) if quick
                              else _restart_rolling_cell(256, 16, 16))
    roll = out["rolling_upgrade"]
    print(f"  rolling n={roll['nodes']}: baseline "
          f"{roll['baseline']['node_seconds_unready']} node-s | fast "
          f"{roll['fast']['node_seconds_unready']} node-s | "
          f"{roll['unready_ratio']}x", file=sys.stderr)

    key = out["single_node"][-1]
    if not quick:
        assert key["reads_ratio"] >= 10.0, (
            f"warm reads ratio {key['reads_ratio']}x < 10x floor")
        assert key["wall_ratio"] >= 3.0, (
            f"warm wall ratio {key['wall_ratio']}x < 3x floor")
        assert roll["unready_ratio"] >= 2.0, (
            f"rolling unready ratio {roll['unready_ratio']}x < 2x floor")
    default_name = ("bench_restart_r21_quick.json" if quick
                    else "bench_restart_r21.json")
    out_path = os.environ.get("BENCH_RESTART_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", default_name)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    return {
        "metric": "restart_ready_warm_speedup",
        "value": key["wall_ratio"],
        "unit": "x",
        "vs_baseline": round(key["wall_ratio"] / 3.0, 3),
        "baseline_source": "ISSUE 19 acceptance: snapshot-warm restart "
                           ">= 10x fewer counted sysfs reads AND >= 3x "
                           "lower restart-to-ready wall than the cold "
                           "walk at 4096 devices; first-resource-ready "
                           "strictly before all-resources-ready; claims "
                           "exactly-once across restart; corrupt cache "
                           "falls back cold and converges; rolling "
                           "upgrade >= 2x less node-seconds-unready",
        "reads_ratio": key["reads_ratio"],
        "warm_wall_ms": key["warm_wall_ms"],
        "cold_wall_ms": key["cold_wall_ms"],
        "rolling_unready_ratio": roll["unready_ratio"],
        "exactly_once": out["claims"]["exactly_once"]
        and roll["exactly_once"],
        "matrix_file": os.path.relpath(
            out_path, os.path.dirname(os.path.abspath(__file__))),
    }


def main() -> int:
    import logging
    logging.disable(logging.CRITICAL)  # keep the one-line contract

    if "--selfheal" in sys.argv:
        out = run_selfheal(quick="--quick" in sys.argv)
        print(json.dumps(out))
        # the CI smoke leg must go red when any link of the closed
        # loop is missing — the artifact is still written above
        ok = out["soak"]["ok"] and all(out["chain"].values())
        return 0 if ok else 1
    if "--trace-fleet" in sys.argv:
        out = run_trace_fleet(quick="--quick" in sys.argv)
        print(json.dumps(out))
        ok = (out["soak"]["ok"] and all(out["waterfall"]["stages"]
                                        .values())
              and out["slo"]["exemplar_resolved_on_fleet_trace"])
        return 0 if ok else 1
    if "--autopilot" in sys.argv:
        out = run_autopilot(quick="--quick" in sys.argv)
        print(json.dumps(out))
        # the CI smoke leg (and make soak-autopilot) must go red when
        # the soak ends with invariant violations — the report is still
        # printed and the artifact still written for the post-mortem
        return 0 if out["soak_ok"] else 1
    if "--restart" in sys.argv:
        print(json.dumps(run_restart(quick="--quick" in sys.argv)))
        return 0
    if "--brokeripc" in sys.argv:
        print(json.dumps(run_brokeripc(quick="--quick" in sys.argv)))
        return 0
    if "--broker" in sys.argv:
        print(json.dumps(run_broker(quick="--quick" in sys.argv)))
        return 0
    if "--fleetsched" in sys.argv:
        print(json.dumps(run_fleetsched(quick="--quick" in sys.argv)))
        return 0
    if "--fleet-placement" in sys.argv:
        print(json.dumps(run_fleet_placement(quick="--quick" in sys.argv)))
        return 0
    if "--placement" in sys.argv:
        print(json.dumps(run_placement(quick="--quick" in sys.argv)))
        return 0
    if "--fleet" in sys.argv:
        print(json.dumps(run_fleet(quick="--quick" in sys.argv)))
        return 0
    if "--scale" in sys.argv:
        print(json.dumps(run_scale(quick="--quick" in sys.argv)))
        return 0
    if "--discovery" in sys.argv:
        print(json.dumps(run_discovery()))
        return 0
    if "--health" in sys.argv:
        print(json.dumps(run_health()))
        return 0
    if "--attach-burst" in sys.argv:
        print(json.dumps(run_attach_burst()))
        return 0
    if "--trace-overhead" in sys.argv:
        print(json.dumps(run_trace_overhead(quick="--quick" in sys.argv)))
        return 0
    if "--transport" in sys.argv:
        print(json.dumps(run_transport(quick="--quick" in sys.argv)))
        return 0
    if "--attach" in sys.argv:
        result = run_attach(quick="--quick" in sys.argv)
        # the r10 tracing-overhead artifact rides the same invocation so
        # the CI bench-smoke job exercises both (docs/bench_attach_r10.json)
        trace_result = run_trace_overhead(quick="--quick" in sys.argv)
        result["trace_overhead_file"] = trace_result["matrix_file"]
        print(json.dumps(result))
        return 0
    root = tempfile.mkdtemp(prefix="tdpbench-")
    try:
        result = run_config1(root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if "--matrix" in sys.argv:
        run_matrix()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
