"""PluginManager: per-resource plugin fan-out, error tolerance, run loop."""

import os
import threading
import time
from dataclasses import replace

import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin.config import Config
from tpu_device_plugin.lifecycle import PluginManager


@pytest.fixture
def kubelet(short_root):
    host = FakeHost(short_root)
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kub = FakeKubelet(cfg.kubelet_socket)
    yield host, cfg, kub
    kub.stop()


def test_manager_starts_plugin_per_resource(kubelet):
    host, cfg, kub = kubelet
    # two generations: 4x v4 (0062) + 2x v5e (0063), plus mdev partitions
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0062",
                               iommu_group=str(11 + i)))
    for i in range(2):
        host.add_chip(FakeChip(f"0000:01:{i:02x}.0", device_id="0063",
                               iommu_group=str(21 + i)))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="31")

    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kub.wait_for(3)
        names = sorted(r.resource_name for r in kub.registrations)
        assert names == [
            "cloud-tpus.google.com/TPU_vhalf",
            "cloud-tpus.google.com/v4",
            "cloud-tpus.google.com/v5e",
        ]
        socks = sorted(os.listdir(cfg.device_plugin_path))
        assert "tpukubevirt-v4.sock" in socks
        assert "tpukubevirt-v5e.sock" in socks
        assert "tpukubevirt-vtpu-TPU_vhalf.sock" in socks
    finally:
        manager.stop()
    assert all(not os.path.exists(os.path.join(cfg.device_plugin_path, s))
               for s in ("tpukubevirt-v4.sock", "tpukubevirt-v5e.sock"))


def test_manager_tolerates_partial_start_failure(kubelet, monkeypatch):
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))

    from tpu_device_plugin import server as server_mod

    orig_start = server_mod.TpuDevicePlugin.start

    def flaky_start(self):
        if self.resource_suffix == "v4":
            raise RuntimeError("boom")
        orig_start(self)

    monkeypatch.setattr(server_mod.TpuDevicePlugin, "start", flaky_start)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kub.wait_for(1)
        # the failed plugin stays pending for retry; the healthy one serves
        assert [p.resource_suffix for p in manager.pending] == ["v4"]
        assert kub.registrations[0].resource_name == "cloud-tpus.google.com/v5e"
    finally:
        manager.stop()


def test_pending_plugins_start_concurrently(kubelet, monkeypatch):
    """Cold start must overlap plugin start()s: with two resources, both
    starts must be in flight at once (a barrier only passable concurrently),
    instead of the old serial for-loop."""
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))

    from tpu_device_plugin import server as server_mod

    orig_start = server_mod.TpuDevicePlugin.start
    barrier = threading.Barrier(2)

    def rendezvous_start(self):
        # a serial loop deadlocks here (BrokenBarrierError after timeout),
        # leaving both plugins pending — the assert below catches it
        barrier.wait(timeout=10)
        orig_start(self)

    monkeypatch.setattr(server_mod.TpuDevicePlugin, "start", rendezvous_start)
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert manager.pending == [], \
            "plugins did not start concurrently (barrier never filled)"
        assert kub.wait_for(2)
    finally:
        manager.stop()


def test_manager_shares_one_health_hub_across_plugins(kubelet):
    """All plugin servers ride the manager's hub: one inotify fd however
    many resources, and no plugin spins up a private hub."""
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="31")
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kub.wait_for(3)
        assert len(manager.plugins) == 3
        for p in manager.plugins:
            assert p._health_hub is manager.health_hub
            assert p._own_hub is None
        stats = manager.health_stats()
        assert stats["inotify_fds"] == 1
        # 3 plugin subscriptions + the manager's lifecycle-FSM fs watch
        assert stats["subscriptions"] == 4
    finally:
        manager.stop()
    assert manager.health_stats()["subscriptions"] == 0


def test_plugin_started_late_when_kubelet_appears(short_root):
    """Plugin pod up before the kubelet: registration must retry, not die."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = replace(Config().with_root(host.root), grpc_timeout_s=1.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        time.sleep(1.5)  # first start attempt fails: no kubelet socket yet
        assert len(manager.pending) == 1
        kub2 = FakeKubelet(cfg.kubelet_socket)
        try:
            assert kub2.wait_for(1, timeout=15), \
                "plugin never registered after kubelet came up"
            deadline = time.monotonic() + 5
            while manager.pending and time.monotonic() < deadline:
                time.sleep(0.05)
            assert manager.pending == []
        finally:
            kub2.stop()
    finally:
        stop.set()
        t.join(timeout=10)


def test_run_loop_stops_on_event(kubelet):
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    assert kub.wait_for(1)
    stop.set()
    t.join(timeout=10)
    assert not t.is_alive()
    assert manager.plugins == []


def test_rediscovery_restarts_on_inventory_change(kubelet):
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = replace(cfg, rediscovery_interval_s=0.3)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        # hotplug a second chip -> manager must notice and re-register
        host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
        assert kub.wait_for(2, timeout=15)
    finally:
        stop.set()
        t.join(timeout=10)


def test_discover_only_dumps_inventory(tmp_path, capsys):
    """--discover-only prints the inventory JSON and exits without touching
    the kubelet (no socket exists and nothing fails)."""
    import json
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    from tpu_device_plugin.cli import main
    rc = main(["--root", str(tmp_path), "--discover-only"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["devices"]["0062"][0]["bdf"] == "0000:00:04.0"
    assert payload["partitions"]["TPU_vhalf"][0]["uuid"] == "uuid-1"
    assert payload["iommu_groups"]["11"] == ["0000:00:04.0"]
    assert payload["node_facts"]["cloud-tpus.google.com/v4.chips"] == "1"
    assert payload["unmatched_device_ids"] == []


def test_discover_only_warns_per_unmatched_id(tmp_path, capsys, caplog):
    """An id outside the generation table gets a per-id warning naming the
    fallback resource (the packaged ids are placeholders — operators must
    learn they need --generation-map before names mean anything)."""
    import json
    import logging
    host = FakeHost(tmp_path)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           device_id="00ff"))
    from tpu_device_plugin.cli import main
    with caplog.at_level(logging.WARNING):
        rc = main(["--root", str(tmp_path), "--discover-only"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["unmatched_device_ids"] == ["00ff"]
    warnings = [r for r in caplog.records
                if "not in the generation table" in r.getMessage()]
    assert len(warnings) == 1
    assert "00ff" in warnings[0].getMessage()
    assert "TPU_00FF" in warnings[0].getMessage()  # the fallback name


def test_incremental_rediscovery_spares_unchanged_resources(kubelet):
    """Hotplugging a chip of model B must restart ONLY model B's plugin;
    model A keeps serving with no re-registration (no advertisement blip)."""
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))
    cfg = replace(cfg, rediscovery_interval_s=0.3)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(2)
        plugin_a = next(p for p in manager.plugins
                        if p.resource_suffix == "v4")
        # hotplug a second v5e chip
        host.add_chip(FakeChip("0000:01:01.0", device_id="0063",
                               iommu_group="22"))
        assert kub.wait_for(3, timeout=15)  # only v5e re-registers
        time.sleep(0.5)  # a further tick must not churn anything
        names = [r.resource_name for r in kub.registrations]
        assert names.count("cloud-tpus.google.com/v4") == 1
        assert names.count("cloud-tpus.google.com/v5e") == 2
        # the v4 plugin OBJECT survived — same instance, still serving
        assert any(p is plugin_a for p in manager.plugins)
        assert plugin_a.serving
    finally:
        stop.set()
        t.join(timeout=10)


def test_incremental_rediscovery_stops_removed_resource(kubelet):
    """A vanished model's plugin is stopped (socket gone); others survive."""
    import shutil
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))
    cfg = replace(cfg, rediscovery_interval_s=0.3)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(2)
        v4_sock = os.path.join(cfg.device_plugin_path, "tpukubevirt-v4.sock")
        v5e_sock = os.path.join(cfg.device_plugin_path, "tpukubevirt-v5e.sock")
        assert os.path.exists(v4_sock) and os.path.exists(v5e_sock)
        # the v4 chip vanishes from sysfs
        shutil.rmtree(os.path.join(host.pci, "0000:00:04.0"))
        deadline = time.monotonic() + 10
        while os.path.exists(v4_sock) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not os.path.exists(v4_sock), "removed resource still serving"
        assert os.path.exists(v5e_sock)
        # the socket vanishes inside stop() before _apply_inventory swaps
        # the plugin list — poll rather than assert instantly
        deadline = time.monotonic() + 5
        while [p.resource_suffix for p in manager.plugins] != ["v5e"] \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert [p.resource_suffix for p in manager.plugins] == ["v5e"]
    finally:
        stop.set()
        t.join(timeout=10)


def test_shared_group_change_restarts_coupled_resource(kubelet):
    """A chip of another model joining a group the v4 plugin allocates must
    restart the v4 plugin too (its group expansion changed) — per-resource
    signatures include full IOMMU group membership."""
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    cfg = replace(cfg, rediscovery_interval_s=0.3)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        # a v5e chip lands in the SAME iommu group (no ACS isolation)
        host.add_chip(FakeChip("0000:01:00.0", device_id="0063",
                               iommu_group="11"))
        # BOTH plugins (re-)register: v4 restarted + v5e new
        assert kub.wait_for(3, timeout=15)
        names = [r.resource_name for r in kub.registrations]
        assert names.count("cloud-tpus.google.com/v4") == 2
        assert names.count("cloud-tpus.google.com/v5e") == 1
    finally:
        stop.set()
        t.join(timeout=10)


def test_timer_ticks_use_dirty_set_rescan_not_full_walk(kubelet):
    """Steady-state rediscovery ticks must go through the HostSnapshot's
    dirty-set path: after the boot full walk, a change-free tick reads NO
    per-device sysfs files (asserted via the discovery module's
    read-counting shim) and restarts nothing."""
    from tpu_device_plugin import discovery as disc
    host, cfg, kub = kubelet
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    cfg = replace(cfg, rediscovery_interval_s=0.2)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        # let the boot full walk finish, then watch two+ steady ticks
        time.sleep(0.3)
        with disc.count_reads() as w:
            time.sleep(0.7)
        per_device = [p for p in w.paths if "/devices/0000:" in p]
        assert per_device == [], per_device
        stats = manager.discovery_stats()
        assert stats["incremental"] is True
        assert stats["full_scans"] == 1
        assert stats["dirty_rescans"] >= 2
        assert len(kub.registrations) == 1        # nothing churned
    finally:
        stop.set()
        t.join(timeout=10)


def test_timer_hotplug_reads_only_the_new_bdf(kubelet):
    """A chip added between ticks is picked up via the listdir diff: the
    rescan reads the NEW chip's files and no unchanged BDF's."""
    from tpu_device_plugin import discovery as disc
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    cfg = replace(cfg, rediscovery_interval_s=0.2)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        time.sleep(0.3)
        with disc.count_reads() as w:
            host.add_chip(FakeChip("0000:01:00.0", device_id="0063",
                                   iommu_group="21"))
            assert kub.wait_for(2, timeout=15)    # v5e plugin came up
        touched = {p for p in w.paths if "/devices/0000:" in p}
        assert touched, "rescan never read the hotplugged chip"
        assert all("0000:01:00.0" in p for p in touched), touched
    finally:
        stop.set()
        t.join(timeout=10)


def test_timer_flap_dirties_only_flapped_device_and_recovers(kubelet):
    """A vfio flap between ticks feeds the flapped chip into the dirty set
    (via the manager's health-listener seam): the next rescans re-read ONLY
    that BDF, the device is never permanently lost (chaos invariant), and
    no plugin restarts (the record itself never changed)."""
    from tpu_device_plugin import discovery as disc
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", device_id="0062", iommu_group="12"))
    cfg = replace(cfg, rediscovery_interval_s=0.2, health_poll_s=0.1)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        plugin = manager.plugins[0]
        time.sleep(0.3)
        with disc.count_reads() as w:
            host.remove_vfio_group("11")          # chip 04 flaps Unhealthy
            deadline = time.monotonic() + 5
            while plugin.status_snapshot()["devices"]["0000:00:04.0"] \
                    != "Unhealthy" and time.monotonic() < deadline:
                time.sleep(0.05)
            time.sleep(0.5)                       # a tick drains the hint
        touched = {p for p in w.paths if "/devices/0000:" in p}
        assert touched, "flap never dirtied a rescan"
        assert all("0000:00:04.0" in p for p in touched), touched
        # chaos invariant: the node restores, no permanent device loss
        with open(os.path.join(host.devfs, "vfio", "11"), "w"):
            pass
        deadline = time.monotonic() + 10
        while plugin.status_snapshot()["devices"]["0000:00:04.0"] \
                != "Healthy" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert plugin.status_snapshot()["devices"]["0000:00:04.0"] == \
            "Healthy"
        assert len(kub.registrations) == 1        # flap != inventory change
    finally:
        stop.set()
        t.join(timeout=10)


def test_full_rescan_flag_disables_snapshot(kubelet):
    """--full-rescan (incremental_rediscovery=False) keeps the classic full
    walk on every tick — per-device reads on each one."""
    from tpu_device_plugin import discovery as disc
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = replace(cfg, rediscovery_interval_s=0.2,
                  incremental_rediscovery=False)
    manager = PluginManager(cfg)
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kub.wait_for(1)
        with disc.count_reads() as w:
            time.sleep(0.7)
        assert [p for p in w.paths if "/devices/0000:" in p]
        assert manager.discovery_stats() == {"incremental": False}
        assert manager.snapshot is None
    finally:
        stop.set()
        t.join(timeout=10)


def test_daemon_sigterm_clean_shutdown(short_root):
    """The real process contract: SIGTERM -> exit 0, sockets removed."""
    import signal
    import subprocess
    import sys
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kub = FakeKubelet(cfg.kubelet_socket)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_device_plugin", "--root", host.root],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        assert kub.wait_for(1, timeout=15)
        sock = os.path.join(cfg.device_plugin_path, "tpukubevirt-v4.sock")
        assert os.path.exists(sock)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=15)
        assert proc.returncode == 0, out[-500:]
        assert not os.path.exists(sock), "socket left behind after SIGTERM"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
        kub.stop()


def test_drain_and_undrain(kubelet):
    """Drain marks every device Unhealthy via an ANDed source; undrain
    restores — unless another source is genuinely unhealthy."""
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kub.wait_for(1)
        plugin = manager.plugins[0]
        manager.drain(True)
        assert plugin.status_snapshot()["devices"]["0000:00:04.0"] == "Unhealthy"
        # a real failure during the drain window
        plugin.set_group_health("11", False, "fs")
        manager.drain(False)
        # undrain must NOT mask the real failure
        assert plugin.status_snapshot()["devices"]["0000:00:04.0"] == "Unhealthy"
        plugin.set_group_health("11", True, "fs")
        assert plugin.status_snapshot()["devices"]["0000:00:04.0"] == "Healthy"
    finally:
        manager.stop()


def test_drain_applies_to_plugins_born_during_drain(kubelet):
    host, cfg, kub = kubelet
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    manager = PluginManager(cfg)
    manager.start()
    try:
        assert kub.wait_for(1)
        manager.drain(True)
        # hotplug a new model while draining
        host.add_chip(FakeChip("0000:01:00.0", device_id="0063",
                               iommu_group="21"))
        from tpu_device_plugin.discovery import discover
        manager._apply_inventory(discover(cfg))
        assert kub.wait_for(2)
        v5e = next(p for p in manager.plugins if p.resource_suffix == "v5e")
        assert v5e.status_snapshot()["devices"]["0000:01:00.0"] == "Unhealthy"
        manager.drain(False)
        assert v5e.status_snapshot()["devices"]["0000:01:00.0"] == "Healthy"
    finally:
        manager.stop()


def test_daemon_sigusr_drain_cycle(short_root):
    """Real process: SIGUSR1 drains (visible on /status), SIGUSR2 restores."""
    import json
    import signal as signal_mod
    import subprocess
    import sys
    import urllib.request
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kub = FakeKubelet(cfg.kubelet_socket)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_device_plugin", "--root", host.root,
         "--status-port", "18095", "--status-host", "127.0.0.1", "--log-json"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def get_status():
        return json.loads(urllib.request.urlopen(
            "http://127.0.0.1:18095/status", timeout=2).read())

    try:
        assert kub.wait_for(1, timeout=15)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                if get_status()["plugins"]:
                    break
            except OSError:
                time.sleep(0.1)
        proc.send_signal(signal_mod.SIGUSR1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = get_status()
            if s["draining"] and s["plugins"][0]["devices"][
                    "0000:00:04.0"] == "Unhealthy":
                break
            time.sleep(0.1)
        assert s["draining"] is True
        assert s["plugins"][0]["devices"]["0000:00:04.0"] == "Unhealthy"
        proc.send_signal(signal_mod.SIGUSR2)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = get_status()
            if not s["draining"] and s["plugins"][0]["devices"][
                    "0000:00:04.0"] == "Healthy":
                break
            time.sleep(0.1)
        assert s["draining"] is False
        assert s["plugins"][0]["devices"]["0000:00:04.0"] == "Healthy"
    finally:
        proc.terminate()
        out, _ = proc.communicate(timeout=15)
        kub.stop()
    # --log-json: every line parses as JSON
    for line in out.splitlines():
        if line.strip():
            json.loads(line)
