// libtpuhealth — native TPU liveness shim.
//
// The one native component of the plugin, mirroring the role of the
// reference's NVML cgo binding (the only C code it has:
// vendor/.../nvml/nvml_dl.go:30 dlopen("libnvidia-ml.so.1")). A vfio-bound
// TPU has no host driver to query, so liveness comes from three probes that
// work regardless of driver binding:
//
//  1. PCI config-space read: sysfs exposes <bdf>/config even for vfio-bound
//     devices; a chip that fell off the bus reads back all-0xFF.
//  2. Device-node probe: the vfio group / accel char device must exist.
//  3. libtpu presence: dlopen("libtpu.so") + symbol lookup, *without*
//     initializing the driver — initialization would seize the chips the
//     plugin is trying to hand out (the same reason the reference's
//     passthrough path has no NVML probe).
//
// Exposed as a flat C ABI consumed from Python via ctypes
// (tpu_device_plugin/native/__init__.py).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <dlfcn.h>
#include <fcntl.h>
#include <unistd.h>

extern "C" {

// Return codes shared by all probes.
enum tpuhealth_status {
  TPUHEALTH_OK = 0,          // device looks alive
  TPUHEALTH_DEAD = 1,        // device present in sysfs but not responding
  TPUHEALTH_MISSING = 2,     // path does not exist
  TPUHEALTH_ERR = -1,        // probe itself failed (permissions, I/O error)
};

// Probe a PCI device via its sysfs config file (e.g.
// /sys/bus/pci/devices/0000:00:05.0/config). Reads the 16-bit vendor id:
// unreadable or 0xFFFF means the device no longer answers config cycles.
int tpuhealth_probe_config(const char* config_path) {
  int fd = open(config_path, O_RDONLY);
  if (fd < 0) {
    return errno == ENOENT ? TPUHEALTH_MISSING : TPUHEALTH_ERR;
  }
  uint8_t buf[2] = {0, 0};
  ssize_t n = read(fd, buf, sizeof(buf));
  close(fd);
  if (n != static_cast<ssize_t>(sizeof(buf))) {
    return TPUHEALTH_DEAD;
  }
  uint16_t vendor = static_cast<uint16_t>(buf[0]) |
                    (static_cast<uint16_t>(buf[1]) << 8);
  if (vendor == 0xFFFF || vendor == 0x0000) {
    return TPUHEALTH_DEAD;
  }
  return TPUHEALTH_OK;
}

// Probe that a device node (vfio group, /dev/accelN) still exists and is
// openable. O_NONBLOCK so a wedged driver cannot hang the health thread.
int tpuhealth_probe_node(const char* dev_path) {
  int fd = open(dev_path, O_RDONLY | O_NONBLOCK);
  if (fd < 0) {
    if (errno == ENOENT) return TPUHEALTH_MISSING;
    // EACCES/EBUSY still prove the node exists and is owned by a driver.
    if (errno == EACCES || errno == EBUSY || errno == EPERM) return TPUHEALTH_OK;
    return TPUHEALTH_ERR;
  }
  close(fd);
  return TPUHEALTH_OK;
}

// PCI status register (config offset 0x06), the passthrough analogue of
// NVML's XID error events: parity/SERR/abort bits latch on bus errors even
// while the chip is vfio-bound. Returns the raw 16-bit value (>= 0), or
// -TPUHEALTH_MISSING / a negative error when unreadable. The caller decides
// what to do with the bits — they can be sticky from boot-time probing, so
// they are a diagnostic, not a liveness veto.
int tpuhealth_pci_status(const char* config_path) {
  int fd = open(config_path, O_RDONLY);
  if (fd < 0) {
    // TPUHEALTH_ERR is already negative; MISSING must be negated
    return errno == ENOENT ? -TPUHEALTH_MISSING : TPUHEALTH_ERR;
  }
  uint8_t buf[2] = {0, 0};
  ssize_t n = pread(fd, buf, sizeof(buf), 6);
  close(fd);
  if (n != static_cast<ssize_t>(sizeof(buf))) {
    return TPUHEALTH_ERR;
  }
  return static_cast<int>(static_cast<uint16_t>(buf[0]) |
                          (static_cast<uint16_t>(buf[1]) << 8));
}

// PCIe link status vs capability: detects DEGRADED links — current speed/
// width trained below the device maximum (connector faults, thermal
// retraining) — the passthrough analogue of NVML's
// nvmlDeviceGetCurrPcieLinkWidth/Generation family. Walks the PCI
// capability list (pointer at config 0x34) to the PCI Express capability
// (id 0x10), reading Link Capabilities (+0x0C) and Link Status (+0x12).
// Speeds are PCIe generation codes (1=2.5GT/s .. 6=64GT/s), widths are
// lane counts. Returns TPUHEALTH_OK with all four outputs filled, DEAD for
// an off-bus chip, MISSING when the path is gone, ERR when the capability
// is unreachable (short sysfs read — non-root sees only 64 bytes — or no
// PCIe capability, e.g. fixture trees).
int tpuhealth_pcie_link(const char* config_path, int* cur_speed,
                        int* cur_width, int* max_speed, int* max_width) {
  int fd = open(config_path, O_RDONLY);
  if (fd < 0) {
    return errno == ENOENT ? TPUHEALTH_MISSING : TPUHEALTH_ERR;
  }
  uint8_t cfg[256];
  ssize_t n = read(fd, cfg, sizeof(cfg));
  close(fd);
  if (n < 64) return TPUHEALTH_ERR;
  if (cfg[0] == 0xFF && cfg[1] == 0xFF) return TPUHEALTH_DEAD;
  if (!(cfg[0x06] & 0x10)) return TPUHEALTH_ERR;  // no capability list
  uint8_t off = cfg[0x34] & 0xFC;
  for (int guard = 0; guard < 48; ++guard) {
    if (off < 0x40 || static_cast<ssize_t>(off) + 0x14 > n) break;
    if (cfg[off] == 0x10) {
      uint32_t linkcap = static_cast<uint32_t>(cfg[off + 0x0C]) |
                         (static_cast<uint32_t>(cfg[off + 0x0D]) << 8) |
                         (static_cast<uint32_t>(cfg[off + 0x0E]) << 16) |
                         (static_cast<uint32_t>(cfg[off + 0x0F]) << 24);
      uint16_t linkstat = static_cast<uint16_t>(cfg[off + 0x12]) |
                          (static_cast<uint16_t>(cfg[off + 0x13]) << 8);
      *max_speed = static_cast<int>(linkcap & 0xF);
      *max_width = static_cast<int>((linkcap >> 4) & 0x3F);
      *cur_speed = static_cast<int>(linkstat & 0xF);
      *cur_width = static_cast<int>((linkstat >> 4) & 0x3F);
      return TPUHEALTH_OK;
    }
    off = cfg[off + 1] & 0xFC;
  }
  return TPUHEALTH_ERR;
}

// One-read diagnostics: status-register error bits AND PCIe link state
// from a single open+read of the config file (the /status-/metrics scrape
// and the 5 s health poll call this per device — two separate probes would
// double the syscalls). Outputs: *status_reg = raw 16-bit status (offset
// 0x06) or -1 when unreadable; link outputs as in tpuhealth_pcie_link,
// all -1 when the PCIe capability is unreachable. Returns tpuhealth_status
// for the config read itself.
int tpuhealth_chip_diag(const char* config_path, int* status_reg,
                        int* cur_speed, int* cur_width,
                        int* max_speed, int* max_width) {
  *status_reg = *cur_speed = *cur_width = *max_speed = *max_width = -1;
  int fd = open(config_path, O_RDONLY);
  if (fd < 0) {
    return errno == ENOENT ? TPUHEALTH_MISSING : TPUHEALTH_ERR;
  }
  uint8_t cfg[256];
  ssize_t n = read(fd, cfg, sizeof(cfg));
  close(fd);
  if (n < 8) return TPUHEALTH_ERR;
  if (cfg[0] == 0xFF && cfg[1] == 0xFF) return TPUHEALTH_DEAD;
  *status_reg = static_cast<int>(static_cast<uint16_t>(cfg[0x06]) |
                                 (static_cast<uint16_t>(cfg[0x07]) << 8));
  if (n < 64 || !(cfg[0x06] & 0x10)) return TPUHEALTH_OK;
  uint8_t off = cfg[0x34] & 0xFC;
  for (int guard = 0; guard < 48; ++guard) {
    if (off < 0x40 || static_cast<ssize_t>(off) + 0x14 > n) break;
    if (cfg[off] == 0x10) {
      uint32_t linkcap = static_cast<uint32_t>(cfg[off + 0x0C]) |
                         (static_cast<uint32_t>(cfg[off + 0x0D]) << 8) |
                         (static_cast<uint32_t>(cfg[off + 0x0E]) << 16) |
                         (static_cast<uint32_t>(cfg[off + 0x0F]) << 24);
      uint16_t linkstat = static_cast<uint16_t>(cfg[off + 0x12]) |
                          (static_cast<uint16_t>(cfg[off + 0x13]) << 8);
      *max_speed = static_cast<int>(linkcap & 0xF);
      *max_width = static_cast<int>((linkcap >> 4) & 0x3F);
      *cur_speed = static_cast<int>(linkstat & 0xF);
      *cur_width = static_cast<int>((linkstat >> 4) & 0x3F);
      break;
    }
    off = cfg[off + 1] & 0xFC;
  }
  return TPUHEALTH_OK;
}

// libtpu presence: dlopen + lazy symbol lookup, never initialization.
// Returns 1 when libtpu.so is loadable and exports a known entry point,
// 0 when absent. Handle is cached for the process lifetime.
static void* tpuhealth_libtpu_handle() {
  static void* handle = dlopen("libtpu.so", RTLD_LAZY | RTLD_LOCAL);
  return handle;
}

int tpuhealth_libtpu_available(void) {
  void* h = tpuhealth_libtpu_handle();
  if (h == nullptr) return 0;
  // Current libtpu exposes the PJRT entry point; older builds the TpuDriver.
  if (dlsym(h, "GetPjrtApi") != nullptr) return 1;
  if (dlsym(h, "TpuDriver_Open") != nullptr) return 1;
  return 0;
}

// ABI version tag so the Python side can detect stale .so builds.
// v2 added tpuhealth_pci_status, v3 tpuhealth_pcie_link, v4
// tpuhealth_chip_diag (one-read combination of the two); the Python loader
// accepts older shims and falls back to its own readers for missing
// symbols.
int tpuhealth_abi_version(void) { return 4; }

}  // extern "C"
