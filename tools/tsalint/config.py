"""Project rule configuration for tsalint.

Everything the analyzer needs to know about THIS codebase lives here, so
the engine (analyzer.py) stays generic and unit-testable with synthetic
configs (tests/test_tsalint.py builds its own LintConfig for fixtures).

Lock node naming: ``<module>.<Class>.<attr>`` for instance locks,
``<module>.<name>`` for module-level locks — the same names modules pass
to ``lockdep.instrument``, so a static finding and a runtime report point
at the same lock.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Optional, Set, Tuple


@dataclass(frozen=True)
class CarrierSpec:
    """One cross-boundary trace carrier (rule 8, trace-carrier).

    ``name`` is the lint id documented in docs/observability.md's
    propagation taxonomy table (the 3-way cross-check key, exactly like
    fault sites vs docs/fault-injection.md). ``kind`` selects how the
    crossing is detected and what "threads context" means there:

    - ``call-kwarg``: every call whose leaf name is ``call`` must pass
      the ``field`` keyword (or reach the positional slot ``arg_index``,
      0-based, self excluded) — and not as a literal ``None``.
    - ``dict-key``: every dict literal containing ALL ``markers`` keys
      is a carrier record and must also carry ``field``. A record built
      without it is still fine when the builder (or, via the call-graph
      fixpoint, every resolved caller) stamps ``rec[field] = ...``
      afterwards. A ``**spread`` makes the literal opaque (skipped), and
      a marker bound to a string CONSTANT marks a synthesized fixed
      frame (hello handshakes, injected-invalid sub-ops), not a
      crossing.
    - ``header-store``: the crossing evidence is a subscript store of
      the literal ``field`` key (``headers["Traceparent"] = ...``);
      existence anywhere in scope is the threading — rule 8 only pins
      liveness (a registered header carrier with no store is dead).

    ``scope``: path suffixes the detection applies to (empty = every
    scanned file) — the broker frame shape {"op", "seq"} also appears on
    the DECODE side in brokeripc.py, which receives context rather than
    threads it.
    """
    name: str
    kind: str
    field: str
    call: str = ""
    arg_index: int = -1
    markers: FrozenSet[str] = frozenset()
    scope: FrozenSet[str] = frozenset()

    def in_scope(self, path: str) -> bool:
        return not self.scope or any(path.endswith(s) for s in self.scope)


@dataclass
class LintConfig:
    # Lock nodes whose critical sections must never contain blocking calls.
    hot_locks: FrozenSet[str] = frozenset()
    # class qualname ("module.Class") -> {counter attr: owning lock node}.
    # A counter attr of the form "name[*]" matches subscript mutations of
    # self.name (dict-backed counter groups).
    counters: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # Dotted call names considered blocking (suffix-matched on the rendered
    # call target, e.g. "os.listdir"), plus bare method names considered
    # blocking on ANY receiver (apiserver round-trips).
    blocking_calls: FrozenSet[str] = frozenset()
    blocking_methods: FrozenSet[str] = frozenset()
    # fault-site rule inputs; None disables the rule (fixture runs).
    registered_sites: Optional[Set[str]] = None
    documented_sites: Optional[Set[str]] = None
    # stop-like method names a thread/timer must be joined/cancelled from
    stop_methods: FrozenSet[str] = frozenset(
        {"stop", "close", "shutdown", "_teardown", "stop_serving"})
    # modules allowed to construct/mutate epochs (the builder pattern):
    # the epoch-mutation rule flags any attribute/dict write to an
    # epoch-rooted expression OUTSIDE these modules. Matched by module
    # name (file stem), so fixture runs can exempt their own "epoch.py".
    epoch_modules: FrozenSet[str] = frozenset({"epoch"})
    # broker-boundary rule (rule 7) whitelist: path SUFFIXES of the files
    # allowed to contain privileged calls (device-node opens, sysfs
    # bind/unbind/driver_override writes, config-space reads). None
    # disables the rule (fixture runs); the project config whitelists the
    # broker, discovery, and the native shim (PRIVILEGED_SEAMS below).
    privileged_modules: Optional[FrozenSet[str]] = None
    # trace-carrier rule (rule 8) inputs; None disables the rule (fixture
    # runs). `carriers` is the code-side registry (CARRIERS below),
    # `documented_carriers` the lint ids parsed from docs/observability.md's
    # propagation taxonomy table — the same 3-way check as fault sites.
    carriers: Optional[Tuple[CarrierSpec, ...]] = None
    documented_carriers: Optional[Set[str]] = None


# Blocking-call vocabulary: calls that can sleep, touch disk, or cross the
# network. Deliberately NOT including os.path.* stat probes or condition
# waits (cond.wait releases the lock; stat probes are bounded and some are
# load-bearing inside small locks by design, e.g. LiveAttrReader).
BLOCKING_CALLS = frozenset({
    "open", "io.open",
    "os.listdir", "os.scandir", "os.walk",
    "os.open", "os.read", "os.write", "os.pread", "os.pwrite",
    "os.unlink", "os.remove", "os.replace", "os.rename",
    "os.makedirs", "os.rmdir", "os.fsync",
    "time.sleep",
    "shutil.rmtree", "shutil.copyfile",
    "subprocess.run", "subprocess.Popen", "subprocess.check_output",
    "socket.socket", "socket.create_connection",
    "select.select",
    "json.dump", "json.load",
})
# method names that are blocking whatever the receiver: the stdlib
# ApiClient verbs (network), urllib, grpc dial helpers, file writers
BLOCKING_METHODS = frozenset({
    "get_json", "put_json", "post_json", "request", "urlopen",
    "channel_ready_future", "_atomic_write_json", "_atomic_write_text",
    "_save_checkpoint",
})

# The hot set, exactly the three the correctness argument leans on:
# - the epoch store's writer condition (every epoch build/publish and
#   every parked ListAndWatch waiter rides it; a blocking call inside a
#   writer critical section would stall every reader wakeup),
# - the DRA driver's global checkpoint-map lock (claim commits and
#   rediscovery swaps contend on it),
# - the group-commit checkpoint condition (every claim's ACK latency is a
#   function of what happens under it).
# The old server device-table condition is gone: hot READS are lock-free
# epoch snapshots now (epoch.py; the lockdep read-path gate pins them).
HOT_LOCKS = frozenset({
    "epoch.EpochStore._cond",
    "dra.DraDriver._lock",
    "dra.DraDriver._ckpt_cond",
})

# Ownership sentinel for LOCK-FREE counters (round 15): a counter mapped
# to this value is owned by epoch.AtomicCounter (sharded per-thread
# cells, mutated only via .add()) — there IS no owning lock, and the
# counter-lock rule instead fails on ANY plain attribute mutation
# (`self.x += 1` / read-modify-write assign) of the attr: re-locking a
# lock-free counter silently, or mutating it as a bare int, both break
# the zero-lock read-path contract. The counter-drift audit
# (tests/test_counter_drift.py) still requires a /status + /metrics
# surface for every entry, lock-free or not.
LOCKFREE = "<lock-free: epoch.AtomicCounter>"

# The broker-boundary whitelist (rule 7, ISSUE 11): the ONLY files that
# may contain privileged calls. Path-suffix matched, because the two
# __init__.py files would collide as module stems:
# - broker.py — the privilege seam itself (both sides of it);
# - discovery.py — the read-only sysfs walk that BUILDS the inventory
#   (it predates the broker and runs before any serving surface is up;
#   the spawned broker process reuses it unchanged);
# - native/__init__.py — the probe implementation (config-space reads)
#   that the broker executes on the privileged side; daemon-side callers
#   reach it only through the broker.health_shim seam.
PRIVILEGED_SEAMS = frozenset({
    "tpu_device_plugin/broker.py",
    "tpu_device_plugin/discovery.py",
    "tpu_device_plugin/native/__init__.py",
})

# The trace-carrier registry (rule 8, ISSUE 20): every OUTBOUND
# process/privilege boundary the r17 propagation design names must
# thread its context field, and the registry must stay in 3-way sync
# with docs/observability.md's propagation taxonomy table (lint ids in
# the table's first column) and with the production crossing sites —
# a registered carrier no code crosses is dead, a carrier the docs
# don't name is undocumented, a documented id the registry dropped is
# undeclared. Inbound attach points (server.py gRPC metadata, the
# brokeripc decode path, watch-event consumption) RECEIVE context and
# are deliberately not carriers.
CARRIERS: Tuple[CarrierSpec, ...] = (
    # scheduler decision -> fabric multiclaim record: the fleetsim
    # fabric's multiclaim_begin(uid, shape, shards, traceparent=)
    CarrierSpec(name="multiclaim.traceparent", kind="call-kwarg",
                field="traceparent", call="multiclaim_begin", arg_index=3),
    # claim prepare -> the claim itself: the checkpoint entry stamped
    # under DraDriver._lock (spec_path+devices identify the entry shape)
    CarrierSpec(name="checkpoint-entry.traceparent", kind="dict-key",
                field="traceparent",
                markers=frozenset({"spec_path", "devices"}),
                scope=frozenset({"tpu_device_plugin/dra.py"})),
    # migration source -> destination host: the handoff record that
    # rides the same group commit as the entry deletion
    CarrierSpec(name="handoff.traceparent", kind="dict-key",
                field="traceparent",
                markers=frozenset({"source_node", "generation"}),
                scope=frozenset({"tpu_device_plugin/dra.py"})),
    # serving daemon -> privileged broker: the request frame's span
    # field ({"op", "seq"} is the outbound frame shape; brokeripc.py's
    # decode side and constant-op synthesized frames are out of scope)
    CarrierSpec(name="broker-frame.span", kind="dict-key", field="span",
                markers=frozenset({"op", "seq"}),
                scope=frozenset({"tpu_device_plugin/broker.py"})),
    # daemon -> apiserver: the W3C Traceparent request header
    CarrierSpec(name="kubeapi.traceparent-header", kind="header-store",
                field="Traceparent",
                scope=frozenset({"tpu_device_plugin/kubeapi.py"})),
)

# /status + /metrics counter ownership. Key classes by "module.Class";
# "name[*]" covers dict-backed counter groups (stats["k"] += 1).
COUNTERS: Dict[str, Dict[str, str]] = {
    # server hot-path counters (_alloc_count, _pref_hits/_pref_misses,
    # _lw_resends) moved to epoch.AtomicCounter — lock-free by design,
    # so they have no owning lock to configure here; only the cold-path
    # restart counter keeps classic lock ownership.
    "server.TpuDevicePlugin": {
        "_restart_count": "server.TpuDevicePlugin._lifecycle_lock",
        # response byte plane (round 15): AtomicCounters — any plain
        # `+= 1` on these attrs is a finding (LOCKFREE sentinel)
        "_alloc_bytes_reused": LOCKFREE,
        "_alloc_serializations": LOCKFREE,
        "_self_dial_reuses": LOCKFREE,
    },
    # broker crossing fast path (round 20): batched-sub-op and response-
    # ring counters are epoch.AtomicCounters on the client base class
    # (any plain `+= 1` is a finding); registered on _BaseClient so the
    # MRO walk covers InProcessBroker and SocketBrokerClient mutations.
    "broker._BaseClient": {
        "batched_ops": LOCKFREE,
        "ring_hits": LOCKFREE,
        "ring_fallbacks": LOCKFREE,
    },
    "healthhub.HealthHub": {
        "_probe_cycles": "healthhub.HealthHub._lock",
        "_probes_last_cycle": "healthhub.HealthHub._lock",
        "_probes_deduped_last_cycle": "healthhub.HealthHub._lock",
        "_probe_timeouts": "healthhub.HealthHub._lock",
        "_probe_errors": "healthhub.HealthHub._lock",
        "_existence_scans": "healthhub.HealthHub._lock",
    },
    "dra.DraDriver": {
        "publish_stats[*]": "dra.DraDriver._publish_lock",
        "checkpoint_stats_counters[*]": "dra.DraDriver._ckpt_cond",
        "_prepare_inflight": "dra.DraDriver._ckpt_cond",
        "_attach_active": "dra.DraDriver._ckpt_cond",
        "_checkpoint_bytes": "dra.DraDriver._ckpt_cond",
        # migration handoff counters (emitted/completed): /status reads
        # them lock-free via a C-atomic fixed-key dict copy
        "handoff_stats[*]": "dra.DraDriver._lock",
        # slice placement (ISSUE 10): fragmentation-recompute + defrag-
        # advisor counters mutate under the global lock (the recompute is
        # writer-side, the advisor bumps after building its proposal);
        # /status reads them lock-free via a fixed-key C-atomic dict copy
        "placement_stats[*]": "dra.DraDriver._lock",
        # prepare-ack byte plane (round 15): AtomicCounters (LOCKFREE)
        "_ack_bytes_reused": LOCKFREE,
        "_ack_serializations": LOCKFREE,
    },
    # device lifecycle FSM: every transition/orphan/swap counter mutates
    # under the FSM writer lock; stats() reads them lock-free (GIL-atomic
    # int reads + C-atomic dict copies), same contract as healthhub
    "lifecycle_fsm.DeviceLifecycle": {
        "transition_counts[*]": "lifecycle_fsm.DeviceLifecycle._lock",
        "claims_orphaned_total": "lifecycle_fsm.DeviceLifecycle._lock",
        "identity_swaps_total": "lifecycle_fsm.DeviceLifecycle._lock",
        "invalid_transitions_total": "lifecycle_fsm.DeviceLifecycle._lock",
    },
    # allocate.AllocationPlanner fragment_hits/misses are AtomicCounters
    # (no owning lock; the fragment cache is epoch-keyed and lock-free).
    # trace.py (flight recorder) counters are LOCK-FREE-OWNED by design:
    # span/event totals are epoch.AtomicCounter, ring cursors and
    # histogram cells are single-owner-thread sharded cells — there is no
    # owning lock to configure, and tests/test_tsalint.py carries a
    # fixture proving a span() on an epoch read path trips no rule.
    # tests/test_counter_drift.py pins every entry BELOW to its /status
    # and /metrics surface names — extend its SURFACES table when adding
    # counters here.
    # publish pacing (ISSUE 9): wave/coalesce/throttle/delay counters all
    # mutate inside `with self._cond` blocks of PublishPacer.run;
    # snapshot() reads them lock-free (fixed-key C-atomic dict copy).
    # ApiClient.throttled_total is an epoch.AtomicCounter (lock-free
    # owned, like the trace-plane counters — no entry here by design).
    "kubeapi.PublishPacer": {
        "stats[*]": "kubeapi.PublishPacer._cond",
    },
    # watch-stream reflector (ISSUE 12): stream/event/relist/resync/
    # degradation counters mutate under the reflector's own lock;
    # snapshot() reads them lock-free (fixed-key C-atomic dict copy).
    # DraDriver.watch_repairs is an epoch.AtomicCounter (lock-free
    # owned, no entry by design — like ApiClient.throttled_total).
    "kubeapi.Reflector": {
        "stats[*]": "kubeapi.Reflector._lock",
    },
    "resilience.BackoffPolicy": {
        "attempts": "resilience.BackoffPolicy._lock",
        "total_attempts": "resilience.BackoffPolicy._lock",
    },
    "resilience.CircuitBreaker": {
        "trips": "resilience.CircuitBreaker._lock",
        "rejected": "resilience.CircuitBreaker._lock",
        "half_open_rejected": "resilience.CircuitBreaker._lock",
        "_consecutive_failures": "resilience.CircuitBreaker._lock",
    },
    "discovery.HostSnapshot": {
        "stats[*]": "discovery.HostSnapshot._stats_lock",
    },
    "faults": {
        "_fired[*]": "faults._lock",
    },
    # trace propagation (round 17): the module-level context counters
    # are epoch.AtomicCounter (LOCKFREE — any plain rebind-as-count or
    # augmented assignment is a finding; reset()'s reconstruction is
    # initialization, which the rule ignores by design)
    "trace": {
        "_ctx_propagated": LOCKFREE,
        "_ctx_attached": LOCKFREE,
        "_ctx_dropped": LOCKFREE,
    },
    # SLO engine (round 17): eval/breach counters mutate under the
    # engine's own plain lock (deliberately UNregistered with lockdep —
    # the /status scrape drives evaluate() inside the zero-lock-gated
    # status read path, and the cold writer lock must stay invisible to
    # the gate like trace.py's maintenance lock); snapshot() reads them
    # via a C-atomic dict copy
    "slo.SLOEngine": {
        "counters[*]": "slo.SLOEngine._lock",
    },
    # remediation engine (round 18): action/rollback/veto/shed counters
    # mutate under the engine's own plain lock — deliberately
    # UNregistered like the SLO engine's (on_transition fires on the
    # zero-lock-gated /status scrape thread); snapshot() reads a
    # C-atomic dict copy
    "remediation.RemediationEngine": {
        "counters[*]": "remediation.RemediationEngine._lock",
    },
    # sharded fleet scheduler (round 19): decision/wave/conflict/replan
    # counters are epoch.AtomicCounter in a fixed-key dict (LOCKFREE —
    # wave planning and CAS replans bump them outside any lock;
    # snapshot() reads .value)
    "fleetplace.FleetScheduler": {
        "stats[*]": LOCKFREE,
    },
    # incremental fragmentation accountant (round 19): delta/recompute/
    # relist-skip counters are AtomicCounters too — bumped on the
    # reflector writer thread, read lock-free by snapshot()
    "fleetplace.FragAccountant": {
        "stats[*]": LOCKFREE,
    },
}


def registered_fault_sites(faults_source: str) -> Set[str]:
    """The site registry, read from faults.py's _SITE_CATEGORY literal —
    the same dict arm()/configure() enforce at runtime."""
    tree = ast.parse(faults_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "_SITE_CATEGORY" and node.value is not None:
            value = node.value
        elif isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_SITE_CATEGORY"
                for t in node.targets):
            value = node.value
        else:
            continue
        if isinstance(value, ast.Dict):
            return {k.value for k in value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)}
    raise ValueError("faults.py: _SITE_CATEGORY dict literal not found")


def documented_fault_sites(doc_text: str) -> Set[str]:
    """Sites documented in docs/fault-injection.md — the first backticked
    token of each row of the '## Fault points' table."""
    sites: Set[str] = set()
    in_section = False
    for line in doc_text.splitlines():
        if line.startswith("## "):
            in_section = line.strip() == "## Fault points"
            continue
        if in_section:
            m = re.match(r"\s*\|\s*`([a-z0-9_.-]+)`\s*\|", line)
            if m:
                sites.add(m.group(1))
    return sites


def documented_carriers(doc_text: str) -> Set[str]:
    """Carrier lint ids documented in docs/observability.md — the first
    backticked token of each row of the boundary-by-boundary carrier
    taxonomy table ('## Trace propagation'). Rows whose first cell is
    not a backticked id (same-thread inheritance, inbound attach points)
    are taxonomy prose, not checkable carriers."""
    ids: Set[str] = set()
    in_table = False
    for line in doc_text.splitlines():
        if "boundary-by-boundary carrier taxonomy" in line:
            in_table = True
            continue
        if in_table:
            if line.startswith("## ") or (ids and not line.strip()):
                break
            m = re.match(r"\s*\|\s*`([a-z0-9_.-]+)`\s*\|", line)
            if m:
                ids.add(m.group(1))
    return ids


def project_config(faults_source: str, doc_text: str,
                   observability_text: str) -> LintConfig:
    """The LintConfig for THIS repo (scripts/lint_concurrency.py)."""
    return LintConfig(
        hot_locks=HOT_LOCKS,
        counters=COUNTERS,
        blocking_calls=BLOCKING_CALLS,
        blocking_methods=BLOCKING_METHODS,
        registered_sites=registered_fault_sites(faults_source),
        documented_sites=documented_fault_sites(doc_text),
        privileged_modules=PRIVILEGED_SEAMS,
        carriers=CARRIERS,
        documented_carriers=documented_carriers(observability_text),
    )
