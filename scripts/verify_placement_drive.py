"""End-to-end drive of the slice placement plane (PR 10).

Real daemon (cli.main subprocess) with --dra against a fake 8-chip v5e
host (full 2x4 torus); driven as the kubelet + an operator would:
  1. boot: fragmentation gauges live on /status + /metrics (free 8,
     score 0.0)
  2. checkerboard the host with 4 DRA claims over dra.sock (real gRPC)
     -> fragmentation 0.75, largest free box 1
  3. /debug/defrag?shape=2x2 -> unplaceable-but-satisfiable advisory
     with migrations resolving locally; shape=4x4 -> unsatisfiable
  4. admit a pod through the kubelet devicemanager sim ->
     GetPreferredAllocation placement scoring surfaces on /metrics
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import grpc  # noqa: E402
from fakehost import FakeChip, FakeHost  # noqa: E402
from kubelet_sim import DeviceManagerSim  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402
from tpu_device_plugin.kubeletapi import draapi, drapb  # noqa: E402

root = tempfile.mkdtemp(prefix="vfypl-", dir="/tmp")
fh = FakeHost(root)
for i in range(8):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i), numa_node=i // 4,
                         serial=f"sn-{i}"))

os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
sim = DeviceManagerSim(os.path.join(root, "device-plugins"))
api = FakeApiServer()
port = 18171
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-a")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--health-poll-seconds", "0.3", "-v"],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def status():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=2) as r:
        return json.load(r)


def metrics():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2) as r:
        return r.read().decode()


def defrag(query):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/defrag?{query}", timeout=2) as r:
        return json.load(r)


def wait_for(pred, what, timeout=30):
    dl = time.time() + timeout
    while time.time() < dl:
        try:
            if pred():
                print(f"OK: {what}")
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise SystemExit(f"FAIL: timeout waiting for {what}")


try:
    wait_for(lambda: status(), "daemon up")
    wait_for(lambda: status()["dra"]["fragmentation"]["v5e"]["free"] == 8,
             "fragmentation record live on /status (free 8)")
    m = metrics()
    assert 'tpu_plugin_dra_fragmentation{generation="v5e"} 0.0' in m
    assert 'tpu_plugin_dra_largest_free_box{generation="v5e"} 8' in m
    print("OK: fragmentation gauges on /metrics (score 0.0, box 8)")

    # 2. checkerboard: claims on (0,1),(1,0),(0,3),(1,2) = 05,08,07,0a
    dra_sock = os.path.join(root, "plugins/cloud-tpus.google.com/dra.sock")
    # the inventory sink publishes (fragmentation live) BEFORE serving
    # the DRA sockets — wait for the socket, not just the gauges
    wait_for(lambda: os.path.exists(dra_sock), "dra.sock served")
    with grpc.insecure_channel(f"unix://{dra_sock}") as ch:
        stub = draapi.DraPluginStub(ch)
        for i, bdf in enumerate(["0000:00:05.0", "0000:00:08.0",
                                 "0000:00:07.0", "0000:00:0a.0"]):
            name = "d" + bdf.lower().replace(":", "-").replace(".", "-")
            api.add_claim("ns", f"vm{i}", f"uid-vm{i}",
                          "cloud-tpus.google.com", [{"device": name}])
            resp = stub.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns", name=f"vm{i}",
                                uid=f"uid-vm{i}")]), timeout=10)
            assert resp.claims[f"uid-vm{i}"].error == "", \
                resp.claims[f"uid-vm{i}"].error
    print("OK: 4 claims prepared over dra.sock (checkerboard)")
    wait_for(lambda: status()["dra"]["fragmentation"]["v5e"]
             == {"chips": 8, "free": 4, "departed": 0,
                 "largest_free_box": 1, "fragmentation": 0.75},
             "fragmentation recomputed (0.75, largest box 1)")

    # 3. the defrag advisor over real HTTP
    prop = defrag("shape=2x2")
    assert not prop["placeable"] and prop["satisfiable"], prop
    assert prop["moves"] >= 1 and prop["target"]["node"] == "node-a", prop
    assert all(mig["target_node"] == "node-a"
               for mig in prop["migrations"]), prop
    print(f"OK: /debug/defrag 2x2 -> {prop['moves']} migration(s), "
          f"locally resolvable")
    prop = defrag("shape=4x4")
    assert not prop["satisfiable"], prop
    print("OK: /debug/defrag 4x4 -> unsatisfiable (free 4 < 16)")
    s = status()["dra"]["placement"]
    assert s["defrag_proposals_total"] == 2, s
    assert s["defrag_unsatisfiable_total"] == 1, s
    print("OK: advisor counters on /status (2 proposals, 1 unsatisfiable)")

    # 4. kubelet pod admission -> placement scoring on /metrics
    assert sim.wait_for_resource("cloud-tpus.google.com/v5e")
    ids, _resp = sim.admit_pod("cloud-tpus.google.com/v5e", 2)
    assert len(ids) == 2, ids
    wait_for(lambda: "tpu_plugin_pref_placement_scored_total"
             f'{{resource="cloud-tpus.google.com/v5e"}} 1' in metrics(),
             "preferred-allocation placement scoring on /metrics")
    print("PLACEMENT DRIVE PASS")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    api.stop()
    sim.stop()
    shutil.rmtree(root, ignore_errors=True)
