#!/usr/bin/env python3
"""Merge Cloud TPU device ids into a full upstream pci.ids database.

The upstream pci.ids (https://pci-ids.ucw.cz, GPLv2+/BSD-3 dual-licensed)
carries no Cloud TPU device ids under vendor 1ae0 — Google has never
published a PCI-id table for TPUs (see tpu_device_plugin/naming.py). The
plugin's generation table is the authoritative TPU namer; pci.ids is only
the display-name fallback for ids the table does not know (reference
behavior: pkg/device_plugin/device_plugin.go:371-438 streaming
/usr/pci.ids). Shipping the FULL database (VERDICT r4 item 7) gives
mixed-hardware fleets the same fallback quality as the reference, and this
script re-inserts the TPU placeholder ids every time `make update-pcidb`
refreshes the file:

    python scripts/merge_tpu_pciids.py utils/pci.ids

Idempotent: existing 1ae0 device lines are kept, TPU ids are inserted in
sorted position, and nothing outside the 1ae0 block is touched.
"""
import re
import sys

# Placeholder ids matching tpu_device_plugin/naming.py's generation table;
# real TPU ids are not published upstream.
TPU_DEVICES = {
    "0062": "Cloud TPU v4 [placeholder id]",
    "0063": "Cloud TPU v5e [placeholder id]",
    "0064": "Cloud TPU v5p [placeholder id]",
    "0065": "Cloud TPU v6e [placeholder id]",
}

MERGE_MARK = "# Cloud TPU placeholder ids merged by scripts/merge_tpu_pciids.py"


def merge(text: str) -> str:
    lines = text.splitlines(keepends=True)
    out = []
    i = 0
    merged = False
    while i < len(lines):
        line = lines[i]
        out.append(line)
        i += 1
        if not line.startswith("1ae0"):
            continue
        # collect the existing vendor block (device + comment lines)
        block = []
        while i < len(lines) and (lines[i].startswith("\t")
                                  or lines[i].startswith("#")):
            # stop at a comment that belongs to the NEXT vendor (a comment
            # directly preceding a non-tab line)
            if lines[i].startswith("#"):
                j = i
                while j < len(lines) and lines[j].startswith("#"):
                    j += 1
                if j >= len(lines) or not lines[j].startswith("\t"):
                    break
            block.append(lines[i])
            i += 1
        present = {m.group(1) for ln in block
                   if (m := re.match(r"\t([0-9a-f]{4})  ", ln))}
        additions = [(did, f"\t{did}  {name}\n")
                     for did, name in sorted(TPU_DEVICES.items())
                     if did not in present]
        mark_pending = bool(additions) and MERGE_MARK + "\n" not in block
        # merge the two sorted device lists; the mark comment rides
        # directly before the first inserted id
        result = []

        def emit_addition():
            nonlocal mark_pending
            if mark_pending:
                result.append(MERGE_MARK + "\n")
                mark_pending = False
            result.append(additions.pop(0)[1])

        for ln in block:
            m = re.match(r"\t([0-9a-f]{4})  ", ln)
            if m:
                while additions and additions[0][0] < m.group(1):
                    emit_addition()
            result.append(ln)
        while additions:
            emit_addition()
        out.extend(result)
        merged = True
    if not merged:
        raise SystemExit("vendor 1ae0 not found in input pci.ids")
    return "".join(out)


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "utils/pci.ids"
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    with open(path, "w", encoding="utf-8") as f:
        f.write(merge(text))
    print(f"merged TPU ids into {path}")


if __name__ == "__main__":
    main()
