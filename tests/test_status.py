"""Status endpoint: /healthz gating and /status content."""

import json
import os
import threading
import time
import urllib.request

import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin.config import Config
from tpu_device_plugin.lifecycle import PluginManager
from tpu_device_plugin.status import StatusServer


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture
def rig(short_root):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    status = StatusServer(manager, port=0)
    status.start()
    yield host, manager, status
    status.stop()
    manager.stop()
    kubelet.stop()


def test_healthz_is_liveness_not_readiness(rig):
    """healthz must stay 200 while the run loop is alive even when no plugin
    is serving yet (boot-wait-for-kubelet must NOT be killed by a liveness
    probe); readyz flips with actual serving state."""
    host, manager, status = rig
    code, _ = _get(status.port, "/healthz")
    assert code == 503  # run loop not started
    code, _ = _get(status.port, "/readyz")
    assert code == 503

    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if _get(status.port, "/readyz")[0] == 200:
            break
        time.sleep(0.05)
    assert _get(status.port, "/healthz")[0] == 200
    assert _get(status.port, "/readyz")[0] == 200
    stop.set()
    t.join(timeout=10)
    code, _ = _get(status.port, "/healthz")
    assert code == 503  # loop exited


def test_healthz_alive_while_pending(short_root):
    """No kubelet at all: plugins stay pending, healthz 200, readyz 503."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), grpc_timeout_s=0.5)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    manager = PluginManager(cfg)
    status = StatusServer(manager, port=0)
    status.start()
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while not manager.pending and time.monotonic() < deadline:
            time.sleep(0.05)
        assert _get(status.port, "/healthz")[0] == 200
        assert _get(status.port, "/readyz")[0] == 503
    finally:
        stop.set()
        t.join(timeout=10)
        status.stop()


def test_status_payload(rig):
    host, manager, status = rig
    manager.start()
    code, body = _get(status.port, "/status")
    assert code == 200
    payload = json.loads(body)
    assert payload["pending"] == []
    (plugin,) = payload["plugins"]
    assert plugin["resource"] == "cloud-tpus.google.com/v4"
    assert plugin["serving"] is True
    assert plugin["devices"] == {"0000:00:04.0": "Healthy"}
    assert plugin["restarts"] == 0


def test_unknown_path_404(rig):
    host, manager, status = rig
    code, _ = _get(status.port, "/nope")
    assert code == 404


def test_metrics_exposition(rig):
    host, manager, status = rig
    manager.start()
    code, body = _get(status.port, "/metrics")
    assert code == 200
    text = body.decode()
    assert ('tpu_plugin_devices{resource="cloud-tpus.google.com/v4",'
            'health="Healthy"} 1') in text
    assert ('tpu_plugin_serving{resource="cloud-tpus.google.com/v4"} 1'
            ) in text
    assert "tpu_plugin_pending_plugins 0" in text
    # gauge must reflect the live probe, whatever this host reports
    expected = int(manager.native_info["libtpu_available"])
    assert f"tpu_plugin_libtpu_available {expected}" in text
    # health flip shows up in the gauge
    manager.plugins[0].set_group_health("11", False, "fs")
    code, body = _get(status.port, "/metrics")
    assert ('tpu_plugin_devices{resource="cloud-tpus.google.com/v4",'
            'health="Unhealthy"} 1') in body.decode()


def test_recent_allocations_surface_on_status(rig):
    import grpc
    from tpu_device_plugin import kubeletapi as api
    from tpu_device_plugin.kubeletapi import pb
    host, manager, status = rig
    manager.start()
    plugin = manager.plugins[0]
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        api.DevicePluginStub(ch).Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])]),
            timeout=5)
    code, body = _get(status.port, "/status")
    recent = json.loads(body)["plugins"][0]["recent_allocations"]
    assert recent and recent[0]["devices"] == [["0000:00:04.0"]]
    assert "T" in recent[0]["time"]  # ISO timestamp


def test_allocation_counter_in_metrics(rig):
    import grpc
    from tpu_device_plugin import kubeletapi as api
    from tpu_device_plugin.kubeletapi import pb
    host, manager, status = rig
    manager.start()
    plugin = manager.plugins[0]
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        for _ in range(2):
            stub.Allocate(pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["0000:00:04.0"])]),
                timeout=5)
        # failed allocations are never counted
        with pytest.raises(grpc.RpcError):
            stub.Allocate(pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["nope"])]), timeout=5)
    _, body = _get(status.port, "/metrics")
    assert ('tpu_plugin_allocations_total'
            '{resource="cloud-tpus.google.com/v4"} 2') in body.decode()


def test_degraded_link_surfaces_on_status_and_metrics(rig):
    """A chip whose PCIe link trained below max (gen1x8 on a gen4x16 part)
    shows on /status per-BDF and in the tpu_plugin_degraded_links gauge —
    without affecting device health (diagnostic, not a liveness veto)."""
    from tests.test_health import _pcie_config
    host, manager, status = rig
    manager.start()
    cfg_path = os.path.join(host.pci, "0000:00:04.0", "config")
    with open(cfg_path, "wb") as f:
        f.write(_pcie_config(1, 8, 4, 16))
    code, body = _get(status.port, "/status")
    payload = json.loads(body)
    (plugin,) = payload["plugins"]
    assert plugin["degraded_links"] == {"0000:00:04.0": "gen1x8 of gen4x16"}
    assert plugin["devices"] == {"0000:00:04.0": "Healthy"}  # no veto
    code, body = _get(status.port, "/metrics")
    assert ('tpu_plugin_degraded_links{resource="cloud-tpus.google.com/v4"}'
            ' 1') in body.decode()
    # link back at full speed -> gauge drops to 0
    with open(cfg_path, "wb") as f:
        f.write(_pcie_config(4, 16, 4, 16))
    code, body = _get(status.port, "/metrics")
    assert ('tpu_plugin_degraded_links{resource="cloud-tpus.google.com/v4"}'
            ' 0') in body.decode()
