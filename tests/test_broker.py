"""Privilege-separated broker tests (ISSUE 11 tentpole).

Covers the brokeripc wire contract (framing round-trip, fd passing over
real socketpairs, version-mismatch handshake refusal, oversized and
malformed frame rejection), the BrokerServer's path policy + audit
plane (every crossing carries the caller's flight-recorder span), the
held-fd registry surviving serving-daemon disconnects, the typed
BrokerUnavailable degradation on broker death with respawn + handshake
recovery, and the seam semantics both client shapes share.

The suite runs its seam-facing tests against BOTH client shapes: the
default in-process broker, and — under ``TDP_BROKER=spawn`` (the CI
matrix leg) — a real spawned broker process per fixture root, so the
two-process path is exercised by the same assertions.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import time

import pytest

from tpu_device_plugin import broker, brokeripc, faults, trace
from tpu_device_plugin.broker import (BrokerError, BrokerServer,
                                      BrokerUnavailable, InProcessBroker,
                                      PathPolicy, SocketBrokerClient)

SPAWN_MODE = os.environ.get("TDP_BROKER") == "spawn"


@pytest.fixture(autouse=True)
def clean_seam():
    """Every test starts from the lazy in-process default and leaves no
    installed client behind."""
    broker.reset_client()
    faults.reset()
    yield
    faults.reset()
    broker.reset_client()


def _wait(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def served(short_root):
    """An in-process BrokerServer on a real unix socket + a connected
    SocketBrokerClient — the two-process wire without the process
    spawn cost (the real-subprocess path has its own tests below)."""
    sock_path = os.path.join(short_root, "broker.sock")
    server = BrokerServer(sock_path, root=short_root)
    server.start()
    client = SocketBrokerClient(sock_path)
    yield short_root, server, client
    client.close()
    server.stop()


@pytest.fixture
def bare_server(short_root):
    """A BrokerServer with NO connected client: the broker accepts ONE
    daemon connection at a time by design, so tests that drive raw
    sockets must not share the socket with a fixture client."""
    sock_path = os.path.join(short_root, "broker.sock")
    server = BrokerServer(sock_path, root=short_root)
    server.start()
    yield short_root, server
    server.stop()


@pytest.fixture
def seam(short_root):
    """The seam under test: in-process by default; under TDP_BROKER=spawn
    a REAL broker subprocess rooted at the fixture tree, installed as
    the process-global client — the CI matrix leg's two-process path."""
    if SPAWN_MODE:
        sock_path = os.path.join(short_root, "broker.sock")
        proc = broker.spawn_broker(sock_path, root=short_root)
        client = SocketBrokerClient(sock_path)
        prev = broker.set_client(client)
        yield short_root, client
        broker.set_client(prev)
        client.close()
        proc.terminate()
        proc.wait(timeout=5)
    else:
        client = InProcessBroker()
        prev = broker.set_client(client)
        yield short_root, client
        broker.set_client(prev)


# ------------------------------------------------------------- framing


def test_frame_round_trip_over_socketpair():
    a, b = socket.socketpair()
    try:
        obj = {"op": "hello", "seq": 7, "nested": {"x": [1, 2, 3]}}
        brokeripc.send_frame(a, obj)
        got, fds = brokeripc.recv_frame(b)
        assert got == obj
        assert fds == []
    finally:
        a.close()
        b.close()


def test_fd_passing_over_real_socketpair(tmp_path):
    """SCM_RIGHTS: the receiver's fd is a live duplicate — reading it
    yields the sender's file content."""
    payload_file = tmp_path / "node"
    payload_file.write_bytes(b"device-bytes")
    a, b = socket.socketpair()
    fd = os.open(payload_file, os.O_RDONLY)
    try:
        brokeripc.send_frame(a, {"ok": True, "seq": 1}, fds=(fd,))
        got, fds = brokeripc.recv_frame(b, want_fds=1)
        assert got["ok"] is True
        assert len(fds) == 1
        # the received fd is a kernel dup: reading it proves liveness
        assert os.pread(fds[0], 64, 0) == b"device-bytes"
        os.close(fds[0])
    finally:
        os.close(fd)
        a.close()
        b.close()


def test_oversized_frame_rejected_without_allocation():
    """A corrupt length prefix must raise, not allocate gigabytes."""
    a, b = socket.socketpair()
    try:
        a.sendall(brokeripc.MAGIC + struct.pack(">I", brokeripc.MAX_FRAME + 1))
        with pytest.raises(brokeripc.BrokerProtocolError,
                           match="exceeds MAX_FRAME"):
            brokeripc.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_bad_magic_and_malformed_payload_rejected():
    for wire, match in (
            (b"XXXX" + struct.pack(">I", 2) + b"{}", "bad frame magic"),
            (brokeripc.MAGIC + struct.pack(">I", 9) + b"not-json!",
             "malformed"),
            (brokeripc.MAGIC + struct.pack(">I", 2) + b"[]",
             "not an object")):
        a, b = socket.socketpair()
        try:
            a.sendall(wire)
            with pytest.raises(brokeripc.BrokerProtocolError, match=match):
                brokeripc.recv_frame(b)
        finally:
            a.close()
            b.close()


def test_peer_death_mid_frame_is_connection_lost():
    a, b = socket.socketpair()
    try:
        a.sendall(brokeripc.MAGIC + struct.pack(">I", 100) + b"short")
        a.close()
        with pytest.raises(brokeripc.BrokerConnectionLost):
            brokeripc.recv_frame(b)
    finally:
        b.close()


# ----------------------------------------------------------- handshake


def test_version_mismatch_handshake_refused(bare_server):
    """A client speaking a future protocol version is refused BEFORE any
    operation is served."""
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        brokeripc.send_frame(raw, {
            "op": "hello", "seq": 0,
            "version": brokeripc.PROTOCOL_VERSION + 1})
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is False
        assert "version" in reply["error"]
        with pytest.raises(brokeripc.BrokerProtocolError,
                           match="refused handshake"):
            brokeripc.check_hello_reply(reply)
    finally:
        raw.close()


def test_malformed_frame_closes_connection_with_protocol_error(bare_server):
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        raw.sendall(b"GET / HTTP/1.1\r\n\r\n" + b"\x00" * 8)
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is False
        assert reply["kind"] == "protocol"
        # the broker closed the connection after the framing error
        # (clean EOF or RST depending on unread bytes — both are "gone")
        try:
            assert raw.recv(1) == b""
        except ConnectionResetError:
            pass
    finally:
        raw.close()


# ----------------------------------------------------- path policy


def test_path_policy_refuses_outside_roots(short_root):
    policy = PathPolicy(short_root)
    with pytest.raises(BrokerError, match="path policy"):
        policy.check_read("/etc/shadow")
    with pytest.raises(BrokerError, match="path policy"):
        policy.check_node(os.path.join(short_root, "etc/passwd"))
    with pytest.raises(BrokerError, match="path policy"):
        policy.check_write(os.path.join(short_root, "sys/devices/x/remove"))
    # component safety: <root>/system must not pass as <root>/sys
    with pytest.raises(BrokerError, match="path policy"):
        policy.check_read(os.path.join(short_root, "system/x"))
    # the allowed shapes
    policy.check_read(os.path.join(short_root, "sys/bus/pci/devices"))
    policy.check_node(os.path.join(short_root, "dev/vfio/11"))
    policy.check_node(os.path.join(short_root, "dev/accel0"))
    policy.check_write(os.path.join(
        short_root, "sys/bus/pci/drivers/vfio-pci/bind"))


def test_server_refuses_bad_paths_with_typed_errors(served):
    root, server, client = served
    with pytest.raises(BrokerError, match="refused"):
        client.read_attr("k", "/etc/hostname")
    with pytest.raises(BrokerError, match="refused"):
        client.open_node(os.path.join(root, "sys/whatever"))
    with pytest.raises(BrokerError, match="refused"):
        client.write_sysfs(os.path.join(root, "sys/x/remove"), "1")
    # the connection survives refusals: a good request still answers
    os.makedirs(os.path.join(root, "sys/bus"), exist_ok=True)
    assert client.node_exists(os.path.join(root, "sys/bus")) is True


# ------------------------------------------------- operations + audit


def test_open_node_passes_fd_and_broker_holds_its_own(served):
    root, server, client = served
    node = os.path.join(root, "dev/vfio/11")
    os.makedirs(os.path.dirname(node), exist_ok=True)
    with open(node, "w") as f:
        f.write("vfio-group-11")
    fd = client.open_node(node)
    try:
        assert os.pread(fd, 64, 0) == b"vfio-group-11"
    finally:
        os.close(fd)
    stats = client.stats()
    assert stats["broker"]["held_fds"] == 1
    assert node in stats["broker"]["held_paths"]


def test_broker_keeps_fds_across_client_disconnect(served):
    """kill -9 of the serving daemon: the broker sees EOF, keeps its
    held fds, and serves the reconnected daemon with audit intact."""
    root, server, client = served
    node = os.path.join(root, "dev/vfio/12")
    os.makedirs(os.path.dirname(node), exist_ok=True)
    with open(node, "w") as f:
        f.write("x")
    os.close(client.open_node(node))
    ops_before = client.stats()["broker"]["ops"]["open_node"]
    # abrupt disconnect — no shutdown op, exactly what SIGKILL produces
    client.close()
    client2 = SocketBrokerClient(server.socket_path)
    try:
        stats = client2.stats()["broker"]
        assert stats["held_fds"] == 1, "broker dropped fds on daemon death"
        assert stats["ops"]["open_node"] == ops_before
    finally:
        client2.close()


def test_every_crossing_is_audited_with_span_context(served):
    """Each request carries the caller's active flight-recorder span;
    the broker's audit ring links the crossing back to it, and the
    client records a broker.ipc span per crossing."""
    root, server, client = served
    trace.reset()
    with trace.span("dra.prepare.claim", claim_uid="claim-42"):
        client.node_exists(os.path.join(root, "dev"))
    spans = trace.snapshot(op="broker.ipc")
    assert spans, "crossing recorded no broker.ipc span"
    crossing_span = spans[-1]
    # attribute inheritance: the crossing span carries the claim context
    assert crossing_span["attrs"]["claim_uid"] == "claim-42"
    audit = client.stats()["broker"]["audit"]
    crossing = [a for a in audit if a["op"] == "node_exists"][-1]
    # the broker's audit entry links back to the daemon-side crossing
    # span (op + seq), so /debug/flight and /debug/broker correlate
    assert crossing["span"] is not None
    assert crossing["span"]["op"] == "broker.ipc"
    assert crossing["span"]["seq"] == crossing_span["seq"]
    # r17: the frame carries the FULL trace context, so the broker-side
    # audit entry (and the broker process's own broker.serve span) join
    # the caller's fleet trace
    assert crossing["span"]["trace_id"] == crossing_span["trace_id"]
    assert crossing["span"]["span_id"] == crossing_span["span_id"]
    trace.reset()


def test_span_context_carries_full_trace_context():
    """brokeripc.span_context(): {op, seq} pre-r17 shape extended with
    the active span's trace_id/span_id (one counted propagation); None
    outside any span."""
    trace.reset()
    assert brokeripc.span_context() is None
    with trace.span("dra.prepare.claim", claim_uid="c1") as sp:
        ctx = brokeripc.span_context()
        assert ctx["op"] == "dra.prepare.claim"
        assert ctx["trace_id"] == sp.trace_id
        assert ctx["span_id"] == sp.span_id
    assert trace.stats()["ctx_propagated_total"] == 1
    trace.reset()


def test_write_sysfs_performs_rebind_write(served):
    root, server, client = served
    bind = os.path.join(root, "sys/bus/pci/drivers/vfio-pci/bind")
    os.makedirs(os.path.dirname(bind), exist_ok=True)
    with open(bind, "w") as f:
        f.write("")
    client.write_sysfs(bind, "0000:00:04.0")
    with open(bind) as f:
        assert f.read() == "0000:00:04.0"


def test_read_attr_and_read_link(served):
    root, server, client = served
    dev_dir = os.path.join(root, "sys/bus/pci/devices/0000:00:04.0")
    os.makedirs(dev_dir, exist_ok=True)
    with open(os.path.join(dev_dir, "vendor"), "w") as f:
        f.write("0x1ae0\n")
    os.makedirs(os.path.join(root, "sys/kernel/iommu_groups/7"),
                exist_ok=True)
    os.symlink(os.path.join(root, "sys/kernel/iommu_groups/7"),
               os.path.join(dev_dir, "iommu_group"))
    assert client.read_attr("v", os.path.join(dev_dir, "vendor")) \
        .strip() == b"0x1ae0"
    assert client.read_link(os.path.join(dev_dir, "iommu_group")) == "7"
    assert client.read_attr("gone", os.path.join(dev_dir, "absent")) is None


# -------------------------------------------- death + typed degradation


def test_broker_death_yields_typed_unavailable_then_reconnect(served):
    root, server, client = served
    assert client.node_exists(os.path.join(root, "dev")) is False
    server.stop()
    with pytest.raises(BrokerUnavailable, match="broker unavailable"):
        client.node_exists(os.path.join(root, "dev"))
    # every later call fails fast with the SAME typed error
    with pytest.raises(BrokerUnavailable):
        client.read_link(os.path.join(root, "dev"))
    # respawn (new server, same socket) + handshake recovers
    server2 = BrokerServer(server.socket_path, root=root)
    server2.start()
    try:
        client.reconnect()
        assert client.node_exists(os.path.join(root, "dev")) is False
        assert client.reconnects.value == 1
    finally:
        server2.stop()


def test_injected_broker_fault_is_typed_unavailable():
    client = InProcessBroker()
    with faults.injected("broker.ipc", kind="drop", count=1):
        with pytest.raises(BrokerUnavailable, match="broker unavailable"):
            client.node_exists("/dev")
    # disarmed: back to answering
    assert isinstance(client.node_exists("/dev"), bool)
    assert client.errors.value == 1


# -------------------------------------------------- real subprocess path


def test_spawned_broker_kill9_respawn_recovers(short_root):
    """The acceptance shape against a REAL broker process: kill -9 →
    typed unavailable; respawn + handshake → recovery; the respawned
    broker is a different pid."""
    sock_path = os.path.join(short_root, "broker.sock")
    proc = broker.spawn_broker(sock_path, root=short_root)
    client = SocketBrokerClient(sock_path)
    try:
        pid1 = client.stats()["broker"]["pid"]
        assert pid1 == proc.pid
        proc.kill()
        proc.wait(timeout=5)
        with pytest.raises(BrokerUnavailable):
            client.node_exists(os.path.join(short_root, "dev"))
        proc = broker.spawn_broker(sock_path, root=short_root)
        client.reconnect()
        pid2 = client.stats()["broker"]["pid"]
        assert pid2 == proc.pid and pid2 != pid1
    finally:
        client.close()
        proc.terminate()
        proc.wait(timeout=5)


def test_spawned_broker_survives_client_death(short_root):
    sock_path = os.path.join(short_root, "broker.sock")
    proc = broker.spawn_broker(sock_path, root=short_root)
    try:
        client = SocketBrokerClient(sock_path)
        client.close()          # daemon "dies"
        client2 = SocketBrokerClient(sock_path)   # daemon "restarts"
        assert client2.stats()["broker"]["pid"] == proc.pid
        client2.close()
        assert proc.poll() is None, "broker died with its client"
    finally:
        proc.terminate()
        proc.wait(timeout=5)


# --------------------------------------------------- seam-facing tests


def test_seam_allocate_crossing_budget_and_audit(seam):
    """A steady-state Allocate plan crosses the privilege boundary at
    most twice (one batched revalidation + at most one TTL-expired
    iommufd probe) in EITHER mode, every crossing visible as a
    broker.ipc span."""
    from dataclasses import replace as dc_replace

    from tests.fakehost import FakeChip, FakeHost
    from tpu_device_plugin.allocate import AllocationPlanner
    from tpu_device_plugin.config import Config
    from tpu_device_plugin.discovery import discover_passthrough

    root, client = seam
    host = FakeHost(root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    cfg = dc_replace(Config().with_root(root), shared_scan_ttl_s=60.0)
    registry, _ = discover_passthrough(cfg)
    planner = AllocationPlanner(cfg, registry, "v4")
    bdfs = sorted(registry.bdf_to_group)
    trace.reset()
    planner.plan(bdfs)                      # cold: fragments + iommufd
    before = client.crossings.value
    planner.plan(bdfs)                      # steady state
    per_attach = client.crossings.value - before
    assert 1 <= per_attach <= 2, per_attach
    spans = trace.snapshot(op="broker.ipc")
    assert any(s["attrs"]["broker_op"] == "revalidate" for s in spans)
    trace.reset()


def test_seam_supports_iommufd_routes_through_broker(seam):
    from tpu_device_plugin.allocate import supports_iommufd
    from tpu_device_plugin.config import Config

    root, client = seam
    cfg = Config().with_root(root)
    before = client.crossings.value
    assert supports_iommufd(cfg) is False
    os.makedirs(os.path.join(root, "dev"), exist_ok=True)
    with open(os.path.join(root, "dev/iommu"), "w") as f:
        f.write("")
    assert supports_iommufd(cfg) is True
    assert client.crossings.value == before + 2


def test_seam_read_link_routes_mdev_prepare(seam):
    root, client = seam
    target_dir = os.path.join(root, "sys/kernel/iommu_groups/42")
    os.makedirs(target_dir, exist_ok=True)
    link = os.path.join(root, "sys/bus/mdev/devices")
    os.makedirs(link, exist_ok=True)
    link_path = os.path.join(link, "iommu_group")
    os.symlink(target_dir, link_path)
    assert broker.seam_read_link(link_path) == "42"
    assert broker.seam_read_link(os.path.join(link, "absent")) is None


def test_brokered_health_shim_matches_native_verdicts(seam):
    """BrokeredHealth forwards every probe through the seam client (IPC
    in spawn mode, direct in-process) and its verdicts agree with the
    plain native shim's; broker.health_shim picks the right shape for
    the installed client."""
    from tpu_device_plugin.native import MISSING, OK, TpuHealth

    root, client = seam
    picked = broker.health_shim()
    if SPAWN_MODE:
        assert isinstance(picked, broker.BrokeredHealth)
    else:
        assert isinstance(picked, TpuHealth)
    # the brokered shape must answer identically over EITHER client
    shim = broker.BrokeredHealth(client)
    dev_dir = os.path.join(root, "sys/bus/pci/devices/0000:00:04.0")
    os.makedirs(dev_dir, exist_ok=True)
    with open(os.path.join(dev_dir, "config"), "wb") as f:
        f.write(b"\xe0\x1a\x00\x00\x00\x00\x00\x00")
    native = TpuHealth()
    cfg_path = os.path.join(dev_dir, "config")
    assert shim.probe_config(cfg_path) == native.probe_config(cfg_path) == OK
    assert shim.probe_config(cfg_path + ".gone") == MISSING
    assert shim.chip_alive(os.path.join(root, "sys/bus/pci/devices"),
                           "0000:00:04.0") is True
    bits, _link = shim.chip_diagnostics(
        os.path.join(root, "sys/bus/pci/devices"), "0000:00:04.0")
    assert bits == 0


def test_in_process_node_policy_matches_spawned_policy():
    client = InProcessBroker()
    with pytest.raises(BrokerError, match="not a device node"):
        client.open_node("/etc/passwd")
    with pytest.raises(BrokerError, match="write_sysfs refused"):
        client.write_sysfs("/sys/bus/pci/devices/x/remove", "1")


def test_seam_default_and_set_reset():
    default = broker.get_client()
    assert isinstance(default, InProcessBroker)
    assert broker.get_client() is default       # stable
    other = InProcessBroker()
    prev = broker.set_client(other)
    assert prev is default
    assert broker.get_client() is other
    broker.reset_client()
    assert broker.get_client() is not other


def test_broker_main_entrypoint_serves_and_exits(short_root):
    """python -m tpu_device_plugin.broker round-trip: the module main
    binds, answers a handshake + an op, and exits on shutdown."""
    sock_path = os.path.join(short_root, "broker.sock")
    proc = broker.spawn_broker(sock_path, root=short_root)
    client = SocketBrokerClient(sock_path)
    try:
        assert client.node_exists(os.path.join(short_root, "dev")) is False
        client.shutdown_broker()
        assert _wait(lambda: proc.poll() is not None, timeout=5)
        assert proc.returncode == 0
    finally:
        client.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=5)


def test_stats_surface_is_json_serializable(seam):
    root, client = seam
    client.node_exists(os.path.join(root, "dev"))
    json.dumps(client.stats(), default=str)
    json.dumps(client.client_stats())


# ----------------------------------------- hardening (review findings)


def test_malformed_request_fields_do_not_kill_the_broker(served):
    """A compromised/version-skewed daemon sending structurally-valid
    frames with missing or wrong-shaped FIELDS gets typed refusals —
    never a dead accept thread with dropped fds (the threat-model DoS)."""
    root, server, client = served
    node = os.path.join(root, "dev/vfio/13")
    os.makedirs(os.path.dirname(node), exist_ok=True)
    with open(node, "w") as f:
        f.write("x")
    os.close(client.open_node(node))
    for req in ({"op": "node_exists"},                  # missing path
                {"op": "chip_alive", "pci_base": 7},    # wrong shape
                {"op": "revalidate",
                 "pci_base": os.path.join(root, "sys"),
                 "pairs": [["only-one-element"]]},      # not a 2-list
                {"op": "open_node"}):
        with pytest.raises(BrokerError, match="refused"):
            client._request(**{k: v for k, v in req.items() if k != "op"},
                            op=req["op"])
    # the broker survived every one of them: fds held, still serving
    stats = client.stats()["broker"]
    assert stats["held_fds"] == 1
    assert client.node_exists(node) is True


def test_traversal_bdf_and_arbitrary_node_are_refused(served):
    """PathPolicy holds for the joined/indirect fields too: a traversal
    bdf must not escape the readable roots, and the chip_alive node path
    must not be usable as an arbitrary-file existence oracle."""
    root, server, client = served
    base = os.path.join(root, "sys/bus/pci/devices")
    os.makedirs(base, exist_ok=True)
    with pytest.raises(BrokerError, match="path component"):
        client.chip_alive(base, "../../../etc")
    with pytest.raises(BrokerError, match="path component"):
        client.chip_diagnostics(base, "..")
    with pytest.raises(BrokerError, match="path policy"):
        client._request("chip_alive", pci_base=base,
                        bdf="0000:00:04.0", node="/etc/hostname")

    class _Planner:
        class cfg:
            pci_base_path = base
        _vendor_ok = frozenset({"1ae0"})

    from tpu_device_plugin.allocate import AllocationError as _AE
    with pytest.raises((BrokerError, _AE), match="path component"):
        client.revalidate_batch(_Planner(), [("../escape", "11")])


def test_ops_refused_before_handshake(bare_server):
    """A client that SKIPS hello gets nothing: the version contract
    ('refused before serving anything else') must not depend on client
    cooperation."""
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        brokeripc.send_frame(raw, {"op": "node_exists", "seq": 1,
                                   "path": os.path.join(root, "dev")})
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is False
        assert reply["kind"] == "version"
        # hello unlocks the connection
        brokeripc.send_frame(raw, brokeripc.hello_request(seq=2))
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is True
        brokeripc.send_frame(raw, {"op": "node_exists", "seq": 3,
                                   "path": os.path.join(root, "dev")})
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is True
    finally:
        raw.close()


def test_wedged_broker_times_out_typed_unavailable(short_root):
    """A broker that is alive but STUCK (accepts + handshakes, then
    never answers) must degrade to typed unavailable within the op
    timeout — not pin the channel lock forever."""
    import threading

    sock_path = os.path.join(short_root, "wedged.sock")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(1)

    def wedge():
        conn, _ = listener.accept()
        req, _ = brokeripc.recv_frame(conn)          # the hello
        brokeripc.send_frame(conn, {
            "ok": True, "seq": req["seq"],
            "version": brokeripc.PROTOCOL_VERSION})
        brokeripc.recv_frame(conn)                   # the op — swallowed
        time.sleep(5)                                # ...and never answered
        conn.close()

    t = threading.Thread(target=wedge, daemon=True)
    t.start()
    client = SocketBrokerClient(sock_path, op_timeout_s=0.3)
    try:
        t0 = time.monotonic()
        with pytest.raises(BrokerUnavailable):
            client.node_exists("/dev")
        assert time.monotonic() - t0 < 3.0
    finally:
        client.close()
        listener.close()
        t.join(timeout=6)


def test_spawn_mode_accepts_0x_prefixed_vendor_ids(served):
    """cfg.vendor_ids spelled with the 0x prefix must revalidate
    identically over the broker (the in-process reader accepts both
    spellings; a mode-dependent divergence would be a spawn-only
    outage)."""
    root, server, client = served
    base = os.path.join(root, "sys/bus/pci/devices")
    dev = os.path.join(base, "0000:00:04.0")
    os.makedirs(dev, exist_ok=True)
    with open(os.path.join(dev, "vendor"), "w") as f:
        f.write("0x1ae0\n")
    os.makedirs(os.path.join(root, "sys/kernel/iommu_groups/11"),
                exist_ok=True)
    os.symlink(os.path.join(root, "sys/kernel/iommu_groups/11"),
               os.path.join(dev, "iommu_group"))

    class _Planner:
        class cfg:
            pci_base_path = base

    for spelling in ("1ae0", "0x1ae0"):
        _Planner._vendor_ok = frozenset({spelling})
        client.revalidate_batch(_Planner(), [("0000:00:04.0", "11")])


def test_shutdown_requires_handshake(bare_server):
    """An un-handshaked local process must NOT be able to kill the
    privileged broker through the socket: a refused shutdown leaves the
    broker serving."""
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        brokeripc.send_frame(raw, {"op": "shutdown", "seq": 1})
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is False and reply["kind"] == "version"
        assert not server._stop.is_set(), \
            "refused shutdown still stopped the broker"
    finally:
        raw.close()
    # the broker still serves a proper (handshaked) client
    client = SocketBrokerClient(server.socket_path)
    try:
        assert client.node_exists(os.path.join(root, "dev")) is False
    finally:
        client.close()


def test_socket_live_distinguishes_wedged_from_dead(short_root):
    path = os.path.join(short_root, "probe.sock")
    assert broker.socket_live(path) is False          # nothing there
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(path)
    listener.listen(1)
    try:
        assert broker.socket_live(path) is True       # listening (wedged)
    finally:
        listener.close()
    assert broker.socket_live(path) is False          # stale socket file


# ------------------------------------ round 20: crossing fast path


def test_binary_codec_round_trip():
    """decode_body(encode_body(x)) == x across every field kind: opcode,
    zigzag ints, bools, strings, the compact span context, nested batch
    bodies, and the JSON catch-all for unknown keys / wrong-typed
    values."""
    span = {"op": "dra.prepare", "seq": 5,
            "trace_id": "a" * 32, "span_id": "b" * 16}
    cases = [
        {"op": "read_attr", "path": "/sys/x", "seq": 3, "span": span},
        {"op": "read_attr", "path": "/x", "seq": -7,
         "span": {"op": "p", "seq": 0}},                  # short span
        {"op": "hello", "version": 2, "ring": True, "seq": 0},
        {"ok": True, "seq": 0, "version": 2, "pid": 4242, "ring": True,
         "ring_slots": 512, "ring_slot_size": 512},
        {"op": "batch", "seq": 9, "ops": [
            {"op": "read_link", "path": "/a", "seq": 0},
            {"op": "node_exists", "path": "/b", "seq": 1}]},
        {"ok": True, "seq": 9, "results": [
            {"ok": True, "seq": 0, "target": "../g/11"},
            {"ok": False, "seq": 1, "kind": "refused", "error": "no"}]},
        # catch-all: unknown key, wrong-typed value, non-canonical span
        {"op": "stats", "seq": 1, "mystery": {"deep": [1, 2]}},
        {"op": "read_attr", "path": "/x", "seq": 1,
         "span": {"op": "has\x1fus", "seq": 1}},
        {"op": "read_attr", "path": "/x", "seq": 1,
         "span": {"op": "extra", "seq": 1, "trace_id": "t",
                  "span_id": "s", "more": True}},
        {"ok": True, "seq": 2, "vendors": {"0000:00:04.0": "0x1ae0"}},
    ]
    enc = brokeripc.RequestEncoder()
    for obj in cases:
        assert brokeripc.decode_body(brokeripc.encode_body(obj)) == obj
        frame = enc.encode_frame(obj)
        assert frame[:4] == brokeripc.BIN_MAGIC
        assert brokeripc.decode_body(
            frame[brokeripc._HEADER_SIZE:]) == obj
    # repeated static segments hit the pre-serialized cache
    before = enc.static_hits
    enc.encode_frame({"op": "read_attr", "path": "/sys/x", "seq": 99,
                      "span": span})
    assert enc.static_hits == before + 1


def test_binary_codec_skips_unknown_tags_and_rejects_garbage():
    from tpu_device_plugin.epoch import encode_delimited, encode_varint

    body = brokeripc.encode_body({"op": "stats", "seq": 1})
    # a future delimited field and a future varint field: skipped
    future = encode_delimited(30, b"whatever") \
        + encode_varint(30 << 3) + encode_varint(17)
    assert brokeripc.decode_body(body + future) == \
        {"op": "stats", "seq": 1}
    for garbage, match in (
            (b"\xff", "truncated varint"),
            (encode_varint((4 << 3) | 2) + encode_varint(99), "truncated"),
            (encode_varint((1 << 3) | 5), "unsupported wire type"),
            (encode_varint(1 << 3) + encode_varint(99), "unknown opcode"),
            (encode_varint((2 << 3) | 2) + encode_varint(1) + b"x",
             "arrived delimited")):
        with pytest.raises(brokeripc.BrokerProtocolError, match=match):
            brokeripc.decode_body(garbage)


def _open_fds():
    return len(os.listdir("/proc/self/fd"))


def test_recv_frame_closes_received_fds_on_every_error_path(tmp_path):
    """The r20 bugfix regression: a frame that arrives WITH SCM_RIGHTS
    fds but fails to decode must close the received kernel dups before
    raising — on every error path — or each malformed reply leaks one
    fd into the long-running daemon."""
    payload = tmp_path / "f"
    payload.write_bytes(b"x")
    fd = os.open(payload, os.O_RDONLY)
    try:
        bad_frames = [
            # bad magic
            b"XXXX" + struct.pack(">I", 2) + b"{}",
            # oversized length prefix
            brokeripc.MAGIC + struct.pack(">I", brokeripc.MAX_FRAME + 1),
            # malformed JSON payload
            brokeripc.MAGIC + struct.pack(">I", 9) + b"not-json!",
            # non-object payload
            brokeripc.MAGIC + struct.pack(">I", 2) + b"[]",
            # malformed binary payload
            brokeripc.BIN_MAGIC + struct.pack(">I", 1) + b"\xff",
        ]
        for wire in bad_frames:
            a, b = socket.socketpair()
            try:
                socket.send_fds(a, [wire], [fd])
                # the kernel dup materializes in this process only once
                # recv_fds runs — so a clean decode-error path leaves
                # the fd table exactly as it was before the recv
                baseline = _open_fds()
                with pytest.raises(brokeripc.BrokerProtocolError):
                    brokeripc.recv_frame(b, want_fds=1)
                assert _open_fds() == baseline, \
                    f"leaked received fd on {wire[:4]!r}"
            finally:
                a.close()
                b.close()
        # peer death after the fd-bearing first chunk: the header never
        # completes, the dup must still be closed
        a, b = socket.socketpair()
        socket.send_fds(a, [brokeripc.MAGIC[:2]], [fd])
        a.close()
        baseline = _open_fds()
        try:
            with pytest.raises(brokeripc.BrokerConnectionLost):
                brokeripc.recv_frame(b, want_fds=1)
            assert _open_fds() == baseline
        finally:
            b.close()
    finally:
        os.close(fd)


# ------------------------------------- round 20: version negotiation


def test_negotiation_v2_binary_end_to_end(served):
    """Both peers current: hello negotiates v2, every post-hello frame
    is binary, the response ring attaches, and the pre-serialized frame
    cache serves repeated requests."""
    root, server, client = served
    assert client.negotiated_version == 2
    stats = client.stats()
    assert stats["protocol_version"] == 2
    assert stats["ring_attached"] is True
    dev = os.path.join(root, "dev")
    for _ in range(3):
        assert client.node_exists(dev) is False
    assert client.stats()["frame_cache_hits_total"] >= 2


def test_negotiation_v1_peer_json_fallback(bare_server):
    """A v1 serving daemon against a v2 broker: the hello version field
    pins the session to JSON framing, no ring is offered, and every op
    still round-trips."""
    root, server = bare_server
    client = SocketBrokerClient(server.socket_path, protocol_version=1)
    try:
        assert client.negotiated_version == 1
        stats = client.stats()
        assert stats["protocol_version"] == 1
        assert stats["ring_attached"] is False
        assert client.node_exists(os.path.join(root, "dev")) is False
        vendor = os.path.join(root, "sys/bus/pci/devices",
                              "0000:00:04.0", "vendor")
        assert client.read_attr("0000:00:04.0", vendor) is None
    finally:
        client.close()


def test_negotiation_rejects_unknown_version_client_side():
    with pytest.raises(ValueError, match="not in"):
        SocketBrokerClient("/nonexistent.sock", protocol_version=3)


def test_binary_frame_before_v2_negotiation_refused(bare_server):
    """A peer that negotiated v1 (JSON) and then speaks binary anyway is
    a protocol violation — refused and disconnected, not served."""
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        brokeripc.send_frame(raw, {"op": "hello", "seq": 0, "version": 1})
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is True and reply["version"] == 1
        brokeripc.send_frame(raw, {"op": "stats", "seq": 1}, binary=True)
        reply, _ = brokeripc.recv_frame(raw)
        assert reply["ok"] is False
        assert reply["kind"] == "protocol"
        assert "binary framing" in reply["error"]
    finally:
        raw.close()


def test_reply_framing_mirrors_request_framing(bare_server):
    """hello is ALWAYS JSON (framing is negotiated, not assumed); after
    a v2 hello the server answers binary requests with binary frames
    and JSON requests with JSON frames."""
    root, server = bare_server
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.settimeout(5)
    raw.connect(server.socket_path)
    try:
        brokeripc.send_frame(raw, {
            "op": "hello", "seq": 0,
            "version": brokeripc.PROTOCOL_VERSION})
        reply, fds, binary = brokeripc.recv_frame_ex(raw)
        assert reply["ok"] is True and binary is False
        brokeripc.send_frame(raw, {"op": "stats", "seq": 1}, binary=True)
        reply, fds, binary = brokeripc.recv_frame_ex(raw)
        assert reply["ok"] is True and binary is True
        brokeripc.send_frame(raw, {"op": "stats", "seq": 2})
        reply, fds, binary = brokeripc.recv_frame_ex(raw)
        assert reply["ok"] is True and binary is False
    finally:
        raw.close()


# --------------------------------------- round 20: batched crossings


def test_kill9_mid_batch_typed_unavailable_then_exactly_once_retry(
        short_root):
    """A broker killed -9 under a pending batch yields a typed
    per-sub-op 'unavailable' result for EVERY sub-op (no partial
    silence), and after respawn + handshake ONE retry executes the
    batch exactly once — the respawned broker's audit shows a single
    batch crossing."""
    from tests.fakehost import FakeChip, FakeHost

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    sock_path = os.path.join(short_root, "broker.sock")
    proc = broker.spawn_broker(sock_path, root=short_root)
    client = SocketBrokerClient(sock_path)
    pci = os.path.join(short_root, "sys/bus/pci/devices")
    subs = [
        {"op": "read_attr",
         "path": os.path.join(pci, "0000:00:04.0", "vendor")},
        {"op": "read_link",
         "path": os.path.join(pci, "0000:00:04.0", "iommu_group")},
    ]
    try:
        proc.kill()
        proc.wait(timeout=5)
        results = client.run_batch(subs)
        assert len(results) == len(subs)
        for i, res in enumerate(results):
            assert res["ok"] is False and res["seq"] == i
            assert res["kind"] == "unavailable"
        # the typed batch degradation surfaces through the list helpers
        # as the SAME exception type singular ops raise
        with pytest.raises(BrokerUnavailable):
            client.read_link_batch([subs[1]["path"]])

        proc = broker.spawn_broker(sock_path, root=short_root)
        client.reconnect()
        retried = client.run_batch(subs)
        assert [r["ok"] for r in retried] == [True, True]
        assert retried[0]["data"] == "0x1ae0\n"
        assert retried[1]["target"] == "11"
        audit = client.stats()["broker"]["audit"]
        # exactly ONE batch crossing on the respawned broker, carrying
        # one audit entry per sub-op through the same machinery
        assert len([a for a in audit if a["op"] == "batch"]) == 1
        assert len([a for a in audit if a["op"] == "read_attr"]) == 1
        assert len([a for a in audit if a["op"] == "read_link"]) == 1
    finally:
        client.close()
        proc.terminate()
        proc.wait(timeout=5)


def _normalize_audit(entries):
    """Audit entries minus the run-variant parts (timestamps, span
    seq/ids): what MUST be byte-identical across framings."""
    out = []
    for a in entries:
        span = a.get("span")
        out.append({
            "op": a["op"], "path": a.get("path"), "ok": a["ok"],
            "error": a.get("error"),
            "span": None if span is None else {
                "op": span["op"],
                "has_trace": "trace_id" in span and "span_id" in span},
        })
    return out


def test_audit_and_trace_contract_identical_across_framings(short_root):
    """The acceptance contract: the SAME op sequence over the v1 JSON
    framing and the v2 binary framing must leave byte-identical audit
    rings (modulo timestamps and span ids) and byte-identical
    client-side broker.ipc span attributes — the fast path changes the
    wire, never the semantics."""
    from tests.fakehost import FakeChip, FakeHost

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    pci = os.path.join(short_root, "sys/bus/pci/devices")
    vendor = os.path.join(pci, "0000:00:04.0", "vendor")
    group = os.path.join(pci, "0000:00:04.0", "iommu_group")

    def run(version, sock_name):
        sock_path = os.path.join(short_root, sock_name)
        server = BrokerServer(sock_path, root=short_root)
        server.start()
        # ring off so the v2 run crosses for every op exactly like v1
        # (a ring hit is the absence of a crossing, not a different one)
        client = SocketBrokerClient(sock_path, protocol_version=version,
                                    ring=False)
        trace.reset()
        try:
            with trace.span("contract.check"):
                client.node_exists(os.path.join(short_root, "dev"))
                client.read_attr("0000:00:04.0", vendor)
                client.read_link(group)
                client.chip_alive(pci, "0000:00:04.0")
                client.run_batch([
                    {"op": "read_attr", "path": vendor},
                    {"op": "read_link", "path": group}])
                with pytest.raises(BrokerError):
                    client.read_attr("0000:00:04.0", "/etc/passwd")
            audit = client.stats()["broker"]["audit"]
            spans = [{k: v for k, v in s["attrs"].items()}
                     for s in trace.snapshot(op="broker.ipc")]
            for s in spans:
                s.pop("seq", None)
            return _normalize_audit(audit), spans
        finally:
            client.close()
            server.stop()
            trace.reset()

    audit_v1, spans_v1 = run(1, "v1.sock")
    audit_v2, spans_v2 = run(2, "v2.sock")
    assert json.dumps(audit_v1, sort_keys=True) == \
        json.dumps(audit_v2, sort_keys=True)
    assert json.dumps(spans_v1, sort_keys=True) == \
        json.dumps(spans_v2, sort_keys=True)
    # sanity: the contract actually covered the interesting entries
    ops = [a["op"] for a in audit_v1]
    assert "batch" in ops and "read_attr" in ops and "hello" in ops
    assert any(a["error"] for a in audit_v1), "refusal must be audited"


def test_batch_forbidden_ops_and_cap(served):
    root, server, client = served
    results = client.run_batch([
        {"op": "node_exists", "path": os.path.join(root, "dev")},
        {"op": "open_node", "path": "/dev/vfio/11"},
        {"op": "shutdown"},
        {"op": "write_sysfs", "path": "/sys/x", "data": "y"},
        {"op": "frobnicate"},
    ])
    assert results[0]["ok"] is True
    for res in results[1:]:
        assert res["ok"] is False and res["kind"] == "refused"
    with pytest.raises(BrokerError, match="batch of"):
        client.run_batch([{"op": "node_exists", "path": "/dev"}]
                         * (brokeripc.MAX_BATCH_OPS + 1))


# ------------------------------------------- round 20: response ring


def test_ring_writer_reader_round_trip_and_stats():
    writer = brokeripc.RingWriter(slots=8, slot_size=256)
    reader = brokeripc.RingReader(os.dup(writer.fd))
    try:
        key = brokeripc.ring_key("read_attr", "/sys/x/vendor")
        assert writer.publish(key, {"ok": True, "data": "0x1ae0"})
        value, verdict = reader.lookup(key, ttl_s=60.0)
        assert verdict == "hit"
        assert value == {"ok": True, "data": "0x1ae0"}
        # unpublished key: miss (empty slot or key mismatch)
        assert reader.lookup(
            brokeripc.ring_key("read_attr", "/other"), ttl_s=60.0)[1] \
            in ("miss",)
    finally:
        reader.close()
        writer.close()


def test_ring_torn_write_detected_and_stale_ttl():
    writer = brokeripc.RingWriter(slots=8, slot_size=256)
    reader = brokeripc.RingReader(os.dup(writer.fd))
    try:
        key = brokeripc.ring_key("probe_config", "/sys/x/config")
        assert writer.publish(key, {"verdict": 1})
        # TTL of zero: the entry is immediately stale — fall back
        assert reader.lookup(key, ttl_s=0.0)[1] == "stale"
        # fake a writer caught mid-update: odd seqlock == torn
        import zlib
        slot_off = brokeripc._RING_HEADER_PAD \
            + (zlib.crc32(key) % writer.slots) * writer.slot_size
        seq = struct.unpack_from(">I", writer._mm, slot_off)[0]
        struct.pack_into(">I", writer._mm, slot_off, seq | 1)
        assert reader.lookup(key, ttl_s=60.0)[1] == "torn"
        # writer completes (seq moves on, even): readable again
        struct.pack_into(">I", writer._mm, slot_off, (seq | 1) + 1)
        value, verdict = reader.lookup(key, ttl_s=60.0)
        assert verdict == "hit" and value == {"verdict": 1}
    finally:
        reader.close()
        writer.close()


def test_ring_oversized_value_skipped_not_torn():
    writer = brokeripc.RingWriter(slots=4, slot_size=128)
    reader = brokeripc.RingReader(os.dup(writer.fd))
    try:
        key = brokeripc.ring_key("read_attr", "/sys/x/vendor")
        assert writer.publish(key, {"data": "y" * 500}) is False
        assert writer.stats()["skipped_oversize_total"] == 1
        assert reader.lookup(key, ttl_s=60.0)[1] == "miss"
    finally:
        reader.close()
        writer.close()


def test_ring_fault_forces_socket_fallback_with_correct_value(served):
    """The broker.ring fault site: an injected torn read falls back to
    the socket and still returns the RIGHT bytes — detected, counted,
    never wrong."""
    root, server, client = served
    from tests.fakehost import FakeChip, FakeHost
    host = FakeHost(root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    vendor = os.path.join(root, "sys/bus/pci/devices",
                          "0000:00:04.0", "vendor")
    assert client.stats()["ring_attached"] is True
    first = client.read_attr("0000:00:04.0", vendor)   # publishes
    hits0 = client.ring_hits.value
    assert client.read_attr("0000:00:04.0", vendor) == first
    assert client.ring_hits.value == hits0 + 1
    fallbacks0 = client.ring_fallbacks.value
    crossings0 = client.crossings.value
    with faults.injected("broker.ring", kind="drop", count=1):
        assert client.read_attr("0000:00:04.0", vendor) == first
    assert client.ring_fallbacks.value == fallbacks0 + 1
    assert client.crossings.value == crossings0 + 1, \
        "the fallback must be a real, counted crossing"
