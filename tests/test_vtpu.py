"""vTPU partition plugin: scoped mounts, live validation, packing preference."""

import os
from concurrent import futures

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.vtpu import VtpuDevicePlugin


@pytest.fixture
def mdev_rig(short_root):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", numa_node=1))
    host.add_mdev("uuid-a1", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    host.add_mdev("uuid-a2", "TPU vhalf", "0000:00:04.0", iommu_group="22")
    host.add_mdev("uuid-b1", "TPU vhalf", "0000:00:05.0", iommu_group="23")
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["TPU_vhalf"]
    plugin = VtpuDevicePlugin(cfg, "TPU_vhalf", registry, parts)
    return host, cfg, plugin


def _serve(plugin):
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    api.add_device_plugin_servicer(server, plugin)
    server.add_insecure_port(f"unix://{plugin.socket_path}")
    server.start()
    return server


def test_mdev_allocate_scoped_vfio_mount(mdev_rig):
    host, cfg, plugin = mdev_rig
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            resp = stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["uuid-a1"])]),
                timeout=5)
            cresp = resp.container_responses[0]
            # only the partition's own group — never the whole /dev/vfio dir
            assert [d.container_path for d in cresp.devices] == \
                ["/dev/vfio/vfio", "/dev/vfio/21"]
            assert cresp.envs[
                "MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_TPU_VHALF"] == "uuid-a1"
    finally:
        server.stop(0)


def test_mdev_allocate_without_group_falls_back_wide(short_root):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_mdev("uuid-x", "TPU vhalf", "0000:00:04.0")  # no iommu_group
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    plugin = VtpuDevicePlugin(cfg, "TPU_vhalf", registry,
                              registry.partitions_by_type["TPU_vhalf"])
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["uuid-x"])]),
                timeout=5)
            assert [d.container_path for d in resp.container_responses[0].devices] == \
                ["/dev/vfio/vfio", "/dev/vfio"]
    finally:
        server.stop(0)


def test_mdev_type_mismatch_rejected(mdev_rig):
    host, cfg, plugin = mdev_rig
    # live sysfs now claims a different type for uuid-a1
    with open(os.path.join(host.pci, "0000:00:04.0", "uuid-a1",
                           "mdev_type", "name"), "w") as f:
        f.write("TPU vother\n")
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            with pytest.raises(grpc.RpcError) as exc_info:
                api.DevicePluginStub(ch).Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(devices_ids=["uuid-a1"])]),
                    timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop(0)


def test_mdev_unlink_recreate_different_type_rejected(mdev_rig):
    """The kept-fd live-type read must not serve the DELETED inode's bytes
    after the mdev is removed and recreated at the same uuid with another
    type: on a regular-file root (this test, --root re-rooting) unlink
    does not invalidate an open fd, so the reader's st_nlink staleness
    check is what catches it (LiveAttrReader)."""
    host, cfg, plugin = mdev_rig
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            # successful allocate primes the cached fd for uuid-a1
            stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["uuid-a1"])]),
                timeout=5)
            # remove + recreate the mdev at the same uuid, different type
            name_path = os.path.join(host.pci, "0000:00:04.0", "uuid-a1",
                                     "mdev_type", "name")
            os.unlink(name_path)
            with open(name_path, "w") as f:
                f.write("TPU vother\n")
            with pytest.raises(grpc.RpcError) as exc_info:
                stub.Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(devices_ids=["uuid-a1"])]),
                    timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "live type" in exc_info.value.details()
    finally:
        server.stop(0)


def test_unknown_partition_rejected(mdev_rig):
    host, cfg, plugin = mdev_rig
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            with pytest.raises(grpc.RpcError) as exc_info:
                api.DevicePluginStub(ch).Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(devices_ids=["nope"])]),
                    timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop(0)


def test_preferred_allocation_packs_parents(mdev_rig):
    host, cfg, plugin = mdev_rig
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["uuid-b1", "uuid-a1", "uuid-a2"],
                        allocation_size=2)]),
                timeout=5)
            picked = list(resp.container_responses[0].deviceIDs)
            # both partitions of chip 04 (the fullest parent), not one of each
            assert sorted(picked) == ["uuid-a1", "uuid-a2"]
    finally:
        server.stop(0)


def test_preferred_allocation_honors_must_include_parent(mdev_rig):
    host, cfg, plugin = mdev_rig
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["uuid-a1", "uuid-a2", "uuid-b1"],
                        must_include_deviceIDs=["uuid-b1"],
                        allocation_size=2)]),
                timeout=5)
            picked = list(resp.container_responses[0].deviceIDs)
            assert picked[0] == "uuid-b1"
            assert len(picked) == 2
    finally:
        server.stop(0)


def test_logical_partition_allocate_mounts_accel(short_root, tmp_path):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=3))
    pc = tmp_path / "partitions.json"
    import json
    pc.write_text(json.dumps({"per_core": True}))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root),
                  partition_config_path=str(pc))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["v4-core"]
    plugin = VtpuDevicePlugin(cfg, "v4-core", registry, parts)
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(
                        devices_ids=["0000:00:04.0-core0",
                                     "0000:00:04.0-core1"])]),
                timeout=5)
            cresp = resp.container_responses[0]
            # both cores share one accel node -> deduped single spec
            assert [d.container_path for d in cresp.devices] == ["/dev/accel3"]
            assert cresp.devices[0].permissions == "rw"
    finally:
        server.stop(0)


def test_logical_partition_readonly_node_permissions(short_root, tmp_path):
    """--partition-node-permissions r: accel-backed partitions hand the VMI
    a read-only node (docs/design.md, vTPU trust boundary)."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=3))
    pc = tmp_path / "partitions.json"
    import json
    pc.write_text(json.dumps({"per_core": True}))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root),
                  partition_config_path=str(pc),
                  partition_node_permissions="r")
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["v4-core"]
    plugin = VtpuDevicePlugin(cfg, "v4-core", registry, parts)
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(
                        devices_ids=["0000:00:04.0-core0"])]),
                timeout=5)
            assert resp.container_responses[0].devices[0].permissions == "r"
    finally:
        server.stop(0)


def test_logical_partition_without_accel_mounts_parent_group(short_root, tmp_path):
    """Explicit partition of a vfio-bound parent with no accel node: the VMI
    must still receive DeviceSpecs — the parent's VFIO group (VERDICT r1 #4)."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))  # vfio-bound
    import json
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["vslice"]
    plugin = VtpuDevicePlugin(cfg, "vslice", registry, parts)
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["p0"])]),
                timeout=5)
            cresp = resp.container_responses[0]
            assert [d.container_path for d in cresp.devices] == \
                ["/dev/vfio/vfio", "/dev/vfio/11"]
            assert cresp.envs[
                "MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_VSLICE"] == "p0"
    finally:
        server.stop(0)


def test_unallocatable_logical_partition_refused_at_discovery(short_root, tmp_path):
    """A partition with neither an accel node nor a vfio-bound parent can
    never produce a DeviceSpec — discovery must drop it with a reason."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    import json
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "ghost", "type": "vslice", "parent_bdf": "0000:00:99.0"},
        {"uuid": "ok0", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    registry, _ = discover(cfg)
    uuids = [p.uuid for p in registry.partitions_by_type.get("vslice", ())]
    assert uuids == ["ok0"]


def test_vfio_backed_partition_sets_pci_resource_env(short_root, tmp_path):
    """virt-launcher attaches vfio-backed partitions as PCI passthrough of
    the parent; the PCI_RESOURCE env must carry the parent's BDF group."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    import json
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"partitions": [
        {"uuid": "p0", "type": "vslice", "parent_bdf": "0000:00:04.0"}]}))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), partition_config_path=str(pc))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    plugin = VtpuDevicePlugin(cfg, "vslice", registry,
                              registry.partitions_by_type["vslice"])
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            resp = api.DevicePluginStub(ch).Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["p0"])]),
                timeout=5)
            envs = dict(resp.container_responses[0].envs)
            assert envs["MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_VSLICE"] == "p0"
            assert envs["PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_VSLICE"] == \
                "0000:00:04.0"
    finally:
        server.stop(0)


def test_preferred_allocation_numa_tiebreak(short_root):
    """Equal-occupancy parents: prefer the one on the must-include's NUMA
    node (the reference stubs this RPC entirely)."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", numa_node=1))
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="13", numa_node=1))
    for i, parent in enumerate(["0000:00:04.0", "0000:00:05.0", "0000:00:06.0"]):
        host.add_mdev(f"uuid-{i}", "TPU vhalf", parent, iommu_group=str(21 + i))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover(cfg)
    plugin = VtpuDevicePlugin(cfg, "TPU_vhalf", registry,
                              registry.partitions_by_type["TPU_vhalf"])
    server = _serve(plugin)
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            # must-include uuid-1 (numa 1): the second pick should be uuid-2
            # (the other numa-1 parent), not numa-0's uuid-0
            resp = api.DevicePluginStub(ch).GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["uuid-0", "uuid-2"],
                        must_include_deviceIDs=["uuid-1"],
                        allocation_size=2)]),
                timeout=5)
            picked = list(resp.container_responses[0].deviceIDs)
            assert picked == ["uuid-1", "uuid-2"]
    finally:
        server.stop(0)


def test_probe_receives_parent_node_path(short_root):
    """Probes run per parent BDF while watch paths are keyed by partition
    uuid; the probe must still see a representative child's device node so
    chip_alive's node-presence AND (the degraded-inotify backstop) runs."""
    import time
    from dataclasses import replace
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    import json
    pc = os.path.join(host.root, "partitions.json")
    with open(pc, "w") as f:
        f.write(json.dumps({"per_core": True}))
    cfg = replace(Config().with_root(host.root),
                  partition_config_path=pc, health_poll_s=0.1)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    from tests.fakehost import FakeKubelet
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["v4-core"]
    calls = []

    class RecordingShim:
        def chip_alive(self, pci_base, bdf, node=None):
            calls.append((bdf, node))
            return True

    plugin = VtpuDevicePlugin(cfg, "v4-core", registry, parts,
                              health_shim=RecordingShim())
    plugin.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not calls:
            time.sleep(0.02)
        assert calls, "probe never ran"
        bdf, node = calls[0]
        assert bdf == "0000:00:04.0"
        assert node is not None and node.endswith("accel0")
    finally:
        plugin.stop()
        kubelet.stop()


def test_parent_chip_death_fans_out_to_all_partitions(short_root):
    """One probe per DISTINCT parent; a dead chip (all-FF config space)
    marks every partition of that chip Unhealthy."""
    import time
    from dataclasses import replace
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11",
                           driver="google-tpu", accel_index=0))
    import json
    pc = os.path.join(host.root, "partitions.json")
    with open(pc, "w") as f:
        f.write(json.dumps({"per_core": True}))
    cfg = replace(Config().with_root(host.root),
                  partition_config_path=pc, health_poll_s=0.2)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    from tests.fakehost import FakeKubelet
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, _ = discover(cfg)
    parts = registry.partitions_by_type["v4-core"]
    assert len(parts) == 2
    plugin = VtpuDevicePlugin(cfg, "v4-core", registry, parts)
    plugin.start()
    try:
        # chip falls off the bus: config space reads all-FF
        with open(os.path.join(host.pci, "0000:00:04.0", "config"), "wb") as f:
            f.write(b"\xff" * 4)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            devs = plugin.status_snapshot()["devices"]
            if set(devs.values()) == {"Unhealthy"}:
                break
            time.sleep(0.05)
        assert set(devs.values()) == {"Unhealthy"}, devs
        assert len(devs) == 2
    finally:
        plugin.stop()
        kubelet.stop()
