"""GPipe-style microbatched pipeline parallelism over the "pp" mesh axis.

The workload's default pp regime stage-shards the stacked layer weights and
lets XLA move data between scan steps. This module is the explicit-schedule
alternative: inside `shard_map`, each pp rank holds ONLY its stage's layers
(the stacked (L, ...) weights are sharded on L), and activations flow
stage-to-stage with nearest-neighbor `ppermute` — the classic GPipe
fill/drain schedule over `n_micro` microbatches, expressed as one
`lax.scan` over schedule steps (static shapes, compiler-friendly, and
differentiable: JAX transposes the ppermute schedule into the reverse-order
backward sweep automatically).

Scope: pipeline ranks run the dense per-stage computation locally, so the
mesh's sp/tp axes must be 1 (dp composes freely — gradient psum over dp is
inserted by shard_map's AD like in the non-pipelined path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .workload import ModelConfig, Params, _rms_norm, layer_block


def _stage_apply(x, layer_stack, cfg: ModelConfig):
    """Run this rank's slice of the layer stack (same body as workload).

    With cfg.remat the shared block is checkpointed — GPipe stores one
    activation per in-flight microbatch per schedule step, so remat keeps
    that at O(1) per layer."""
    block = layer_block(cfg)

    def body(x, layer):
        return block(x, layer, cfg, "einsum", True, None), None
    x, _ = jax.lax.scan(body, x, layer_stack)
    return x


def gpipe_loss_fn(params: Params, tokens: jax.Array, cfg: ModelConfig,
                  mesh: Mesh, n_micro: int) -> jax.Array:
    """Causal-LM loss computed with an explicit GPipe schedule.

    `params` is the workload's stacked-layer tree; layers are sharded over
    "pp" (each rank sees n_layers/pp of them), embed/unembed replicated,
    tokens sharded over "dp". Loss is identical to `workload.loss_fn` up to
    bf16 reduction order.
    """
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if "pp" not in axis_sizes:
        raise ValueError("gpipe path needs a 'pp' mesh axis "
                         "(slice_mesh(..., pp=N) with N > 1)")
    n_stages = axis_sizes["pp"]
    if axis_sizes.get("sp", 1) != 1 or axis_sizes.get("tp", 1) != 1 \
            or axis_sizes.get("ep", 1) != 1:
        # ep would silently replicate the whole pipeline per expert rank
        # (no expert dispatch in this schedule) — reject like sp/tp
        raise ValueError("gpipe path needs sp == tp == ep == 1 (pp x dp mesh)")
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp={n_stages}")

    def body(layers, embed, unembed, tok):
        stage = jax.lax.axis_index("pp")
        last = n_stages - 1
        b, s = tok.shape
        if b % n_micro:
            raise ValueError(f"local batch {b} not divisible by "
                             f"n_micro={n_micro}")
        mb = b // n_micro
        micro = tok.reshape(n_micro, mb, s)
        d = embed.shape[1]
        outputs0 = jnp.zeros((n_micro, mb, s, d), jnp.bfloat16)
        recv0 = jnp.zeros((mb, s, d), jnp.bfloat16)

        def sched(carry, t):
            recv, outputs = carry
            # stage 0 feeds microbatch t into the pipe (clamped during drain)
            feed = embed.astype(jnp.bfloat16)[
                jnp.take(micro, jnp.clip(t, 0, n_micro - 1), axis=0)]
            x_in = jnp.where(stage == 0, feed, recv)
            y = _stage_apply(x_in, layers, cfg)
            # hand to the next stage; rank 0 receives nothing (zeros stay)
            recv_next = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(n_stages - 1)])
            # the last stage's step-t output belongs to microbatch t-(pp-1)
            out_idx = t - last
            safe = jnp.clip(out_idx, 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe, 0,
                                               keepdims=False)
            take = (stage == last) & (out_idx >= 0)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(take, y, cur), safe, 0)
            return (recv_next, outputs), None

        steps = jnp.arange(n_micro + n_stages - 1)
        (_, outputs), _ = jax.lax.scan(sched, (recv0, outputs0), steps)

        # loss on the last stage only; psum broadcasts it to every rank
        logits = (_rms_norm(outputs) @ unembed.astype(jnp.bfloat16)
                  ).astype(jnp.float32)                    # (M, mb, s, V)
        targets = micro[:, :, 1:]
        logprobs = jax.nn.log_softmax(logits[:, :, :-1])
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)
        local = jnp.where(stage == last, jnp.mean(nll), 0.0)
        loss = jax.lax.psum(local, "pp")
        # average over data-parallel ranks like the sharded-mean in loss_fn
        return jax.lax.pmean(loss, "dp")

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P("pp"), params["layers"]),
            P(),                      # embed replicated
            P(),                      # unembed replicated
            P("dp", None),            # tokens data-parallel
        ),
        out_specs=P(),
        check_vma=False,
    )
    return fn(params["layers"], params["embed"], params["unembed"], tokens)


def build_gpipe(cfg: ModelConfig, mesh: Mesh, n_micro: int, seed: int = 0,
                lr=None):
    """(jitted training step, params, momentum, tokens) for the GPipe path."""
    from .workload import init_params
    lr = cfg.lr if lr is None else lr
    params = init_params(jax.random.key(seed), cfg)
    momentum = jax.tree.map(jnp.zeros_like, params)
    tokens = jax.random.randint(
        jax.random.key(seed + 1), (cfg.batch, cfg.seq_len), 0, cfg.vocab,
        dtype=jnp.int32)

    def step(params, momentum, tokens):
        loss, grads = jax.value_and_grad(gpipe_loss_fn)(
            params, tokens, cfg, mesh, n_micro)
        momentum = jax.tree.map(
            lambda m, g: cfg.momentum * m + g, momentum, grads)
        params = jax.tree.map(lambda p, m: p - lr * m, params, momentum)
        return params, momentum, loss

    return jax.jit(step, donate_argnums=(0, 1)), params, momentum, tokens
