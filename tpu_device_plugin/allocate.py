"""Allocate(): turn requested BDFs into VFIO DeviceSpecs + KubeVirt env vars.

TPU analogue of the reference's passthrough Allocate
(generic_device_plugin.go:352-444): expand each requested BDF to its whole
IOMMU group, re-validate live sysfs against the discovery-time snapshot
(TOCTOU guard, :388-397), emit `/dev/vfio/vfio` + `/dev/vfio/<group>` (plus
the iommufd trio when `/dev/iommu` exists, :692-716), and set the
`PCI_RESOURCE_...` env var KubeVirt's virt-launcher reads to pick the PCI
devices for the VMI (externalResourceProvider contract).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import broker
from . import lockdep
from . import schedcheck
from . import trace
from .config import Config
from .epoch import AtomicCounter, encode_delimited
from .kubeletapi import pb
from .naming import sanitize_name
from .readcount import WindowRegistry
from .registry import Registry, SharedDevice

log = logging.getLogger(__name__)


class AllocationError(Exception):
    """Request references devices this plugin cannot serve (unknown/invalid)."""


# --- plan-path sysfs accounting (shared machinery: readcount.py) -------------
# Same contract as discovery.count_reads: the attach-path perf-honesty guard
# and `bench.py --attach-burst` assert on sysfs access COUNTS (listdir/
# readlink/exists/attribute-read on the Allocate plan path), because counts —
# unlike wall clock on a shared CPU — are load-insensitive. Windowless calls
# cost one truthiness check.

_plan_registry = WindowRegistry()
_plan_note = _plan_registry.note


def count_plan_reads(confine_thread: bool = False):
    """Count this module's sysfs accesses inside the with-block (nests;
    `confine_thread=True` counts only the opening thread — concurrent
    plan() threads on the gRPC pool would inflate a cross-thread window,
    the same hazard discovery's stats gauge guards against)."""
    return _plan_registry.window(confine_thread)


class LiveAttrReader:
    """Kept-open-fd live reads of small sysfs attributes.

    pread(fd, …, 0) re-runs the attribute's sysfs show() on every call, so
    the read stays LIVE (TOCTOU-guard grade) at stat+pread cost (plus one
    fstat per slow-path install) instead of open+read+close per call.
    Staleness is detected two ways, because
    the plugin also runs over regular-file roots (tests, --root
    re-rooting) where an unlinked file's fd would otherwise keep serving
    old bytes forever: the PATH's (st_dev, st_ino) identity is compared
    against the cached fd's — catching unlink/replace on any filesystem,
    including ones that report st_nlink >= 1 for open unlinked files
    (9p/overlay) — and pread errors/empty reads catch sysfs inode
    invalidation. Either falls back to a fresh open, so a genuinely new
    device at the same path is still re-validated from scratch.

    The STEADY-STATE read is LOCK-FREE (the Allocate path's lockdep gate
    pins zero acquisitions): the cache maps key -> an immutable
    (fd, st_dev, st_ino) record, and the fast path is stat(path) ==
    cached identity -> pread(fd) -> RECORD RECHECK (`_fds.get(key) is
    rec`). The recheck closes the fd-reuse hole a lock used to close,
    completely: every replace/evict swaps the dict entry BEFORE closing
    the old fd, so "rec still cached after the pread" happens-before any
    close of rec's fd — the bytes are genuine. If the record moved, the
    pread may have raced a close/reuse (even a double reuse landing back
    on a matching inode — the ABA a trailing fstat could not rule out),
    so the bytes are discarded and the slow path re-reads fresh. A
    closed-unreused fd preads EBADF and falls through identically.
    Only the slow path (first open, stale replace) takes `_lock`.

    read() returns non-empty fresh bytes or None — an empty file is
    reported as None (and never cached), keeping the contract single-faced
    for callers that treat None as "attribute gone".
    """

    def __init__(self) -> None:
        # key -> (fd, st_dev, st_ino); records are immutable tuples,
        # replaced (never mutated) under _lock
        self._fds: Dict[str, Tuple[int, int, int]] = {}
        self._lock = lockdep.instrument(
            "allocate.LiveAttrReader._lock", threading.Lock())

    def __del__(self, _close=os.close):
        # _close bound at def time: os.close may already be torn down when
        # a reader is collected at interpreter shutdown
        for rec in getattr(self, "_fds", {}).values():
            try:
                _close(rec[0])
            except OSError:
                pass

    def read(self, key: str, path: str) -> Optional[bytes]:
        """Fresh non-empty bytes of `path` (cached fd keyed by `key`);
        None if gone/unreadable/empty."""
        schedcheck.yield_point("attr.read.lookup", obj=self, mode="r")
        rec = self._fds.get(key)          # GIL-atomic; no lock
        if rec is not None:
            fd, dev, ino = rec
            try:
                st = os.stat(path)
                if (st.st_dev, st.st_ino) == (dev, ino):
                    schedcheck.yield_point("attr.read.pread", obj=self,
                                           mode="r")
                    raw = os.pread(fd, 256, 0)
                    # record recheck (class docstring): replaces swap the
                    # dict entry before closing the fd, so rec still
                    # being cached proves no close raced the pread
                    schedcheck.yield_point("attr.read.recheck", obj=self,
                                           mode="r")
                    if raw and self._fds.get(key) is rec:
                        return raw
            except OSError:
                pass
            # stale record (file unlinked/replaced, inode invalidated,
            # fd swapped under us, or content gone): slow path
        return self._read_slow(key, path, rec)

    def _read_slow(self, key: str, path: str,
                   stale: Optional[Tuple[int, int, int]]) -> Optional[bytes]:
        """Open fresh, read, and (re)install the record under the lock.
        `stale` is the record the fast path found wanting — evicted (and
        its fd closed) only if it is still the cached one."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            self._evict(key, stale)
            return None
        try:
            raw = os.pread(fd, 256, 0)
            st = os.fstat(fd)
        except OSError:
            os.close(fd)
            self._evict(key, stale)
            return None
        if not raw:
            os.close(fd)   # empty attribute: report None, never cache
            self._evict(key, stale)
            return None
        rec = (fd, st.st_dev, st.st_ino)
        close_fd: Optional[int] = None
        with self._lock:
            prev = self._fds.get(key)
            if prev is None or prev is stale:
                # ORDERING CONTRACT: the dict swap (here, under the lock)
                # happens-before the close below — the fast path's record
                # recheck relies on it
                schedcheck.yield_point("attr.swap.install", obj=self)
                self._fds[key] = rec
                if prev is not None:
                    close_fd = prev[0]   # the replaced stale fd
            else:
                close_fd = fd   # lost the race; another thread installed
        if close_fd is not None:
            # closing a replaced fd can race a concurrent fast-path pread
            # on it — that reader's record recheck discards the bytes
            # (the entry was already swapped), so the close is safe here
            schedcheck.yield_point("attr.swap.close", obj=self)
            try:
                os.close(close_fd)
            except OSError:
                pass
        return raw

    def _evict(self, key: str,
               stale: Optional[Tuple[int, int, int]]) -> None:
        if stale is None:
            return
        with self._lock:
            if self._fds.get(key) is stale:
                del self._fds[key]
            else:
                stale = None   # someone else already replaced/evicted it
        if stale is not None:
            try:
                os.close(stale[0])
            except OSError:
                pass


def live_mdev_type(reader: LiveAttrReader, cfg: Config, uuid: str,
                   prefetched: Optional[bytes] = None) -> str:
    """Live mdev_type/name read (TOCTOU-grade, kept-fd) for Allocate-time
    validation; raises AllocationError when the mdev is gone. Shared by the
    classic vTPU server and the DRA prepare path so the two APIs can never
    validate the same partition differently (reference analogue:
    generic_vgpu_device_plugin.go:216-221).

    The read rides the broker seam in spawn mode (broker.py: the
    privileged process does the sysfs read), so a read-only serving
    daemon prepares mdev partitions without touching the host tree; the
    in-process mode keeps the caller's kept-fd reader — same bytes,
    same lock-free fast path, same read counts.

    `prefetched` carries bytes a BATCHED crossing already fetched (the
    DRA prepare path coalesces every mdev partition's name read into
    one round trip, round 20): the validation below is identical, only
    the per-partition round trip is skipped. A failed prefetch is simply
    not passed, so the singular read (and its diagnostics) still runs."""
    name_path = os.path.join(cfg.mdev_base_path, uuid, "mdev_type", "name")
    _plan_note(name_path)
    client = broker.get_client()
    spawn = client.mode == "spawn"
    if prefetched is not None:
        raw: Optional[bytes] = prefetched
    elif spawn:
        raw = client.read_attr(uuid, name_path)
    else:
        raw = reader.read(uuid, name_path)
    if raw is None:
        if spawn:
            # the broker did the (failed) read host-side; a local
            # diagnostic open would report THIS daemon's lack of host
            # access, not the real errno — exists() through the same
            # seam distinguishes the two triage cases instead
            detail = ("present but empty or unreadable host-side"
                      if client.node_exists(name_path)
                      else "gone host-side")
        else:
            # failure path only: one diagnostic open to recover the errno
            # the operator needs (EACCES mount misconfig vs ENOENT gone)
            try:
                with open(name_path, "rb"):
                    detail = "empty or unreadable"
            except OSError as exc:
                detail = str(exc)
        raise AllocationError(f"partition {uuid}: mdev vanished ({detail})")
    return raw.decode("ascii", "replace").strip().replace(" ", "_")


def supports_iommufd(cfg: Config) -> bool:
    """iommufd-capable host: /dev/iommu exists (reference :692-701).

    Probed through the broker seam (broker.py): a /dev access is a
    privileged fact, and routing it here means a read-only serving
    daemon (CI, tests, spawn mode) never stats the real /dev tree
    itself. One counted crossing; the planner's TTL cache keeps it off
    the steady-state attach path."""
    path = cfg.dev_path("dev/iommu")
    _plan_note(path)
    return broker.get_client().node_exists(path)


def vfio_device_node(cfg: Config, bdf: str) -> Optional[str]:
    """`vfioN` cdev name from sysfs `<bdf>/vfio-dev/` (reference :702-716)."""
    vfio_dev_dir = os.path.join(cfg.pci_base_path, bdf, "vfio-dev")
    _plan_note(vfio_dev_dir)
    try:
        entries = sorted(os.listdir(vfio_dev_dir))
    except OSError:
        return None
    for entry in entries:
        if entry.startswith("vfio"):
            return entry
    return None


def discover_shared_devices(cfg: Config) -> List[SharedDevice]:
    """Scan shared-device classes (EGM analogue, reference :120-157).

    Each class entry lists its member chips in a `chip_devices` file
    (`gpu_devices` also accepted so Grace-Hopper-style EGM trees work) and has
    a matching /dev node. Shared devices are injected all-or-nothing.
    """
    out: List[SharedDevice] = []
    for class_dir in cfg.shared_device_classes:
        _plan_note(class_dir)
        try:
            entries = sorted(os.listdir(class_dir))
        except OSError:
            continue
        for name in entries:
            members: Optional[Tuple[str, ...]] = None
            for member_file in ("chip_devices", "gpu_devices"):
                path = os.path.join(class_dir, name, member_file)
                try:
                    with open(path, "r", encoding="ascii", errors="replace") as f:
                        members = tuple(l.strip() for l in f if l.strip())
                    break
                except OSError:
                    continue
            if not members:
                continue
            dev_path = cfg.dev_path("dev", name)
            if not os.path.exists(dev_path):
                log.warning("shared device %s has no %s; skipping", name, dev_path)
                continue
            out.append(SharedDevice(name=name, dev_path=dev_path, member_bdfs=members))
    return out


@dataclass
class AllocationPlan:
    device_specs: List[pb.DeviceSpec]
    envs: Dict[str, str]
    expanded_bdfs: List[str]
    # fully-qualified CDI names for the expanded devices, precomputed in the
    # group fragment (None when the planner predates the fragment, e.g. a
    # hand-built plan in tests); allocate_response falls back to computing
    # them per call
    cdi_names: Optional[List[str]] = None


# ContainerAllocateResponse field numbers (deviceplugin_v1beta1.proto):
# the byte plane concatenates length-delimited records of exactly these
_F_ENVS = 1          # map<string,string> envs (entry: key=1, value=2)
_F_DEVICES = 3       # repeated DeviceSpec devices
_F_CDI_DEVICES = 5   # repeated CDIDevice cdi_devices
_F_CONTAINER = 1     # AllocateResponse.container_responses


class _GroupFragment:
    """Precompiled Allocate response fragment for ONE IOMMU group.

    Everything deterministic given (registry snapshot, group, iommufd
    state) is built once and concatenated per request: the member-BDF
    expansion order, the iommufd cdev DeviceSpecs (the per-member
    `vfio-dev/` listdirs are the dominant sysfs cost of a cold plan), the
    members' CDI names — and, since round 15, the SERIALIZED byte records
    of those specs/names (group DeviceSpec, iommufd cdev DeviceSpecs, CDI
    names), so a warm Allocate concatenates bytes instead of re-building
    and re-serializing protos. What is NOT in the fragment, by design:
    the per-member TOCTOU revalidation (group link + vendor), which stays
    a live read on every plan.

    Invalidation is BY CONSTRUCTION: fragments live in a cache keyed by
    the caller's epoch token (epoch.py), and a health flap publishes a
    new epoch — the next plan starts a fresh cache and re-lists cdevs.
    An iommufd-state flip misses naturally inside an epoch (the flag is
    part of the fragment). Blind spot: a vfio cdev renamed with NO
    membership change and NO health event serves the stale cdev name
    until a flap or rebuild — the same contract as incremental
    discovery (docs/perf.md).
    """

    __slots__ = ("iommufd", "member_bdfs", "iommufd_specs", "cdi_names",
                 "group_rec", "iommufd_recs", "cdi_recs")

    def __init__(self, iommufd: bool, member_bdfs: Tuple[str, ...],
                 iommufd_specs: Tuple[pb.DeviceSpec, ...],
                 cdi_names: Tuple[str, ...],
                 group_rec: bytes = b"",
                 iommufd_recs: bytes = b"",
                 cdi_recs: bytes = b""):
        self.iommufd = iommufd
        self.member_bdfs = member_bdfs
        self.iommufd_specs = iommufd_specs
        self.cdi_names = cdi_names
        # pre-serialized field records (empty for hand-built fragments in
        # tests — allocate_response_bytes is only reached via the planner,
        # whose _build_fragment always fills them)
        self.group_rec = group_rec
        self.iommufd_recs = iommufd_recs
        self.cdi_recs = cdi_recs


class AllocationPlanner:
    """Per-plugin Allocate fast path.

    Plugin servers are rebuilt on every rediscovery signature change
    (lifecycle.py), so anything deterministic given (cfg, registry,
    resource) is precomputed once here: the KubeVirt env-var key, the
    leading /dev/vfio/vfio DeviceSpec, one /dev/vfio/<group> DeviceSpec
    template per IOMMU group, and each device's revalidation paths.

    What stays LIVE, by design: the TOCTOU guard still re-reads every
    allocated device's iommu_group link and vendor id from sysfs on every
    Allocate (reference behavior, generic_device_plugin.go:388-397) — for
    a multi-group request those reads are batched through one pass — and
    the iommufd probe re-stats /dev/iommu (:362,692-701). The vfio cdev
    names and the rest of the per-group response live in a precompiled
    _GroupFragment cache keyed by the caller's epoch token — a health
    flap publishes a new epoch, so fragments are invalidated by
    construction (the reference re-listed cdevs per Allocate, :702-716).
    The shared-device (EGM-analogue) scan is
    cached for cfg.shared_scan_ttl_s (0 = the reference's
    rescan-every-Allocate behavior, :366,120-157).

    `allowed_bdfs` (fixed at construction) scopes every request to the
    owning plugin's devices: the reference resolves any BDF in its global
    map, so its v-something plugin would allocate another model's GPUs
    (generic_device_plugin.go:376-380) — here a cross-model BDF is an
    AllocationError. None = unscoped (vTPU parent expansion).
    """

    def __init__(
        self,
        cfg: Config,
        registry: Registry,
        resource_suffix: str,
        allowed_bdfs: Optional[frozenset] = None,
        cdi_enabled: Optional[bool] = None,
        broker_client=None,
        byte_records: bool = True,
    ) -> None:
        self.cfg = cfg
        self.registry = registry
        # the privilege seam (broker.py): the per-plan TOCTOU
        # revalidation batch crosses it exactly once — in-process the
        # crossing runs this planner's own live readers (zero registered
        # locks, the epoch gate's contract); in spawn mode the broker
        # process does the reads
        self._broker = broker_client or broker.get_client()
        self.resource_suffix = resource_suffix
        self.allowed_bdfs = allowed_bdfs
        self.cdi_enabled = (bool(cfg.cdi_spec_dir) if cdi_enabled is None
                            else cdi_enabled)
        self.env_key = f"{cfg.env_prefix}_{sanitize_name(resource_suffix)}"
        self._vfio_spec = pb.DeviceSpec(
            host_path=cfg.dev_path("dev/vfio/vfio"),
            container_path="/dev/vfio/vfio",
            permissions="mrw",
        )
        self._group_specs: Dict[str, pb.DeviceSpec] = {
            group: pb.DeviceSpec(
                host_path=cfg.dev_path("dev/vfio", group),
                container_path=f"/dev/vfio/{group}",
                permissions="mrw",
            )
            for group in registry.iommu_map
        }
        self._iommu_spec = pb.DeviceSpec(
            host_path=cfg.dev_path("dev/iommu"),
            container_path="/dev/iommu",
            permissions="mrw",
        )
        # Byte-plane statics (round 15): everything fixed at construction
        # is serialized ONCE here — the per-request assembly in
        # allocate_response_bytes is pure bytes concatenation. The env
        # VALUE (joined expanded BDFs) is the only request-dependent part
        # of the envs entry; its key record is precomputed, the value is
        # patched in per request. `byte_records=False` skips ALL of it:
        # planners that only ever serve the message path (the vTPU parent
        # planner, the DRA prepare planners, the bench's byte_plane=False
        # A/B arm) must not pay — or ledger — serializations for records
        # nothing reads.
        self._byte_records = byte_records
        if byte_records:
            self._vfio_rec = encode_delimited(
                _F_DEVICES, self._vfio_spec.SerializeToString())
            self._group_recs: Dict[str, bytes] = {
                group: encode_delimited(_F_DEVICES,
                                        spec.SerializeToString())
                for group, spec in self._group_specs.items()
            }
            self._iommu_rec = encode_delimited(
                _F_DEVICES, self._iommu_spec.SerializeToString())
            self._env_key_rec = encode_delimited(
                1, self.env_key.encode("ascii"))   # EnvsEntry.key
        # response-plane protobuf serializations this planner paid
        # (fragment/segment builds at miss time, per-request shared-device
        # riders) — lock-free owned; the plugin server shares this counter
        # object and surfaces it as tpu_plugin_alloc_serializations_total
        self.serializations = AtomicCounter()
        # bdf → (iommu_group symlink path, vendor attribute path)
        self._reval_paths: Dict[str, Tuple[str, str]] = {
            bdf: (os.path.join(cfg.pci_base_path, bdf, "iommu_group"),
                  os.path.join(cfg.pci_base_path, bdf, "vendor"))
            for bdf in registry.bdf_to_group
        }
        self._vendor_ok = frozenset(v.lower() for v in cfg.vendor_ids)
        # raw sysfs spellings accepted without the slow-path decode
        self._vendor_ok_raw = frozenset(
            s for v in self._vendor_ok
            for s in (v.encode("ascii"), b"0x" + v.encode("ascii")))
        # live <bdf>/vendor reads for the TOCTOU guard (see LiveAttrReader)
        self._vendor_reader = LiveAttrReader()
        self._shared_cache: Optional[List[SharedDevice]] = None
        self._shared_expires = 0.0
        self._iommufd_cache: Optional[bool] = None
        self._iommufd_expires = 0.0
        # Precompiled per-group response fragments (see _GroupFragment),
        # keyed by EPOCH: the cache is a tuple of at most TWO
        # (epoch_token, dict) slots, newest first — a plan arriving with
        # an unseen token swaps in a fresh dict, retiring the oldest
        # slot. Invalidation by construction, replacing the PR-4
        # health-listener plumbing AND its lock; the second slot keeps a
        # long-running prepare pinned to the PREVIOUS inventory epoch
        # from ping-ponging the cache against new-epoch Allocates.
        # plan() runs on concurrent gRPC worker threads: lookups/stores
        # are GIL-atomic dict ops on the dict captured at plan start, so
        # a build racing an epoch swap lands in the orphaned dict
        # (served once, never reachable from the new epoch) — the old
        # _frag_epoch guard, for free.
        self._frag_cache: Tuple[Tuple[object, Dict[str, _GroupFragment]],
                                ...] = ()
        self.fragment_hits = AtomicCounter()
        self.fragment_misses = AtomicCounter()

    # ------------------------------------------------------ group fragments

    def invalidate_fragments(self) -> None:
        """Manual WHOLESALE drop (tests / ad-hoc callers). Production
        invalidation is by epoch key: the plugin servers and the DRA
        driver pass their current epoch id to plan(), and a health flip
        publishes a new epoch. Emptying the slots means the next plan —
        whatever token it passes, even an unchanged one — starts fresh."""
        self._frag_cache = ()

    def fragment_stats(self) -> Dict[str, int]:
        slots = self._frag_cache
        return {"hits": self.fragment_hits.value,
                "misses": self.fragment_misses.value,
                "size": len(slots[0][1]) if slots else 0}

    def _fragments_for(self, epoch: Optional[object]
                       ) -> Dict[str, _GroupFragment]:
        """The fragment dict for this epoch token (fresh when the token
        is unseen; the previous epoch's slot is retained so concurrent
        plans on adjacent epochs never thrash each other's caches; racy
        swaps are benign — every racer starts empty)."""
        slots = self._frag_cache
        for token, frags in slots:
            if token == epoch:
                return frags
        frags = {}
        self._frag_cache = ((epoch, frags),) + slots[:1]
        return frags

    def _fragment(self, group: str, iommufd: bool,
                  frags: Dict[str, _GroupFragment]) -> _GroupFragment:
        frag = frags.get(group)
        if frag is not None and frag.iommufd == iommufd:
            self.fragment_hits.add()
            return frag
        self.fragment_misses.add()
        # cold path only (a warm attach never reaches here): the rebuild
        # marker makes post-flap fragment churn visible on /debug/flight
        trace.event("allocate.fragment.rebuild", group=group,
                    iommufd=iommufd)
        frag = self._build_fragment(group, iommufd)
        frags[group] = frag
        return frag

    def _build_fragment(self, group: str, iommufd: bool) -> _GroupFragment:
        from .cdi import cdi_device_name
        cfg = self.cfg
        members = tuple(d.bdf for d in self.registry.iommu_map.get(group, ()))
        iommufd_specs: List[pb.DeviceSpec] = []
        if iommufd:
            for bdf in members:
                node = vfio_device_node(cfg, bdf)
                if node is None:
                    # On an iommufd host every vfio-bound device has a cdev;
                    # an unreadable vfio-dev entry would boot the VM with an
                    # incomplete device set — fail fast like the reference
                    # (generic_device_plugin.go:702-716 errors the Allocate).
                    # Failures are never cached.
                    raise AllocationError(
                        f"device {bdf}: iommufd host but no vfio-dev cdev")
                iommufd_specs.append(pb.DeviceSpec(
                    host_path=cfg.dev_path("dev/vfio/devices", node),
                    container_path=f"/dev/vfio/devices/{node}",
                    permissions="mrw",
                ))
        cdi_names = tuple(cdi_device_name(cfg, bdf) for bdf in members)
        if not self._byte_records:
            # message-path-only planner: no records, no ledger entries
            return _GroupFragment(
                iommufd=iommufd,
                member_bdfs=members,
                iommufd_specs=tuple(iommufd_specs),
                cdi_names=cdi_names)
        # serialize the per-group records ONCE, at fragment-build time
        # (cold path): warm byte-plane requests concatenate these without
        # touching protobuf. Counted: the serializations counter is the
        # honest ledger of what the response plane still serializes.
        iommufd_recs = []
        for spec in iommufd_specs:
            iommufd_recs.append(
                encode_delimited(_F_DEVICES, spec.SerializeToString()))
            self.serializations.add()
        cdi_recs = []
        for name in cdi_names:
            cdi_recs.append(encode_delimited(
                _F_CDI_DEVICES,
                pb.CDIDevice(name=name).SerializeToString()))
            self.serializations.add()
        return _GroupFragment(
            iommufd=iommufd,
            member_bdfs=members,
            iommufd_specs=tuple(iommufd_specs),
            cdi_names=cdi_names,
            group_rec=self._group_recs[group],
            iommufd_recs=b"".join(iommufd_recs),
            cdi_recs=b"".join(cdi_recs))

    def _revalidate_live(self, bdf: str, expected_group: str) -> None:
        """TOCTOU guard (NEVER cached): live sysfs must still agree with the
        discovery snapshot — group link unchanged, vendor still a TPU."""
        paths = self._reval_paths.get(bdf)
        if paths is None:  # device outside this registry snapshot
            base = os.path.join(self.cfg.pci_base_path, bdf)
            paths = (os.path.join(base, "iommu_group"),
                     os.path.join(base, "vendor"))
        glink, vpath = paths
        _plan_note(glink)
        try:
            target = os.readlink(glink)
        except OSError:
            target = ""
        if target.rsplit("/", 1)[-1] != expected_group:
            live = target.rsplit("/", 1)[-1] or None
            raise AllocationError(
                f"device {bdf}: iommu group changed "
                f"({expected_group!r} -> {live!r})")
        _plan_note(vpath)
        raw = self._vendor_reader.read(bdf, vpath)
        if raw is not None and raw.strip().lower() in self._vendor_ok_raw:
            return
        # slow path only to produce the same diagnostic as before
        vendor = (raw.strip().lower().decode("ascii", "replace")
                  if raw is not None else None)
        if vendor is not None and vendor.startswith("0x"):
            vendor = vendor[2:]
        if vendor is None or vendor not in self._vendor_ok:
            raise AllocationError(f"device {bdf}: vendor {vendor!r} is not a TPU")

    def shared_devices(self) -> List[SharedDevice]:
        ttl = getattr(self.cfg, "shared_scan_ttl_s", 0.0)
        now = time.monotonic()
        if self._shared_cache is None or ttl <= 0 or now >= self._shared_expires:
            self._shared_cache = discover_shared_devices(self.cfg)
            self._shared_expires = now + ttl
        return self._shared_cache

    def _iommufd(self) -> bool:
        """supports_iommufd under the same TTL as the shared-device scan:
        /dev/iommu is boot-time host configuration, but ttl=0 (the
        reference behavior, :692-701 stats it per Allocate) keeps the
        per-RPC stat for operators who want it."""
        ttl = getattr(self.cfg, "shared_scan_ttl_s", 0.0)
        now = time.monotonic()
        if self._iommufd_cache is None or ttl <= 0 \
                or now >= self._iommufd_expires:
            self._iommufd_cache = supports_iommufd(self.cfg)
            self._iommufd_expires = now + ttl
        return self._iommufd_cache

    def _resolve_groups(self, requested_bdfs: Sequence[str], iommufd: bool,
                        frags: Dict[str, _GroupFragment]
                        ) -> List[Tuple[str, _GroupFragment]]:
        """Validate + expand one container's requested BDFs to an ordered
        (group, fragment) list — the shared front half of plan() and
        allocate_response_bytes. Dedup with a set (membership was an
        O(n^2) list probe across a request's groups) while keeping the
        reference's spec ordering."""
        registry = self.registry
        seen_groups: set = set()
        ordered: List[Tuple[str, _GroupFragment]] = []
        for bdf in requested_bdfs:
            group = registry.bdf_to_group.get(bdf)
            if group is None:
                raise AllocationError(
                    f"requested device {bdf} is not a known TPU")
            if self.allowed_bdfs is not None and bdf not in self.allowed_bdfs:
                raise AllocationError(
                    f"requested device {bdf} is not managed by resource "
                    f"{self.resource_suffix!r}")
            if group in seen_groups:
                continue
            seen_groups.add(group)
            ordered.append((group, self._fragment(group, iommufd, frags)))
        return ordered

    def plan(
        self,
        requested_bdfs: Sequence[str],
        shared_devices: Optional[Sequence[SharedDevice]] = None,
        epoch: Optional[object] = None,
    ) -> AllocationPlan:
        """Build the DeviceSpec list + env map for one container request.

        DeviceSpec order matches the reference's: the shared /dev/vfio/vfio
        container node first, then one /dev/vfio/<group> per IOMMU group,
        then iommufd cdevs + /dev/iommu, then qualifying shared devices.

        The per-group expansion is fragment concatenation (_GroupFragment
        cache, keyed by the caller's `epoch` token — health flips publish
        a new epoch, so fragments are invalidated by construction) plus
        ONE batched live-revalidation pass over every member of every
        requested group — the TOCTOU guard is never cached. Steady state
        acquires ZERO registered locks (the lockdep read-path gate).
        """
        iommufd = self._iommufd()
        if shared_devices is None:
            shared_devices = self.shared_devices()
        frags = self._fragments_for(epoch)

        ordered = self._resolve_groups(requested_bdfs, iommufd, frags)
        # one batched pass for the whole request (multi-group requests no
        # longer interleave revalidation with response assembly), crossing
        # the privilege seam ONCE per plan — the per-attach crossing
        # budget the bench pins (docs/bench_broker_r13.json)
        self._broker.revalidate_batch(self, [
            (m, group) for group, frag in ordered
            for m in frag.member_bdfs])

        ordered_groups = [group for group, _ in ordered]
        specs: List[pb.DeviceSpec] = [self._vfio_spec]
        expanded: List[str] = []
        cdi_names: List[str] = []
        iommufd_specs: List[pb.DeviceSpec] = []
        for group, frag in ordered:
            expanded.extend(frag.member_bdfs)
            cdi_names.extend(frag.cdi_names)
            iommufd_specs.extend(frag.iommufd_specs)
            specs.append(self._group_specs[group])
        specs.extend(iommufd_specs)
        if iommufd and ordered_groups:
            specs.append(self._iommu_spec)

        # Shared devices ride along iff every member chip is in this
        # allocation (all-or-nothing, reference :159-184).
        allocated = set(expanded)
        for shared in shared_devices:
            if shared.member_bdfs and set(shared.member_bdfs) <= allocated:
                specs.append(pb.DeviceSpec(
                    host_path=shared.dev_path,
                    container_path=f"/dev/{shared.name}",
                    permissions="mrw",
                ))
                log.info("allocation includes shared device %s (members %s)",
                         shared.name, ",".join(shared.member_bdfs))

        envs = {self.env_key: ",".join(expanded)}
        log.info("allocate %s: groups=%s devices=%s iommufd=%s cdi=%s",
                 self.resource_suffix, ordered_groups, expanded, iommufd,
                 self.cdi_enabled)
        return AllocationPlan(device_specs=specs, envs=envs,
                              expanded_bdfs=expanded, cdi_names=cdi_names)

    def allocate_response(self, request: pb.AllocateRequest,
                          epoch: Optional[object] = None
                          ) -> pb.AllocateResponse:
        """Full Allocate handler body: one ContainerAllocateResponse per
        container request in the AllocateRequest. `epoch` keys the
        fragment cache (see plan)."""
        shared = self.shared_devices()
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            plan = self.plan(list(creq.devices_ids), shared, epoch=epoch)
            cresp = pb.ContainerAllocateResponse(
                envs=plan.envs, devices=plan.device_specs)
            if self.cdi_enabled:
                names = plan.cdi_names
                if names is None:
                    from .cdi import cdi_device_name
                    names = [cdi_device_name(self.cfg, bdf)
                             for bdf in plan.expanded_bdfs]
                cresp.cdi_devices.extend(
                    pb.CDIDevice(name=name) for name in names)
            resp.container_responses.append(cresp)
        return resp

    # ------------------------------------------------ byte plane (round 15)

    def allocate_response_bytes(self, request: pb.AllocateRequest,
                                epoch: Optional[object] = None) -> bytes:
        """Serialized AllocateResponse bytes for `request`, assembled from
        the epoch-keyed pre-serialized fragment records instead of
        building + serializing protos per call (parse-identical to
        allocate_response — tests/test_preserialized.py pins it).

        This is ALSO the coalesced multi-container fast path: one epoch
        token read, one iommufd probe, one shared-device scan, and ONE
        batched TOCTOU revalidation — one privilege crossing — for the
        WHOLE request, where the message path crossed the broker seam
        once per container. The TOCTOU guard itself stays live: every
        member of every requested group is revalidated per request,
        never cached. Steady state acquires zero registered locks and
        serializes nothing (the bytes-reused counters are the honest
        ledger; fragment builds at an epoch miss still serialize, once).
        """
        if not self._byte_records:
            raise RuntimeError(
                "allocate_response_bytes on a planner built with "
                "byte_records=False — this planner serves the message "
                "path only")
        iommufd = self._iommufd()
        shared_devices = self.shared_devices()
        frags = self._fragments_for(epoch)
        containers: List[List[Tuple[str, _GroupFragment]]] = []
        revalidate: List[Tuple[str, str]] = []
        reval_groups: set = set()
        for creq in request.container_requests:
            ordered = self._resolve_groups(list(creq.devices_ids), iommufd,
                                           frags)
            containers.append(ordered)
            for group, frag in ordered:
                if group not in reval_groups:
                    reval_groups.add(group)
                    revalidate.extend(
                        (m, group) for m in frag.member_bdfs)
        # ONE crossing for the whole (possibly multi-container) request:
        # the attach broker-crossing budget (<= 2 counted) now holds for
        # batched multi-container Allocates too
        self._broker.revalidate_batch(self, revalidate)
        out = []
        for ordered in containers:
            out.append(encode_delimited(
                _F_CONTAINER,
                self._container_bytes(ordered, iommufd, shared_devices)))
        return b"".join(out)

    def _container_bytes(self, ordered: List[Tuple[str, _GroupFragment]],
                         iommufd: bool,
                         shared_devices: Sequence[SharedDevice]) -> bytes:
        """One ContainerAllocateResponse payload: env entry (key record
        precomputed, value patched per request) + DeviceSpec records in
        the reference's order (vfio, groups, iommufd cdevs, /dev/iommu,
        shared riders) + CDI records."""
        expanded = [m for _, frag in ordered for m in frag.member_bdfs]
        env_payload = (self._env_key_rec
                       + encode_delimited(2, ",".join(expanded)
                                          .encode("ascii")))
        parts = [encode_delimited(_F_ENVS, env_payload), self._vfio_rec]
        for _, frag in ordered:
            parts.append(frag.group_rec)
        for _, frag in ordered:
            parts.append(frag.iommufd_recs)
        if iommufd and ordered:
            parts.append(self._iommu_rec)
        if shared_devices:
            # shared riders qualify rarely (every member chip allocated);
            # their specs are encoded per request — counted serializations
            allocated = set(expanded)
            for shared in shared_devices:
                if shared.member_bdfs and set(shared.member_bdfs) <= allocated:
                    parts.append(encode_delimited(
                        _F_DEVICES,
                        pb.DeviceSpec(
                            host_path=shared.dev_path,
                            container_path=f"/dev/{shared.name}",
                            permissions="mrw").SerializeToString()))
                    self.serializations.add()
                    log.info("allocation includes shared device %s "
                             "(members %s)", shared.name,
                             ",".join(shared.member_bdfs))
        if self.cdi_enabled:
            for _, frag in ordered:
                parts.append(frag.cdi_recs)
        log.info("allocate %s: groups=%s devices=%s iommufd=%s cdi=%s "
                 "(byte path)", self.resource_suffix,
                 [g for g, _ in ordered], expanded, iommufd,
                 self.cdi_enabled)
        return b"".join(parts)


def plan_allocation(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    requested_bdfs: Sequence[str],
    shared_devices: Optional[Sequence[SharedDevice]] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> AllocationPlan:
    """One-shot form of AllocationPlanner.plan (tests, ad-hoc callers).

    Long-lived callers (the plugin servers) hold an AllocationPlanner so the
    per-(cfg, registry) precomputation is paid once, not per RPC.
    """
    planner = AllocationPlanner(cfg, registry, resource_suffix,
                                allowed_bdfs=allowed_bdfs,
                                byte_records=False)
    if shared_devices is None:
        shared_devices = discover_shared_devices(cfg)
    return planner.plan(requested_bdfs, shared_devices)


def allocate_response(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    request: pb.AllocateRequest,
    cdi_enabled: Optional[bool] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> pb.AllocateResponse:
    """One-shot form of AllocationPlanner.allocate_response.

    `cdi_enabled=None` falls back to `bool(cfg.cdi_spec_dir)`; the plugin
    server passes an explicit value reflecting whether this resource's CDI
    spec file was actually written (unresolvable names are worse than none).
    """
    planner = AllocationPlanner(cfg, registry, resource_suffix,
                                allowed_bdfs=allowed_bdfs,
                                cdi_enabled=cdi_enabled,
                                byte_records=False)
    return planner.allocate_response(request)
