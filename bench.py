#!/usr/bin/env python3
"""Benchmark: the plugin's VMI-attach control-plane critical path.

BASELINE.md config 1 defines the measurable baseline ("1 vfio-pci stub
device → 1 VMI: Allocate() RPC latency; devices advertised; plugin on CPU").
This bench builds a fake 8-chip v5e host, serves a real plugin over a real
unix-socket gRPC server, and measures the kubelet-visible critical path for
a 4-chip ICI-adjacent allocation: GetPreferredAllocation + Allocate RPC
round-trips. The reference publishes no numbers (SURVEY.md §6), so the
baseline is this protocol's own recorded round-1 p50 (BENCH_r01.json):
vs_baseline = round1_p50 / current_p50, >1.0 meaning faster than round 1.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time
from concurrent import futures

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import grpc

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover, discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import TpuDevicePlugin
from tpu_device_plugin.vtpu import VtpuDevicePlugin

ITERATIONS = 300
WARMUP = 20


def main() -> int:
    import logging
    logging.disable(logging.CRITICAL)  # keep the one-line contract

    root = tempfile.mkdtemp(prefix="tdpbench-")
    try:
        host = FakeHost(root)
        # 8-chip v5e host (2x4 ICI torus), one chip per IOMMU group
        for i in range(8):
            host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                                   iommu_group=str(11 + i), numa_node=i // 4))
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)

        t0 = time.perf_counter()
        registry, generations = discover_passthrough(cfg)
        discovery_ms = (time.perf_counter() - t0) * 1e3
        devices = registry.devices_by_model["0063"]

        plugin = TpuDevicePlugin(cfg, "v5e", registry, devices,
                                 torus_dims=generations["0063"].host_topology)
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        api.add_device_plugin_servicer(server, plugin)
        server.add_insecure_port(f"unix://{plugin.socket_path}")
        server.start()

        all_ids = [d.bdf for d in devices]
        attach_us = []
        pref_us = []
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            for i in range(ITERATIONS + WARMUP):
                t1 = time.perf_counter()
                pref = stub.GetPreferredAllocation(
                    pb.PreferredAllocationRequest(container_requests=[
                        pb.ContainerPreferredAllocationRequest(
                            available_deviceIDs=all_ids, allocation_size=4)]),
                    timeout=5)
                t2 = time.perf_counter()
                picked = list(pref.container_responses[0].deviceIDs)
                resp = stub.Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(devices_ids=picked)]),
                    timeout=5)
                t3 = time.perf_counter()
                assert len(resp.container_responses[0].devices) >= 5  # vfio + 4 groups
                if i >= WARMUP:
                    pref_us.append((t2 - t1) * 1e6)
                    attach_us.append((t3 - t1) * 1e6)
        server.stop(0)

        # secondary: vTPU partition Allocate p50 (mdev path with live sysfs
        # revalidation) on the same host
        host.add_mdev("bench-uuid-0", "TPU vhalf", "0000:00:04.0",
                      iommu_group="31")
        host.add_mdev("bench-uuid-1", "TPU vhalf", "0000:00:04.0",
                      iommu_group="32")
        vregistry, _ = discover(cfg)
        vplugin = VtpuDevicePlugin(cfg, "TPU_vhalf", vregistry,
                                   vregistry.partitions_by_type["TPU_vhalf"])
        vserver = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        api.add_device_plugin_servicer(vserver, vplugin)
        vserver.add_insecure_port(f"unix://{vplugin.socket_path}")
        vserver.start()
        vtpu_us = []
        with grpc.insecure_channel(f"unix://{vplugin.socket_path}") as ch:
            vstub = api.DevicePluginStub(ch)
            for i in range(ITERATIONS // 3 + WARMUP):
                t1 = time.perf_counter()
                vresp = vstub.Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(
                            devices_ids=["bench-uuid-0", "bench-uuid-1"])]),
                    timeout=5)
                # the measured path must be the per-group mount (vfio cdev +
                # groups 31, 32), never the wide /dev/vfio fallback
                assert len(vresp.container_responses[0].devices) == 3
                if i >= WARMUP:
                    vtpu_us.append((time.perf_counter() - t1) * 1e6)
        vserver.stop(0)

        p50 = statistics.median(attach_us)
        # The reference publishes no numbers (SURVEY §6); the recorded
        # round-1 p50 of this same protocol is the baseline, so >1.0 means
        # faster than round 1.
        round1_p50_us = 820.3
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "BENCH_r01.json")) as f:
                round1_p50_us = float(json.load(f)["parsed"]["value"])
        except (OSError, KeyError, ValueError, TypeError):
            pass  # keep the recorded constant if the file is gone/reshaped
        result = {
            "metric": "vmi_attach_control_plane_p50",
            "value": round(p50, 1),
            "unit": "us",
            "vs_baseline": round(round1_p50_us / p50, 3),
            "preferred_allocation_p50_us": round(statistics.median(pref_us), 1),
            "allocate_p50_us": round(p50 - statistics.median(pref_us), 1),
            "p99_us": round(statistics.quantiles(attach_us, n=100)[98], 1),
            "vtpu_allocate_p50_us": round(statistics.median(vtpu_us), 1),
            "discovery_ms": round(discovery_ms, 2),
            "devices_advertised": len(devices),
            "allocation_size": 4,
            "iterations": ITERATIONS,
        }
        print(json.dumps(result))
        return 0
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
