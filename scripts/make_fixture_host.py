#!/usr/bin/env python3
"""Materialize a fake TPU host tree for the image smoke test.

Usage: python scripts/make_fixture_host.py <root>

Builds the same sysfs/devfs shape the unit suites use (tests/fakehost.py,
modeled on the reference's tmpdir fixtures,
pkg/device_plugin/device_plugin_test.go:279-323): four passthrough chips
across two IOMMU groups with accel nodes, one mdev partition, one
EGM-analogue shared device, and the iommufd cdev. CI mounts the tree at
/fixture (read-only) and asserts that `--root /fixture --discover-only`
inventories it from inside the distroless image as the nonroot user.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, "tests"))
from fakehost import FakeChip, FakeHost  # noqa: E402


def build(root: str) -> None:
    host = FakeHost(root)
    chips = [
        ("0000:01:00.0", "7"),
        ("0000:01:01.0", "7"),   # same group as .0 — exercises group expansion
        ("0000:02:00.0", "8"),
        ("0000:02:01.0", "9"),
    ]
    for i, (bdf, group) in enumerate(chips):
        # every vfio-bound device on an iommufd host has a cdev; without
        # one the plugin (correctly) fails the Allocate, which the local
        # KubeVirt contract run flushed out (scripts/e2e_kubevirt_local.py)
        host.add_chip(FakeChip(bdf=bdf, iommu_group=group, accel_index=i,
                               numa_node=i // 2, vfio_dev=f"vfio{i}"))
    host.add_mdev("a1b2c3d4-0000-1111-2222-333344445555", "tpu-v4-1c",
                  "0000:02:00.0", iommu_group="12")
    host.add_shared_device("egm0", ["0000:01:00.0", "0000:01:01.0"])
    host.enable_iommufd()
    # world-readable so the image's nonroot uid (65532) can walk it
    for dirpath, dirnames, filenames in os.walk(root):
        os.chmod(dirpath, 0o755)
        for f in filenames:
            p = os.path.join(dirpath, f)
            if not os.path.islink(p):
                os.chmod(p, 0o644)


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    build(sys.argv[1])
    print(sys.argv[1])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
