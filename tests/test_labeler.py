"""Node topology labeler: facts, feature file, API PATCH, manager wiring."""

import json
import os
import threading
from dataclasses import replace
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover
from tpu_device_plugin.labeler import NodeLabeler, node_facts, write_feature_file
from tpu_device_plugin.lifecycle import PluginManager


@pytest.fixture
def inventory(tmp_path):
    host = FakeHost(tmp_path)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0064",
                               iommu_group=str(11 + i)))
    host.add_mdev("uuid-1", "TPU vhalf", "0000:00:04.0", iommu_group="31")
    cfg = Config().with_root(host.root)
    registry, generations = discover(cfg)
    return cfg, registry, generations


def test_node_facts(inventory):
    cfg, registry, generations = inventory
    facts = node_facts(cfg, registry, generations)
    assert facts == {
        "cloud-tpus.google.com/v5p.chips": "4",
        "cloud-tpus.google.com/v5p.torus": "2x2x1",
        "cloud-tpus.google.com/vtpu.TPU_vhalf": "1",
    }


def test_feature_file_roundtrip(inventory, tmp_path):
    cfg, registry, generations = inventory
    facts = node_facts(cfg, registry, generations)
    path = tmp_path / "features.d" / "tpu"
    assert write_feature_file(str(path), facts)
    lines = path.read_text().splitlines()
    assert lines == [f"{k}={facts[k]}" for k in sorted(facts)]


def test_feature_file_failure_tolerated(tmp_path):
    blocked = tmp_path / "f"
    blocked.write_text("")  # file where a directory is needed
    assert not write_feature_file(str(blocked / "x" / "tpu"), {"a": "1"})


class _FakeApiServer:
    """Captures PATCH /api/v1/nodes/<name>; serves GET with `node_labels`."""

    def __init__(self, node_labels=None):
        self.patches = []
        self.node_labels = dict(node_labels or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = json.dumps(
                    {"metadata": {"labels": outer.node_labels}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PATCH(self):
                length = int(self.headers.get("Content-Length", 0))
                outer.patches.append({
                    "path": self.path,
                    "content_type": self.headers.get("Content-Type"),
                    "auth": self.headers.get("Authorization"),
                    "body": json.loads(self.rfile.read(length)),
                })
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        self._httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def test_patch_node_labels(inventory, tmp_path):
    cfg, registry, generations = inventory
    api = _FakeApiServer()
    token = tmp_path / "token"
    token.write_text("sekret\n")
    try:
        labeler = NodeLabeler(node_name="node-a", api_server=api.url,
                              token_path=str(token))
        assert labeler.publish(node_facts(cfg, registry, generations))
        assert len(api.patches) == 1
        patch = api.patches[0]
        assert patch["path"] == "/api/v1/nodes/node-a"
        assert patch["content_type"] == "application/strategic-merge-patch+json"
        assert patch["auth"] == "Bearer sekret"
        labels = patch["body"]["metadata"]["labels"]
        assert labels["cloud-tpus.google.com/v5p.chips"] == "4"
    finally:
        api.stop()


def test_patch_failure_returns_false(inventory):
    cfg, registry, generations = inventory
    labeler = NodeLabeler(node_name="node-a",
                          api_server="http://127.0.0.1:1")  # nothing listens
    assert not labeler.publish(node_facts(cfg, registry, generations))


def test_manager_publishes_on_inventory(short_root, tmp_path):
    """The manager invokes the labeler seam on every (re)discovery, and a
    failing callback never sinks plugin startup."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    seen = []

    def on_inventory(registry, generations):
        seen.append(node_facts(cfg, registry, generations))
        raise RuntimeError("callback blew up")  # must be tolerated

    manager = PluginManager(cfg, on_inventory=on_inventory)
    manager.start()
    try:
        assert kubelet.wait_for(1)
        assert seen and seen[0]["cloud-tpus.google.com/v4.chips"] == "1"
    finally:
        manager.stop()
        kubelet.stop()


def test_stale_labels_nulled_on_republish(inventory):
    """Facts for disappeared inventory — including labels left by a previous
    pod incarnation (discovered via GET) — must be deleted with null values
    in the strategic-merge PATCH."""
    cfg, registry, generations = inventory
    api = _FakeApiServer(node_labels={
        "cloud-tpus.google.com/ghost.chips": "2",   # previous incarnation
        "kubernetes.io/hostname": "node-a",          # foreign: untouched
    })
    try:
        labeler = NodeLabeler(node_name="node-a", api_server=api.url)
        facts = node_facts(cfg, registry, generations)
        assert labeler.publish(facts)
        labels = api.patches[0]["body"]["metadata"]["labels"]
        assert labels["cloud-tpus.google.com/ghost.chips"] is None
        assert "kubernetes.io/hostname" not in labels
        # partitions vanish -> their key nulled on the next publish
        facts2 = {k: v for k, v in facts.items() if "vtpu" not in k}
        assert labeler.publish(facts2)
        labels2 = api.patches[1]["body"]["metadata"]["labels"]
        assert labels2["cloud-tpus.google.com/vtpu.TPU_vhalf"] is None
    finally:
        api.stop()


def test_require_api_warns_and_fails_without_node_name(inventory, tmp_path, caplog):
    """--label-node without NODE_NAME must not be silently swallowed just
    because a feature file is also configured."""
    import logging
    cfg, registry, generations = inventory
    labeler = NodeLabeler(node_name=None, api_server=None,
                          feature_file=str(tmp_path / "tpu"),
                          require_api=True)
    labeler.node_name = None  # defeat any NODE_NAME in the environment
    with caplog.at_level(logging.WARNING):
        assert labeler.publish(node_facts(cfg, registry, generations)) is False
    assert any("NOT published" in r.message for r in caplog.records)
    assert (tmp_path / "tpu").exists()  # feature path still written


def test_manager_retries_failed_publish(short_root):
    """A publish that fails at boot (API server down) is retried from the
    run loop even though inventory never changes."""
    import threading
    import time as time_mod
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    calls = []

    def on_inventory(registry, generations):
        calls.append(time_mod.monotonic())
        return len(calls) >= 2  # first attempt "fails"

    manager = PluginManager(cfg, on_inventory=on_inventory)
    manager._next_publish_retry = 0.0
    stop = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop,), daemon=True)
    t.start()
    try:
        assert kubelet.wait_for(1)
        manager._next_publish_retry = 0.0  # don't wait 30s in the test
        deadline = time_mod.monotonic() + 10
        while len(calls) < 2 and time_mod.monotonic() < deadline:
            manager._next_publish_retry = 0.0
            time_mod.sleep(0.1)
        assert len(calls) >= 2, "failed publish was never retried"
        assert manager._inventory_published
    finally:
        stop.set()
        t.join(timeout=10)
        kubelet.stop()


def test_feature_file_only_never_touches_ambient_api(inventory, tmp_path,
                                                     monkeypatch):
    """Feature-file-only mode with ambient in-cluster env + NODE_NAME must
    NOT attempt API PATCHes (no RBAC there; each would 403 and fail the
    publish forever)."""
    cfg, registry, generations = inventory
    monkeypatch.setenv("NODE_NAME", "node-a")
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    labeler = NodeLabeler(feature_file=str(tmp_path / "tpu"))
    assert labeler.api_server  # ambient env present...
    assert labeler.publish(node_facts(cfg, registry, generations))  # ...unused
    assert (tmp_path / "tpu").exists()
