"""Mosaic-compile gate: the Pallas kernels on the REAL TPU backend.

The rest of the suite runs the flash/ring kernels in `interpret=True` or on
the CPU mesh, which does not exercise Mosaic lowering constraints (tiling,
scratch layouts, VMEM limits). This gate AOT-lowers + compiles + runs:

  - flash_attention forward at blocks 128x128 and 256x128
  - flash_attention forward+backward (custom-VJP Pallas bwd kernels)
  - one ring_attention step under shard_map on a TPU mesh
  - one ring_flash_attention step (Pallas kernels behind lax.switch)
    forward+backward under shard_map

It skips cleanly off-TPU (the conftest pins CPU unless TDP_TPU_TESTS=1), so
plain CI never touches hardware; in a healthy-chip window it runs in minutes:

    TDP_TPU_TESTS=1 python -m pytest tests/test_tpu_gate.py -v

Reference analogue: the NVML-verified health path is the reference's only
hardware-touching claim (generic_vgpu_device_plugin.go:387-433); here the
hardware-touching claims are the Mosaic kernels, so this is their gate.
"""

import functools

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_device_plugin.validator.flash_attention import flash_attention  # noqa: E402
from tpu_device_plugin.validator.ring_attention import (  # noqa: E402
    ring_attention, ring_flash_attention)


def _tpu_devices():
    try:
        return [d for d in jax.devices() if d.platform == "tpu"]
    except Exception:
        return []


requires_tpu = pytest.mark.skipif(
    not _tpu_devices(),
    reason="no TPU backend (run with TDP_TPU_TESTS=1 on a TPU host)")

HB, SEQ, D = 4, 512, 128


def _qkv(seed=0, dtype=jnp.bfloat16):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((HB, SEQ, D), dtype=np.float32), dtype)
    return mk(), mk(), mk()


def _reference(q, k, v):
    """Plain einsum causal attention in f32 (the oracle)."""
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * (D ** -0.5)
    mask = jnp.tril(jnp.ones((SEQ, SEQ), jnp.bool_))[None]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, vf)


@requires_tpu
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (256, 128)])
def test_flash_forward_mosaic_compiles_and_matches(block_q, block_k):
    q, k, v = _qkv()
    fn = jax.jit(functools.partial(
        flash_attention, causal=True, block_q=block_q, block_k=block_k))
    compiled = fn.lower(q, k, v).compile()   # Mosaic lowering happens here
    out = np.asarray(compiled(q, k, v), np.float32)
    ref = np.asarray(_reference(q, k, v))
    np.testing.assert_allclose(out, ref, atol=3e-2, rtol=3e-2)


@requires_tpu
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (256, 128)])
def test_flash_backward_mosaic_compiles_and_matches(block_q, block_k):
    q, k, v = _qkv(seed=1)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=block_q,
                               block_k=block_k).astype(jnp.float32).sum()

    grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    compiled = grad_fn.lower(q, k, v).compile()  # bwd dkv + dq kernels
    dq, dk, dv = compiled(q, k, v)

    def ref_loss(q, k, v):
        return _reference(q, k, v).sum()

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        got = np.asarray(got, np.float32)
        want = np.asarray(want, np.float32)
        assert np.isfinite(got).all()
        # bf16 grads over 512-long softmax rows: loose but real agreement
        np.testing.assert_allclose(got, want, atol=1e-1, rtol=1e-1)


@requires_tpu
def test_ring_attention_step_compiles_on_tpu_mesh():
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = _tpu_devices()
    mesh = Mesh(np.array(devs[:1]), ("sp",))
    q, k, v = _qkv(seed=2)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(None, "sp", None),) * 3,
                       out_specs=P(None, "sp", None))
    def step(q, k, v):
        return ring_attention(q, k, v, D ** -0.5, axis_name="sp")

    fn = jax.jit(step)
    compiled = fn.lower(q, k, v).compile()
    out = np.asarray(compiled(q, k, v), np.float32)
    ref = np.asarray(_reference(q, k, v))
    np.testing.assert_allclose(out, ref, atol=3e-2, rtol=3e-2)


@requires_tpu
def test_ring_flash_step_compiles_on_tpu_mesh():
    """ring_flash (Pallas kernel per ring step behind lax.switch) must
    Mosaic-compile fwd+bwd and match the oracle — the switch puts three
    compiled kernel variants in one program, which only hardware lowering
    can validate."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = _tpu_devices()
    mesh = Mesh(np.array(devs[:1]), ("sp",))
    q, k, v = _qkv(seed=3)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(None, "sp", None),) * 3,
                       out_specs=P(None, "sp", None),
                       # pallas out_shape carries no varying-mesh-axes
                       # metadata (same reason as workload.py's shard_maps)
                       check_vma=False)
    def step(q, k, v):
        return ring_flash_attention(q, k, v, D ** -0.5, "sp", 128, 128)

    fn = jax.jit(step)
    compiled = fn.lower(q, k, v).compile()
    out = np.asarray(compiled(q, k, v), np.float32)
    ref = np.asarray(_reference(q, k, v))
    np.testing.assert_allclose(out, ref, atol=3e-2, rtol=3e-2)

    def loss(q, k, v):
        return step(q, k, v).astype(jnp.float32).sum()

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
        q, k, v).compile()(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g, np.float32)).all()
