"""ISSUE 14 drive: real daemon with --host-coords — the published
ResourceSlice carries the ICI topology attributes, fleetplace parses it
back into a placement grid, a compiled selector matches every chip, and
/debug/defrag serves the per-generation fragmentation records alongside
the proposal (400 on a generation with no host view / overflow shape).
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

from fakehost import FakeChip, FakeHost  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402

root = tempfile.mkdtemp(prefix="vfyfp-", dir="/tmp")
fh = FakeHost(root)
for i in range(8):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i), numa_node=i // 4,
                         serial=f"sn-{i}"))
os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
api = FakeApiServer()
port = 18271
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-fp")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--host-coords", "1,2", "-v"],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

try:
    slice_obj = None
    for _ in range(100):
        slices = dict(api.slices)
        if slices:
            slice_obj = json.loads(json.dumps(next(iter(slices.values()))))
            if slice_obj.get("spec", {}).get("devices"):
                break
        time.sleep(0.2)
    assert slice_obj is not None, "daemon never published a ResourceSlice"

    from tpu_device_plugin.fleetplace import (
        compile_selector, device_attrs, host_views_from_slices)

    entries = slice_obj["spec"]["devices"]
    assert len(entries) == 8
    for entry in entries:
        attrs = device_attrs(entry)
        assert attrs["generation"] == "v5e", attrs
        assert (attrs["torusX"], attrs["torusY"]) == (2, 4), attrs
        assert attrs["ringSize"] == 4, attrs
        assert attrs["hostId"] == "node-fp", attrs
        assert attrs["ringId"].startswith("node-fp/v5e/"), attrs
        assert (attrs["hostX"], attrs["hostY"]) == (1, 2), attrs
    print("OK: published slice carries ICI topology attributes "
          "(coords, torus dims, ringSize/ringId, hostId, pod slot 1,2)")

    views, idx = host_views_from_slices(
        {slice_obj["metadata"]["name"]: slice_obj}, {})
    view = views["v5e"][0]
    assert view.dims == (2, 4) and len(view.free) == 8
    assert view.host_coords == (1, 2)
    print("OK: fleetplace rebuilt the placement grid from the "
          "published slice (2x4 torus, pod slot (1,2), 8 free)")

    sel = compile_selector('topology.generation == "v5e" && '
                           'topology.ring_size >= 4 && '
                           'topology.host_id == "node-fp"')
    matched = sum(sel.matches(device_attrs(e)) for e in entries)
    assert matched == 8, sel.snapshot()
    assert compile_selector('topology.generation == "v4"').matches(
        device_attrs(entries[0])) is False
    print("OK: compiled selector matches all 8 published chips "
          "(and a v4 selector matches none)")

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/defrag?shape=2x2",
            timeout=5) as r:
        prop = json.load(r)
    assert prop["placeable"] is True
    frag = prop["fragmentation"]["v5e"]
    assert frag["free"] == 8 and frag["fragmentation"] == 0.0, frag
    print("OK: /debug/defrag carries the per-generation fragmentation "
          "records alongside the proposal")

    for bad in ("shape=2x2&generation=nope", "shape=4294967296x2"):
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/defrag?{bad}", timeout=5)
        except urllib.error.HTTPError as exc:
            assert exc.code == 400, (bad, exc.code)
        else:
            raise AssertionError(f"{bad} did not 400")
    print("OK: unknown generation + overflow shape answer 400")
    print("FLEETPLACE DRIVE PASS")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=5)
    except Exception:
        proc.kill()
    api.stop()
