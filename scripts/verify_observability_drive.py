"""End-to-end drive of the observability plane (PR 8).

Real daemon (cli.main subprocess) with --dra + status server against a
fake host; driven as the kubelet would, then inspected the way a fleet
operator would during an incident:
  1. prepare a DRA claim over dra.sock, hot-unplug the chip
  2. GET /debug/flight?claim=<uid> -> the claim's full story (prepare
     span + checkpoint flush + apiserver RTT + orphan event), time-ordered
  3. GET /debug/flight?bdf=<bdf> -> the device's lifecycle transitions
  4. /metrics carries the trace histogram families (strict families)
  5. the fleet trace plane (r17): the claim's trace id from its flight
     records resolves on /debug/fleet/trace?trace= as a node-labeled
     waterfall, and /debug/flight?since_ms= pages the ring as a
     bounded drain
  6. the SLO plane (r17): an injected latency fault ($TDP_FAULTS
     kubeapi.request:delay) moves the publish_rtt burn-rate gauge on
     /status, latches a breach, and the exemplar trace id attached to
     the burning objective resolves on /debug/fleet/trace
  7. SIGHUP -> flight-recorder dump file written, carrying histogram
     snapshots + SLO/burn state alongside the merged ring
  8. stderr is structured key=value and carries span context (claim_uid)
Prints OBSERVABILITY DRIVE PASS on success.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import grpc  # noqa: E402
from fakehost import FakeChip, FakeHost  # noqa: E402
from kubelet_sim import DeviceManagerSim  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402
from tpu_device_plugin.kubeletapi import draapi, drapb  # noqa: E402

root = tempfile.mkdtemp(prefix="vfyobs-", dir="/tmp")
fh = FakeHost(root)
for i in range(4):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i), numa_node=i // 2,
                         serial=f"sn-{i}"))
victim_bdf = "0000:00:04.0"
victim_sysfs = os.path.join(root, "sys/bus/pci/devices", victim_bdf)
victim_vfio = os.path.join(root, "dev/vfio/10")
dump_path = os.path.join(root, "flight-dump.json")
stderr_path = os.path.join(root, "daemon.stderr")

os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
sim = DeviceManagerSim(os.path.join(root, "device-plugins"))
api = FakeApiServer()
port = 18171
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-a", TDP_TRACE_DUMP_PATH=dump_path,
           # latency injection (r17): every apiserver round-trip pays
           # +300 ms — the attach path's claim GET lands as a bad
           # publish_rtt sample, so the SLO burn-rate gauge must move
           # and latch a breach with a resolvable exemplar
           TDP_FAULTS="kubeapi.request:delay:delay=0.3")
stderr_f = open(stderr_path, "w")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--health-poll-seconds", "0.3", "--rediscovery-seconds", "0.5"],
    env=env, stdout=subprocess.DEVNULL, stderr=stderr_f)


def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=2) as r:
        body = r.read()
    return json.loads(body) if path != "/metrics" else body.decode()


def wait_for(pred, what, timeout=30):
    dl = time.time() + timeout
    while time.time() < dl:
        try:
            if pred():
                print(f"OK: {what}")
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise SystemExit(f"FAIL: timeout waiting for {what}")


try:
    wait_for(lambda: get("/status"), "daemon up")
    wait_for(lambda: api.slices, "ResourceSlice published")

    # 1. prepare a claim, then hot-unplug the chip
    api.add_claim("ns", "vm1", "uid-vm1", "cloud-tpus.google.com",
                  [{"device": "d0000-00-04-0"}], generation=5)
    dra_sock = os.path.join(root, "plugins/cloud-tpus.google.com/dra.sock")
    with grpc.insecure_channel(f"unix://{dra_sock}") as ch:
        resp = draapi.DraPluginStub(ch).NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns", name="vm1", uid="uid-vm1")]),
            timeout=10)
    assert resp.claims["uid-vm1"].error == "", resp.claims["uid-vm1"].error
    print("OK: DRA claim prepared over dra.sock")
    shutil.move(victim_sysfs, os.path.join(root, "victim-backup"))
    os.unlink(victim_vfio)
    wait_for(lambda: get("/status")["dra"]["orphaned_claims"] == ["uid-vm1"],
             "claim orphaned on /status")

    # 2. the claim's story from /debug/flight?claim=
    flight = get("/debug/flight?claim=uid-vm1")
    ops = [r["op"] for r in flight["spans"]]
    for needed in ("dra.prepare.claim", "dra.checkpoint.flush",
                   "kubeapi.request", "lifecycle.claim.orphaned"):
        assert needed in ops, (needed, ops)
    ts = [r["ts"] for r in flight["spans"]]
    assert ts == sorted(ts), "flight output not time-ordered"
    assert ops.index("dra.prepare.claim") < ops.index(
        "lifecycle.claim.orphaned")
    assert all(r["attrs"].get("claim_uid") == "uid-vm1"
               for r in flight["spans"])
    print("OK: /debug/flight?claim= replays prepare -> orphan story "
          f"({len(ops)} records)")

    # 3. the device's story from /debug/flight?bdf=
    dev = get(f"/debug/flight?bdf={victim_bdf}")
    transitions = [(r["attrs"].get("from"), r["attrs"].get("to"))
                   for r in dev["spans"] if r["op"] == "lifecycle.transition"]
    assert ("bound", "allocated") in transitions, transitions
    assert ("allocated", "gone") in transitions, transitions
    print("OK: /debug/flight?bdf= shows the lifecycle transitions "
          f"({transitions})")

    # 4. trace histograms on /metrics
    m = get("/metrics")
    for fam in ("tdp_prepare_wall_ms", "tdp_kubeapi_rtt_ms",
                "tdp_checkpoint_commit_ms", "tdp_probe_cycle_ms"):
        assert f"# TYPE {fam} histogram" in m, fam
        assert f'{fam}_bucket{{le="+Inf"}}' in m, fam
    assert "tdp_trace_spans_total" in m
    print("OK: /metrics carries the trace histogram families")

    # 5. fleet trace plane: the claim's trace id resolves on
    # /debug/fleet/trace?trace= as a node-labeled waterfall
    prep = [r for r in flight["spans"] if r["op"] == "dra.prepare.claim"]
    assert prep and prep[-1].get("trace_id"), "prepare span has no trace id"
    tid = prep[-1]["trace_id"]
    waterfall = get(f"/debug/fleet/trace?trace={tid}")
    assert waterfall["trace"] == tid
    wf_ops = set(waterfall["ops"])
    assert "dra.prepare.claim" in wf_ops, wf_ops
    assert "kubeapi.request" in wf_ops, wf_ops
    assert all(r.get("node") for r in waterfall["spans"])
    print(f"OK: /debug/fleet/trace?trace= replays the claim waterfall "
          f"({len(waterfall['spans'])} spans, nodes={waterfall['nodes']})")
    # ... and /debug/flight?since_ms= pages the ring as a bounded drain
    page = get("/debug/flight?since_ms=0&limit=5")
    # >= : a page legitimately extends through an equal-timestamp run
    assert len(page["spans"]) >= 5 and page["more"] is True
    page2 = get(f"/debug/flight?since_ms={page['next_since_ms']}&limit=5")
    assert page2["spans"], "second drain page empty"
    assert page2["spans"][0]["ts"] * 1e3 > page["next_since_ms"] - 1e-6
    print("OK: /debug/flight?since_ms= drains the ring in bounded pages")

    # 6. SLO plane: the injected kubeapi latency moved the publish_rtt
    # burn-rate gauge, latched a breach, and its exemplar resolves
    def slo_burning():
        rec = get("/status")["slo"]["objectives"]["publish_rtt"]
        return rec["burn_rate_fast"] > 0 and rec["bad_total"] > 0
    wait_for(slo_burning, "publish_rtt burn rate moved under the "
             "injected latency fault")
    slo = get("/status")["slo"]
    rec = slo["objectives"]["publish_rtt"]
    assert slo["breaches_total"] >= 1, slo
    assert rec["exemplar"] and rec["exemplar"]["trace_id"], rec
    ex_tid = rec["exemplar"]["trace_id"]
    ex_wf = get(f"/debug/fleet/trace?trace={ex_tid}")
    assert ex_wf["spans"], "exemplar trace id did not resolve"
    m = get("/metrics")
    assert 'tpu_plugin_slo_burn_rate{slo="publish_rtt",window="fast"}' in m
    assert f'trace_id="{ex_tid}"' in m, "exemplar info series missing"
    print(f"OK: SLO breach under injected latency (burn_fast="
          f"{rec['burn_rate_fast']}), exemplar {ex_tid[:8]}... resolves "
          f"to {len(ex_wf['spans'])} spans")

    # 7. SIGHUP dumps the ring (dedicated dump signal; SIGUSR2 stays
    # undrain) — with histogram + SLO context for the post-mortem
    proc.send_signal(signal.SIGHUP)
    wait_for(lambda: os.path.exists(dump_path), "SIGHUP flight dump")
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["reason"] == "SIGHUP"
    assert any(r["op"] == "dra.prepare.claim" for r in dump["spans"])
    assert "tdp_kubeapi_rtt_ms" in dump["histograms"]
    assert dump["slo"]["objectives"]["publish_rtt"]["bad_total"] > 0
    print(f"OK: dump carries {len(dump['spans'])} spans + histogram "
          f"snapshots + SLO state")

    # 8. structured key=value logs with span context
    stderr_f.flush()
    with open(stderr_path) as f:
        logs = f.read()
    assert "claim_uid=uid-vm1" in logs, "span context missing from logs"
    print("OK: stderr logs are key=value and carry claim_uid from the "
          "active span")
    print("OBSERVABILITY DRIVE PASS")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    stderr_f.close()
    api.stop()
    sim.stop()
    shutil.rmtree(root, ignore_errors=True)
