"""remediation — SLO-closed-loop self-healing (ISSUE 16).

The PR 15 SLO plane *detects*: multi-window burn rates latch a breach,
carry an exemplar trace, and page an operator. This module *acts* — it
closes the loop from latched breach to corrective knob to audited
rollback, driving only machinery the fleet already has:

  - a burning attach/prepare/publish SLO backs the publish pacer off
    (``PublishPacer.set_backoff_floor`` — the AIMD window stops
    collapsing while the plane sheds) and throttles claim admission
    through a token bucket (``admit()`` — the DRA prepare and the
    device-plugin Allocate seats consult it; every shed is COUNTED and
    TYPED, never a silent drop);
  - a fragmentation-driven unplaceable burst triggers a targeted defrag
    wave through the scheduler's existing handoff path
    (``FleetScheduler.plan_defrag_wave``/``apply_defrag_wave``);
  - a host whose exemplar traces keep surfacing (exemplar → node
    attribution via ``FleetFlight.trace``) is placement-biased away
    (``FleetScheduler.bias_away``) and drained through the PR 7
    orphan/handoff migration path (``plan_drain`` feeding the same
    ``apply_defrag_wave``).

Every action is an OPERATOR DECISION first: the policy engine's
``remediate`` hook (policy.py — per-hook deadline + circuit breaker,
first-non-None-wins) may veto or retune any action; vetoes are counted
and audited, never silently dropped. Every applied action/rollback
opens a span **linked to the breach's exemplar trace** — a linked root
adopts the remote trace id (trace.py), so ONE
``/debug/fleet/trace?trace=<exemplar>`` query reconstructs the whole
chain: slow request → breach → remediation action → recovery →
rollback.

Hysteresis — the engine must never flap or storm:

  - per-(action, target) cool-down windows (``cooldown_s``);
  - a global actions-per-window budget (``max_actions_per_window`` over
    ``action_window_s``);
  - knobs roll back ONLY on the SLO engine's latched ``recovered``
    transition, which itself latches only after the SLOW window's burn
    drops below target (slo.py) — a fast-window dip mid-incident
    neither unlatches nor rolls anything back.

Wiring and concurrency: the engine SUBSCRIBES to the SLO engine
(``SLOEngine.subscribe``). Subscriber callbacks fire on whatever thread
drove ``evaluate()`` — usually the /status scrape, which runs inside a
zero-registered-locks read-path bracket — so ``on_transition`` only
QUEUES under the engine's plain unregistered lock and touches no
registered lock. All corrective work happens in ``tick()``, driven by
the background thread (``start()``), the autopilot soak, or tests —
never by the scrape itself. ``admit()`` is on the prepare path: its
no-throttle fast path is one attribute read; with a throttle active it
takes only the engine's plain lock. tsalint COUNTERS owns
``counters[*]`` under ``remediation.RemediationEngine._lock``.

Surfaces: ``/status`` ``remediation`` section + the
``tpu_plugin_remediation_*`` families on ``/metrics``
(status.StatusServer), the audited action log on
``/debug/remediation``, and the flight-recorder spans/events above
(docs/observability.md "Remediation").
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from . import trace

log = logging.getLogger(__name__)

__all__ = ["TokenBucket", "RemediationEngine"]

# knob defaults — every one operator-tunable at construction
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_ACTION_WINDOW_S = 300.0
DEFAULT_MAX_ACTIONS_PER_WINDOW = 8
DEFAULT_PACER_FLOOR_S = 0.25
DEFAULT_SHED_RATE = 2.0          # admitted prepares/s while throttling
DEFAULT_SHED_BURST = 4
DEFAULT_NODE_HITS = 2            # exemplar→node surfacings before bias
DEFAULT_UNPLACEABLE_BURST = 5    # unplaceable deltas per tick → defrag
AUDIT_RING = 256

# which corrective knobs a breach on a histogram reaches for; histograms
# not listed get the admission throttle only (the one knob that is
# always safe: it sheds load without touching placement)
HISTOGRAM_ACTIONS: Dict[str, Tuple[str, ...]] = {
    "tdp_attach_wall_ms": ("pacer_backoff", "admission_throttle"),
    "tdp_prepare_wall_ms": ("pacer_backoff", "admission_throttle"),
    "tdp_kubeapi_rtt_ms": ("pacer_backoff",),
    "tdp_watch_convergence_ms": ("pacer_backoff",),
}
DEFAULT_ACTIONS: Tuple[str, ...] = ("admission_throttle",)


class TokenBucket:
    """The admission-shed bucket: ``take()`` admits while tokens last,
    refilling at ``rate``/s up to ``burst``. Plain unregistered lock —
    the prepare path already does API round-trips; one uncontended
    plain-lock take is noise, and the lock never nests with any
    registered lock."""

    def __init__(self, rate: float, burst: float,
                 now: Callable[[], float] = time.monotonic) -> None:
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.rate = rate
        self.burst = burst
        self._now = now
        self._tokens = burst
        self._last = now()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._now()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "burst": self.burst,
                    "tokens": round(self._tokens, 3)}


def _exemplar_link(exemplar: Optional[dict]) -> Optional[dict]:
    """A trace link from a breach exemplar. The histogram exemplar
    carries only the trace id; the link wire shape needs a span id for
    validity, so one is derived from the trace id — the linked ROOT
    span adopts the trace id (trace.py), which is the part the
    one-query reconstruction rides on."""
    tid = (exemplar or {}).get("trace_id")
    if not isinstance(tid, str) or len(tid) != 32:
        return None
    return {"trace_id": tid, "span_id": tid[:16]}


class RemediationEngine:
    """The closed loop: subscribe → queue → tick → act/rollback.

    Constructor wiring is all optional — an engine with no pacer skips
    pacer actions (counted ``skipped`` in the audit, never an error),
    so the same class serves the single-daemon deployment (pacer +
    admission only) and the scheduler-side fleet deployment (defrag +
    bias + drain)."""

    ACTION_KINDS = ("pacer_backoff", "admission_throttle",
                    "defrag_wave", "node_bias")

    def __init__(self,
                 pacer=None,
                 scheduler=None,
                 policy=None,
                 fleet_flight=None,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 action_window_s: float = DEFAULT_ACTION_WINDOW_S,
                 max_actions_per_window: int =
                 DEFAULT_MAX_ACTIONS_PER_WINDOW,
                 pacer_floor_s: float = DEFAULT_PACER_FLOOR_S,
                 shed_rate: float = DEFAULT_SHED_RATE,
                 shed_burst: float = DEFAULT_SHED_BURST,
                 node_hits_threshold: int = DEFAULT_NODE_HITS,
                 unplaceable_burst: int = DEFAULT_UNPLACEABLE_BURST,
                 defrag_shape="2x2",
                 drain_on_bias: bool = True,
                 now: Callable[[], float] = time.monotonic) -> None:
        self.pacer = pacer
        self.scheduler = scheduler
        self.policy = policy
        self.fleet_flight = fleet_flight
        self.cooldown_s = cooldown_s
        self.action_window_s = action_window_s
        self.max_actions_per_window = max(1, max_actions_per_window)
        self.pacer_floor_s = pacer_floor_s
        self.shed_rate = shed_rate
        self.shed_burst = shed_burst
        self.node_hits_threshold = max(1, node_hits_threshold)
        self.unplaceable_burst = max(1, unplaceable_burst)
        self.defrag_shape = defrag_shape
        self.drain_on_bias = drain_on_bias
        self._now = now
        # PLAIN unregistered lock (module doc): guards the queue, the
        # counters, the hysteresis state and the audit ring — and is
        # NEVER held while a knob (registered locks) is being turned
        self._lock = threading.Lock()
        self._pending: List[dict] = []
        # counters[*] owned by remediation.RemediationEngine._lock
        # (tsalint COUNTERS); /status reads a C-atomic dict copy
        self.counters: Dict[str, int] = {
            "transitions_total": 0,
            "ticks_total": 0,
            "actions_total": 0,
            "rollbacks_total": 0,
            "vetoes_total": 0,
            "sheds_total": 0,
            "cooldown_skips_total": 0,
            "window_skips_total": 0,
            "errors_total": 0,
        }
        # active knobs: (kind, target) -> {"slos": set, "trace_id",
        # "applied_at", "detail"} — rolled back when the LAST holding
        # SLO recovers
        self._active: Dict[Tuple[str, str], dict] = {}
        # hysteresis state
        self._last_action: Dict[Tuple[str, str], float] = {}
        self._action_times: Deque[float] = deque()
        # exemplar → node attribution hits across breaches
        self._node_hits: Dict[str, int] = {}
        # per-action-kind last applied trace id (the /status surface)
        self._last_trace: Dict[str, str] = {}
        # the shed bucket: None = no throttle active (admit() fast
        # path is this one attribute read)
        self._shed_bucket: Optional[TokenBucket] = None
        self._shed_reason = ""
        # unplaceable-burst baseline (scheduler stats deltas per tick)
        self._unplaceable_seen: Optional[int] = None
        self._audit: Deque[dict] = deque(maxlen=AUDIT_RING)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # ----------------------------------------------------- subscription

    def on_transition(self, event: dict) -> None:
        """The SLOEngine.subscribe listener. Runs on the evaluating
        thread — possibly the /status scrape inside a zero-lock
        read-path bracket — so it ONLY queues (plain lock, no
        registered locks, no knob work)."""
        with self._lock:
            self.counters["transitions_total"] += 1
            self._pending.append(dict(event))

    # ----------------------------------------------------- admission gate

    def admit(self, ctx: Optional[dict] = None) -> Optional[str]:
        """The admission seat consulted by the DRA prepare path and the
        device-plugin Allocate path. None = admitted. While a throttle
        action is active, requests above the token rate get a TYPED
        reason string (the caller raises/aborts with it) and are
        counted — never silently dropped."""
        bucket = self._shed_bucket            # GIL-atomic ref read
        if bucket is None:
            return None
        if bucket.take():
            return None
        with self._lock:
            self.counters["sheds_total"] += 1
            reason = self._shed_reason
        return reason or "admission shed by remediation throttle"

    # ------------------------------------------------------------- audit

    def _record(self, status: str, action: str, slo: str,
                target: str, trace_id: Optional[str],
                detail: object = None) -> None:
        self._audit.append({
            "ts": time.time(), "status": status, "action": action,
            "slo": slo, "target": target, "trace_id": trace_id,
            "detail": detail})

    # -------------------------------------------------------- hysteresis

    def _admissible(self, kind: str, target: str, slo: str,
                    trace_id: Optional[str], now: float) -> bool:
        """The hysteresis gate, counters + audit under _lock. False =
        skip (already counted and audited — the caller just moves on)."""
        key = (kind, target)
        with self._lock:
            last = self._last_action.get(key)
            if last is not None and now - last < self.cooldown_s:
                self.counters["cooldown_skips_total"] += 1
                self._record("skipped_cooldown", kind, slo, target,
                             trace_id)
                return False
            while self._action_times and \
                    now - self._action_times[0] > self.action_window_s:
                self._action_times.popleft()
            if len(self._action_times) >= self.max_actions_per_window:
                self.counters["window_skips_total"] += 1
                self._record("skipped_window", kind, slo, target,
                             trace_id)
                return False
            # charge the budget now: a policy veto still consumed an
            # operator decision, and NOT charging it would let a vetoing
            # policy be hammered once per tick forever
            self._last_action[key] = now
            self._action_times.append(now)
        return True

    # ----------------------------------------------------------- actions

    def _veto(self, kind: str, slo: str, target: str,
              trace_id: Optional[str], params: dict) -> Optional[str]:
        """The operator gate: policy.remediate may veto/retune. None =
        approved. A veto is counted + audited here."""
        engine = self.policy
        if engine is None or not engine.has_hook("remediate"):
            return None
        ctx = {"action": kind, "slo": slo, "target": target,
               "trace_id": trace_id or ""}
        ctx.update(params)
        reason = engine.remediate(ctx)
        if reason is None:
            return None
        with self._lock:
            self.counters["vetoes_total"] += 1
            self._record("vetoed", kind, slo, target, trace_id,
                         detail=reason)
        return reason

    def _apply(self, kind: str, slo: str, target: str,
               exemplar: Optional[dict], params: dict,
               fn: Callable[[], object]) -> bool:
        """One action end-to-end: hysteresis → policy gate → spanned
        execution (linked to the breach exemplar trace) → active-knob
        registration + audit. Returns True when the knob was turned."""
        now = self._now()
        trace_id = (exemplar or {}).get("trace_id")
        if not self._admissible(kind, target, slo, trace_id, now):
            return False
        if self._veto(kind, slo, target, trace_id, params) is not None:
            return False
        link = _exemplar_link(exemplar)
        try:
            with trace.span("remediation.action", link=link,
                            action=kind, slo=slo, target=target):
                detail = fn()
        except Exception as exc:
            with self._lock:
                self.counters["errors_total"] += 1
                self._record("error", kind, slo, target, trace_id,
                             detail=f"{type(exc).__name__}: {exc}")
            log.exception("remediation: %s on %s failed", kind, target)
            return False
        with self._lock:
            self.counters["actions_total"] += 1
            entry = self._active.get((kind, target))
            if entry is None:
                self._active[(kind, target)] = {
                    "slos": {slo}, "trace_id": trace_id,
                    "applied_at": now, "detail": detail}
            else:
                entry["slos"].add(slo)
            if trace_id:
                self._last_trace[kind] = trace_id
            self._record("applied", kind, slo, target, trace_id,
                         detail=detail)
        log.warning("remediation: %s applied (slo=%s target=%s "
                    "trace=%s): %s", kind, slo, target, trace_id, detail)
        return True

    def _act_pacer_backoff(self, slo: str, exemplar) -> None:
        pacer = self.pacer
        if pacer is None:
            return

        def turn():
            pacer.set_backoff_floor(self.pacer_floor_s)
            return {"floor_s": self.pacer_floor_s}

        self._apply("pacer_backoff", slo, "publish-pacer", exemplar,
                    {"floor_s": self.pacer_floor_s}, turn)

    def _act_admission_throttle(self, slo: str, exemplar) -> None:
        trace_id = (exemplar or {}).get("trace_id") or ""

        def turn():
            # (re)arming is idempotent: a second burning SLO shares the
            # same bucket, and the typed reason names the newest breach
            self._shed_reason = (
                f"remediation admission shed (slo={slo}"
                f"{', trace=' + trace_id if trace_id else ''})")
            if self._shed_bucket is None:
                self._shed_bucket = TokenBucket(
                    self.shed_rate, self.shed_burst, now=self._now)
            return {"rate": self.shed_rate, "burst": self.shed_burst}

        self._apply("admission_throttle", slo, "admission", exemplar,
                    {"rate": self.shed_rate, "burst": self.shed_burst},
                    turn)

    def _act_defrag_wave(self, slo: str, exemplar) -> None:
        sched = self.scheduler
        if sched is None:
            return

        def turn():
            proposal = sched.plan_defrag_wave(self.defrag_shape)
            if proposal.get("placeable"):
                return {"moves_applied": 0, "reason": "already placeable"}
            moves = [m for m in proposal.get("migrations", ())
                     if m.get("target_node") is not None]
            if not moves:
                return {"moves_applied": 0, "reason": "no resolvable moves"}
            report = sched.apply_defrag_wave(proposal)
            return {"moves_applied": report["moves_applied"],
                    "wave": report["wave"]}

        self._apply("defrag_wave", slo, f"shape-{self.defrag_shape}",
                    exemplar, {"shape": str(self.defrag_shape)}, turn)

    def _act_node_bias(self, slo: str, node: str, exemplar) -> None:
        sched = self.scheduler
        if sched is None:
            return

        def turn():
            sched.bias_away(node, reason=f"slo={slo}")
            detail = {"biased": node}
            if self.drain_on_bias:
                plan = sched.plan_drain(node)
                if any(m.get("target_node") for m in plan["migrations"]):
                    report = sched.apply_defrag_wave(plan)
                    detail["drained"] = report["moves_applied"]
                else:
                    detail["drained"] = 0
            return detail

        self._apply("node_bias", slo, node, exemplar,
                    {"node": node, "drain": self.drain_on_bias}, turn)

    # --------------------------------------------------------- rollbacks

    def _rollback_knob(self, kind: str, target: str) -> Optional[dict]:
        """Undo one knob. Returns a detail dict, or None when there is
        nothing to undo (the wired component went away)."""
        if kind == "pacer_backoff":
            if self.pacer is None:
                return None
            self.pacer.clear_backoff_floor()
            return {"floor_cleared": True}
        if kind == "admission_throttle":
            self._shed_bucket = None
            self._shed_reason = ""
            return {"throttle_cleared": True}
        if kind == "node_bias":
            if self.scheduler is None:
                return None
            self.scheduler.clear_bias(target)
            return {"bias_cleared": target}
        # defrag_wave is one-shot: nothing to roll back, but the active
        # entry still clears so a later incident can wave again
        return {}

    def _rollback_for(self, slo: str, exemplar: Optional[dict]) -> int:
        """Roll back every knob `slo` holds; a knob held by several
        burning SLOs survives until its LAST holder recovers."""
        with self._lock:
            to_undo: List[Tuple[str, str]] = []
            for key, entry in list(self._active.items()):
                if slo not in entry["slos"]:
                    continue
                entry["slos"].discard(slo)
                if not entry["slos"]:
                    to_undo.append(key)
        undone = 0
        link = _exemplar_link(exemplar)
        for kind, target in to_undo:
            entry = self._active.get((kind, target)) or {}
            tid = entry.get("trace_id")
            try:
                with trace.span("remediation.rollback",
                                link=link or _exemplar_link(
                                    {"trace_id": tid}),
                                action=kind, slo=slo, target=target):
                    detail = self._rollback_knob(kind, target)
            except Exception as exc:
                with self._lock:
                    self.counters["errors_total"] += 1
                    self._record("error", kind, slo, target, tid,
                                 detail=f"rollback: {exc}")
                log.exception("remediation: rollback of %s on %s failed",
                              kind, target)
                continue
            with self._lock:
                self._active.pop((kind, target), None)
                self.counters["rollbacks_total"] += 1
                self._record("rolled_back", kind, slo, target, tid,
                             detail=detail)
            undone += 1
            log.warning("remediation: %s on %s rolled back (slo=%s "
                        "recovered)", kind, target, slo)
        return undone

    # ------------------------------------------------------ attribution

    def _attribute_node(self, exemplar: Optional[dict]) -> Optional[str]:
        """Exemplar → node via the fleet trace collector: every node
        labeled on the exemplar's waterfall (drivers stamp ``node=`` on
        their RPC roots; the unattributed control plane labels as the
        source name) scores a hit; a node crossing the threshold is the
        bias/drain candidate."""
        ff = self.fleet_flight
        tid = (exemplar or {}).get("trace_id")
        if ff is None or not tid:
            return None
        try:
            waterfall = ff.trace(tid)
        except Exception:
            with self._lock:
                self.counters["errors_total"] += 1
            return None
        hits = [n for n in waterfall.get("nodes", ())
                if n not in ("scheduler", "local")]
        candidate = None
        with self._lock:
            for node in hits:
                self._node_hits[node] = self._node_hits.get(node, 0) + 1
            for node in hits:
                if self._node_hits[node] >= self.node_hits_threshold:
                    candidate = node
                    break
        return candidate

    # -------------------------------------------------------------- tick

    def _check_unplaceable_burst(self) -> Optional[dict]:
        """Scheduler-stats delta check: a burst of unplaceable
        decisions since the last tick is the fragmentation signal (no
        SLO latches for it — capacity exists, it is just shattered)."""
        sched = self.scheduler
        if sched is None:
            return None
        unplaceable = sched.stats["unplaceable_total"].value
        seen, self._unplaceable_seen = self._unplaceable_seen, unplaceable
        if seen is None:
            return None
        if unplaceable - seen < self.unplaceable_burst:
            return None
        return {"slo": "unplaceable_burst", "kind": "breach",
                "histogram": None, "exemplar": None,
                "delta": unplaceable - seen}

    def tick(self, now: Optional[float] = None) -> dict:
        """One remediation pass: drain the queued SLO transitions, act
        on breaches, roll back on recoveries, and run the
        fragmentation-burst check. Never called from the /status scrape
        (knobs take registered locks); the background thread, the
        autopilot soak, or a test drives it. Returns a tick report."""
        del now  # hysteresis uses self._now(); kept for call symmetry
        with self._lock:
            self.counters["ticks_total"] += 1
            batch, self._pending = self._pending, []
        actions = rollbacks = 0
        burst = self._check_unplaceable_burst()
        if burst is not None:
            before = self.counters["actions_total"]
            self._act_defrag_wave(burst["slo"], None)
            actions += self.counters["actions_total"] - before
        for event in batch:
            slo_name = event.get("slo", "")
            exemplar = event.get("exemplar")
            if event.get("kind") == "recovered":
                rollbacks += self._rollback_for(slo_name, exemplar)
                continue
            before = self.counters["actions_total"]
            kinds = HISTOGRAM_ACTIONS.get(event.get("histogram") or "",
                                          DEFAULT_ACTIONS)
            for kind in kinds:
                if kind == "pacer_backoff":
                    self._act_pacer_backoff(slo_name, exemplar)
                elif kind == "admission_throttle":
                    self._act_admission_throttle(slo_name, exemplar)
                elif kind == "defrag_wave":
                    self._act_defrag_wave(slo_name, exemplar)
            node = self._attribute_node(exemplar)
            if node is not None and ("node_bias", node) not in self._active:
                self._act_node_bias(slo_name, node, exemplar)
            actions += self.counters["actions_total"] - before
        return {"processed": len(batch), "actions": actions,
                "rollbacks": rollbacks,
                "burst": None if burst is None else burst["delta"]}

    # ------------------------------------------------- background driver

    def start(self, interval_s: float = 1.0) -> None:
        """Run tick() on a daemon thread every `interval_s` — the
        production wiring (cli.main). Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()

        def run() -> None:
            while not self._stop_evt.wait(timeout=interval_s):
                try:
                    self.tick()
                except Exception:
                    with self._lock:
                        self.counters["errors_total"] += 1
                    log.exception("remediation tick failed")

        self._thread = threading.Thread(
            target=run, daemon=True, name="remediation-tick")
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None

    # ----------------------------------------------------------- surface

    def snapshot(self) -> dict:
        """The /status ``remediation`` section: totals, active knobs,
        live cool-downs, per-action last trace id. Counters via a
        C-atomic dict copy; the rest copied under the plain lock
        (cold-path read, one scrape per interval)."""
        counters = dict(self.counters)
        now = self._now()
        with self._lock:
            active = [{
                "action": kind, "target": target,
                "slos": sorted(entry["slos"]),
                "trace_id": entry.get("trace_id"),
                "age_s": round(now - entry["applied_at"], 1),
            } for (kind, target), entry in sorted(self._active.items())]
            cooldowns = {
                f"{kind}:{target}": round(
                    max(0.0, self.cooldown_s - (now - t)), 1)
                for (kind, target), t in sorted(self._last_action.items())
                if now - t < self.cooldown_s}
            last_trace = dict(self._last_trace)
            pending = len(self._pending)
        bucket = self._shed_bucket
        return {
            **counters,
            "pending_transitions": pending,
            "active_actions": active,
            "cooldowns": cooldowns,
            "last_trace_ids": last_trace,
            "shed_bucket": None if bucket is None else bucket.snapshot(),
            "node_hits": dict(self._node_hits),
        }

    def debug(self) -> dict:
        """The /debug/remediation body: the snapshot plus the audited
        action log (bounded ring, oldest first)."""
        out = self.snapshot()
        out["audit"] = list(self._audit)
        return out


def render_prometheus(engine: RemediationEngine) -> List[str]:
    """tpu_plugin_remediation_* families for /metrics (strict
    text-format: HELP/TYPE per family, contiguous)."""
    snap = engine.snapshot()
    lines: List[str] = []
    families = [
        ("actions_total", "counter",
         "Remediation actions applied (policy-approved, audited)."),
        ("rollbacks_total", "counter",
         "Remediation knobs rolled back after latched SLO recovery."),
        ("vetoes_total", "counter",
         "Remediation actions vetoed by the policy remediate hook."),
        ("sheds_total", "counter",
         "Admission requests shed (typed) by the remediation throttle."),
        ("cooldown_skips_total", "counter",
         "Actions skipped inside a per-target cool-down window."),
        ("window_skips_total", "counter",
         "Actions skipped by the actions-per-window budget."),
        ("transitions_total", "counter",
         "SLO breach/recovery transitions received."),
        ("errors_total", "counter",
         "Remediation actions or rollbacks that raised."),
        ("active_actions", "gauge",
         "Remediation knobs currently applied (not yet rolled back)."),
    ]
    for name, kind, help_text in families:
        lines += [f"# HELP tpu_plugin_remediation_{name} {help_text}",
                  f"# TYPE tpu_plugin_remediation_{name} {kind}"]
        value = (len(snap["active_actions"])
                 if name == "active_actions" else snap[name])
        lines.append(f"tpu_plugin_remediation_{name} {value}")
    return lines
