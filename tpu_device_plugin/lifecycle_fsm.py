"""Per-device lifecycle FSM — the survivability contract for passthrough.

The chaos suite (PR 1) proved the daemon survives restarts and flaps, but
the hard production transitions — PCIe surprise removal of an in-use
chip, the same slot coming back with different silicon, a VMI live
migration moving a claim between nodes — were implicit in whatever
health/rediscovery happened to do. Virtio-FPGA and the SystemC-TLM
PCI-passthrough model (PAPERS.md) both make passthrough devices
survivable by giving every device an explicit attach/detach state
machine; this module is that contract for the daemon:

    (admitted) → present → bound → allocated → detaching → bound
                     │        │        │            │
                     └────────┴────────┴────────────┴──→ gone → replugged
                                                                   │
                                   identity reconciled (BDF+serial)┴→ present

- **present**: enumerated in sysfs; **bound**: vfio-bound (discovery only
  admits bound chips, so inventory devices land here);
- **allocated**: a DRA claim is prepared against it (claim UIDs tracked),
  or the classic device-plugin path granted it (anonymous — the Device
  Plugin API cannot revoke, so these marks ride a lock-free queue and
  demote back to bound on the next inventory sync with no claims);
- **detaching**: an orderly unprepare/migration handoff is in flight;
- **gone**: the sysfs/devfs evidence of the device vanished while the
  daemon was watching — hot-unplug. If claims were attached they are
  ORPHANED: counted, recorded as a guest-visible surprise removal, and
  reported to the DRA driver (which marks the checkpoint entries and
  drops the device from the published ResourceSlice). Orphaned claims
  never silently reattach;
- **replugged**: the device reappeared. Readmission requires identity
  reconciliation — same BDF *and* same serial (sysfs `serial_number`,
  falling back to the PCI device id). A mismatch is an identity swap:
  different silicon in the same slot readmits as a NEW device while the
  old identity's claims stay orphaned.

Fault sites (docs/fault-injection.md): `pci.hotunplug` (value) fires at
the presence-evidence seam — an armed fault makes the next presence
observation read as a surprise removal; `pci.replug` (value) fires in
the identity reconciliation — an armed fault makes the replug read as an
identity swap. Both let chaos schedules inject the transition without a
real fs mutation.

Concurrency: one writer-side lock serializes transitions (hub events,
inventory syncs, DRA claim marks). The READ side — `stats()`, feeding
/status and /metrics — is lock-free by the same contract as
healthhub.stats(): GIL-atomic attribute/int reads and C-atomic dict/
deque copies, so a slow scrape never queues behind a transition (the
/status lockdep gate in tests/test_epoch.py pins zero acquisitions).
The classic Allocate hot path records its marks with one C-atomic deque
append (`note_allocation_event`) — zero locks inside the
`server.Allocate` read-path bracket — and the queue drains under the
lock on the next writer-side call.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from . import faults
from . import lockdep
from . import trace

log = logging.getLogger(__name__)

__all__ = ["ABSENT", "PRESENT", "BOUND", "ALLOCATED", "DETACHING", "GONE",
           "REPLUGGED", "DeviceLifecycle"]

# lifecycle states (the ISSUE's contract; ABSENT is the pseudo-state a
# device is admitted from, so first admission is a counted transition too)
ABSENT = "absent"
PRESENT = "present"
BOUND = "bound"
ALLOCATED = "allocated"
DETACHING = "detaching"
GONE = "gone"
REPLUGGED = "replugged"

# The allowed-transition table. Anything else is an invalid transition:
# counted + logged, never raised — lifecycle events arrive from daemon
# threads (health hub, rediscovery tick) that must not die on a
# surprising interleaving.
_ALLOWED = frozenset({
    (ABSENT, PRESENT),
    (PRESENT, BOUND),
    (BOUND, ALLOCATED),
    (ALLOCATED, DETACHING),
    (DETACHING, BOUND),
    # anonymous classic-path allocation marks demote on an inventory sync
    # that finds no tracked claims (the Device Plugin API never tells us
    # the grant ended)
    (ALLOCATED, BOUND),
    # administrative vfio unbind: the device left the inventory but is
    # still enumerated in sysfs — present, not gone (rebind promotes it
    # back on the next sync)
    (BOUND, PRESENT),
    # a NEW claim prepared while another claim's detach is in flight on
    # the same device re-enters allocated; the last release still
    # returns it to bound
    (DETACHING, ALLOCATED),
    # hot-unplug can strike in any live state
    (PRESENT, GONE),
    (BOUND, GONE),
    (ALLOCATED, GONE),
    (DETACHING, GONE),
    (GONE, REPLUGGED),
    # readmission after identity reconciliation (or as the swap's new
    # identity); a device that vanishes again before reconciling goes
    # straight back
    (REPLUGGED, PRESENT),
    (REPLUGGED, GONE),
})

# how many recent guest-visible surprise removals /status retains
_SURPRISE_RING = 16


class _DeviceRecord:
    __slots__ = ("raw", "serial", "state", "claims", "since")

    def __init__(self, raw: str, serial: Optional[str]) -> None:
        self.raw = raw
        self.serial = serial
        self.state = ABSENT
        self.claims: set = set()
        self.since = time.time()


class DeviceLifecycle:
    """Host-level per-device lifecycle tracker (module docstring).

    `serial_reader(raw) -> Optional[str]` supplies the identity attribute
    for replug reconciliation (discovery.read_serial over sysfs in
    production; tests inject). `on_devices_gone(events)` is the DRA
    driver's hook, fired with a BATCH of `(raw, orphaned_claim_uids)`
    pairs covering every gone transition of one observation — a
    multi-device removal (a PCIe switch dropping) costs one epoch
    publish and one slice republish downstream, not one per device. The
    claim list is empty for an unallocated device; the driver still
    drops it from the published ResourceSlice. Called OUTSIDE the FSM
    lock, after the transitions are recorded, so the driver's own
    locking never nests inside ours.
    """

    def __init__(
        self,
        serial_reader: Optional[Callable[[str], Optional[str]]] = None,
        on_devices_gone: Optional[Callable[[List], None]] = None,
        presence_reader: Optional[Callable[[str], bool]] = None,
        on_device_readmitted: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.serial_reader = serial_reader
        self.on_devices_gone = on_devices_gone
        # fired (outside the lock) when a GONE device passes replug
        # reconciliation — with or without an identity swap. The DRA
        # driver needs this because an unplug+replug that both land
        # within ONE rediscovery tick leaves the registry signature
        # unchanged: no inventory event would ever readmit the device
        # into the published slice without it.
        self.on_device_readmitted = on_device_readmitted
        # CORROBORATION before declaring hot-unplug: a /dev/vfio node
        # flap (udev churn) is a recoverable HEALTH event the health
        # plane already owns — only when the device's sysfs presence is
        # also gone is it a PCIe surprise removal. None trusts the event
        # (tests drive the seam directly); production passes a sysfs
        # isdir probe. An armed `pci.hotunplug` fault bypasses the check
        # so chaos can inject removals without fs mutations.
        self.presence_reader = presence_reader
        self._lock = lockdep.instrument(
            "lifecycle_fsm.DeviceLifecycle._lock", threading.Lock())
        self._records: Dict[str, _DeviceRecord] = {}
        # counters — written ONLY under _lock (tsalint counter ownership);
        # read lock-free by stats() via GIL-atomic reads / C-atomic copies
        self.transition_counts: Dict[str, int] = {}   # "from->to" -> n
        self.claims_orphaned_total = 0
        self.identity_swaps_total = 0
        self.invalid_transitions_total = 0
        self._surprise_removals: deque = deque(maxlen=_SURPRISE_RING)
        # classic-path allocation marks: producers (the Allocate read
        # path, pinned lock-free) append C-atomically; drained under
        # _lock by the next writer-side call
        self._alloc_events: deque = deque()
        # claim marks restored from the DRA checkpoint (restore_claims)
        # for devices not admitted yet: applied at admission, or orphaned
        # by the first sync if the device never returns (it was
        # hot-unplugged while the daemon was down)
        self._pending_claims: Dict[str, set] = {}

    # ------------------------------------------------------------ writers

    def _transition_locked(self, rec: _DeviceRecord, to: str) -> bool:
        """Move `rec` to `to` if the table allows it; count either way."""
        frm = rec.state
        if frm == to:
            return True
        if (frm, to) not in _ALLOWED:
            self.invalid_transitions_total += 1
            log.warning("lifecycle: invalid transition %s: %s -> %s "
                        "(ignored)", rec.raw, frm, to)
            return False
        key = f"{frm}->{to}"
        self.transition_counts[key] = self.transition_counts.get(key, 0) + 1
        rec.state = to
        rec.since = time.time()
        # flight-recorder marker (lock-free event; emitting under the FSM
        # lock costs readers nothing): every device's state history is
        # reconstructable from /debug/flight?bdf=<raw>
        trace.event("lifecycle.transition", device=rec.raw,
                    **{"from": frm, "to": to})
        log.info("lifecycle: %s %s -> %s", rec.raw, frm, to)
        return True

    def _drain_alloc_events_locked(self) -> None:
        while True:
            try:
                ids = self._alloc_events.popleft()
            except IndexError:
                return
            for raw in ids:
                rec = self._records.get(raw)
                if rec is not None and rec.state == BOUND:
                    self._transition_locked(rec, ALLOCATED)

    def _admit_locked(self, raw: str, serial: Optional[str],
                      bound: bool) -> _DeviceRecord:
        rec = self._records[raw] = _DeviceRecord(raw, serial)
        self._transition_locked(rec, PRESENT)
        if bound:
            self._transition_locked(rec, BOUND)
        pending = self._pending_claims.pop(raw, None)
        if pending:
            # restart-restored claim marks (restore_claims): the device
            # came back with its prepared claims still tracked
            self._transition_locked(rec, ALLOCATED)
            rec.claims.update(pending)
        return rec

    def _mark_gone_locked(self, rec: _DeviceRecord) -> Optional[List[str]]:
        """→ GONE; returns the orphaned claim UIDs — empty when nothing
        was attached (the caller still delivers the gone hook outside the
        lock so the DRA slice drops the device) — or None when the
        transition was refused."""
        if not self._transition_locked(rec, GONE):
            return None
        if not rec.claims:
            return []
        orphans = sorted(rec.claims)
        rec.claims.clear()          # orphaned claims never reattach
        self.claims_orphaned_total += len(orphans)
        for uid in orphans:
            # one event PER CLAIM so /debug/flight?claim= ends the
            # claim's story with its surprise removal
            trace.event("lifecycle.claim.orphaned", claim_uid=uid,
                        device=rec.raw)
        self._surprise_removals.append({
            "device": rec.raw,
            "claims": orphans,
            "at": time.time(),
        })
        log.error("lifecycle: surprise removal of ALLOCATED device %s — "
                  "orphaning claim(s) %s (guest saw the device vanish)",
                  rec.raw, ", ".join(orphans))
        return orphans

    def _replug_locked(self, rec: _DeviceRecord,
                       serial: Optional[str]) -> bool:
        """GONE → REPLUGGED → identity reconciliation → PRESENT.

        Returns True when the device readmitted with its identity intact;
        False on an identity swap (new silicon in the slot — readmitted
        as a fresh identity, counted, old claims stay orphaned).
        """
        self._transition_locked(rec, REPLUGGED)
        # fault point "pci.replug" (value kind): an armed fault makes the
        # reconciliation read as an identity swap without a real serial
        # change
        swapped = faults.fire("pci.replug", device=rec.raw)
        if not swapped and rec.serial is not None and serial is not None \
                and serial != rec.serial:
            swapped = True
        trace.event("lifecycle.replug", device=rec.raw,
                    identity_swap=swapped)
        if swapped:
            self.identity_swaps_total += 1
            log.warning(
                "lifecycle: %s replugged with DIFFERENT identity "
                "(serial %r -> %r); readmitting as new silicon — prior "
                "claims stay orphaned", rec.raw, rec.serial, serial)
            rec.serial = serial
            rec.claims.clear()
        elif serial is not None:
            rec.serial = serial
        self._transition_locked(rec, PRESENT)
        return not swapped

    def _read_serial(self, raw: str) -> Optional[str]:
        if self.serial_reader is None:
            return None
        try:
            return self.serial_reader(raw)
        except Exception as exc:
            log.debug("lifecycle: serial read for %s failed: %s", raw, exc)
            return None

    # ------------------------------------------------------- event intake

    def note_fs_event(self, raw: str, exists: bool) -> None:
        """Fast-path presence evidence from the HealthHub fs watcher.

        Unknown devices are ignored (the inventory sync admits); a
        disappearance orphans attached claims; a reappearance runs the
        replug reconciliation.
        """
        # fault point "pci.hotunplug" (value kind): presence evidence is
        # inverted — the chaos suite injects a surprise removal without
        # touching the fake host's filesystem (corroboration is bypassed:
        # the injected removal must win)
        forced = False
        if exists and faults.fire("pci.hotunplug", device=raw):
            exists = False
            forced = True
        if not exists and not forced and self.presence_reader is not None:
            try:
                still_present = self.presence_reader(raw)
            except Exception:
                still_present = False
            if still_present:
                # device node lost but the device is still enumerated:
                # a health event (the health plane flips it Unhealthy),
                # NOT a hot-unplug — no gone transition, no orphaning
                return
        # lazy identity read: only a reappearance of a GONE record pays a
        # sysfs read (the peek is lock-free; a racing transition at worst
        # costs one redundant read)
        peek = self._records.get(raw)
        serial = self._read_serial(raw) \
            if exists and peek is not None and peek.state == GONE else None
        orphans = None
        readmitted = False
        with self._lock:
            rec = self._records.get(raw)
            if rec is None:
                return
            self._drain_alloc_events_locked()
            if not exists and rec.state != GONE:
                orphans = self._mark_gone_locked(rec)
            elif exists and rec.state == GONE:
                if serial is None:
                    # the lock-free peek saw a pre-GONE state (a racing
                    # sync marked it GONE since): the reconciliation
                    # still needs the identity — read it here, under the
                    # lock (rare path; the FSM lock is not hot)
                    serial = self._read_serial(raw)
                self._replug_locked(rec, serial)
                if rec.state == PRESENT:
                    # fs evidence back implies the node is usable again;
                    # the next inventory sync confirms the vfio binding
                    self._transition_locked(rec, BOUND)
                    readmitted = True
        if orphans is not None:
            self._deliver_gone([(raw, orphans)])
        if readmitted:
            self._deliver_readmitted(raw)

    def sync_inventory(self, present: Dict[str, Optional[str]]) -> None:
        """Authoritative sysfs truth from (re)discovery: `present` maps
        every vfio-bound raw id to its serial (None when unreadable).

        New ids are admitted (present→bound); ids that left sysfs go
        GONE (orphaning claims); GONE ids that returned reconcile
        identity and readmit. ALLOCATED records with no tracked claims
        demote to BOUND (anonymous classic-path grants the API never
        tells us ended).
        """
        filtered: Dict[str, Optional[str]] = {}
        forced: set = set()
        for raw, serial in present.items():
            # same seam as note_fs_event: an armed pci.hotunplug makes
            # this sync read the device as missing (corroboration below
            # is bypassed for it — the injected removal must win)
            if faults.fire("pci.hotunplug", device=raw):
                forced.add(raw)
                continue
            filtered[raw] = serial
        # corroborate disappearances OUTSIDE the lock (sysfs probes are
        # file I/O): an id missing from the inventory but still
        # enumerated is an administrative unbind, not a hot-unplug
        absent: Dict[str, bool] = {}
        if self.presence_reader is not None:
            for raw, rec in list(self._records.items()):
                if raw in filtered or raw in forced or rec.state == GONE:
                    continue
                try:
                    absent[raw] = not self.presence_reader(raw)
                except Exception:
                    absent[raw] = True
        orphan_batches: List = []
        readmitted: List[str] = []
        with self._lock:
            self._drain_alloc_events_locked()
            for raw, serial in filtered.items():
                rec = self._records.get(raw)
                if rec is None:
                    self._admit_locked(raw, serial, bound=True)
                elif rec.state == GONE:
                    self._replug_locked(rec, serial)
                    self._transition_locked(rec, BOUND)
                    readmitted.append(raw)
                elif rec.state == PRESENT:
                    # rebound after an administrative unbind: back in the
                    # inventory means vfio-bound again
                    self._transition_locked(rec, BOUND)
                elif rec.state == ALLOCATED and not rec.claims:
                    self._transition_locked(rec, BOUND)
            # restart-restored claim marks whose device is NOT in this
            # sync's ground truth: the hot-unplug happened while the
            # daemon was down — discovered now, orphaned now
            for raw in list(self._pending_claims):
                if raw in filtered:
                    continue
                uids = sorted(self._pending_claims.pop(raw))
                self.claims_orphaned_total += len(uids)
                for uid in uids:
                    trace.event("lifecycle.claim.orphaned", claim_uid=uid,
                                device=raw)
                self._surprise_removals.append(
                    {"device": raw, "claims": uids, "at": time.time()})
                log.error("lifecycle: device %s (with restored claim(s) "
                          "%s) absent at startup sync — hot-unplugged "
                          "while the daemon was down; orphaning",
                          raw, ", ".join(uids))
                orphan_batches.append((raw, uids))
            for raw, rec in self._records.items():
                if raw in filtered or rec.state == GONE:
                    continue
                if not absent.get(raw, True):
                    # left the inventory but still enumerated in sysfs:
                    # an administrative unbind, not a hot-unplug
                    if rec.state == BOUND:
                        self._transition_locked(rec, PRESENT)
                    continue
                orphans = self._mark_gone_locked(rec)
                if orphans is not None:
                    orphan_batches.append((raw, orphans))
        self._deliver_gone(orphan_batches)
        for raw in readmitted:
            self._deliver_readmitted(raw)

    def _deliver_readmitted(self, raw: str) -> None:
        if self.on_device_readmitted is None:
            return
        try:
            self.on_device_readmitted(raw)
        except Exception as exc:
            log.error("lifecycle: device-readmitted callback for %s "
                      "failed: %s", raw, exc)

    def _deliver_gone(self, events: List) -> None:
        """`events` is [(raw, orphaned_claim_uids), ...] — one batched
        delivery per observation so a multi-device removal costs one
        downstream publish."""
        if self.on_devices_gone is None or not events:
            return
        try:
            self.on_devices_gone(events)
        except Exception as exc:
            log.error("lifecycle: devices-gone callback for %s failed: %s",
                      [raw for raw, _ in events], exc)

    # ------------------------------------------------------- claim marks

    def restore_claims(self, claims_by_raw: Dict[str, List[str]]) -> None:
        """Replay persisted claim marks after a daemon restart (the DRA
        driver calls this from attach_lifecycle with every
        non-orphaned checkpoint entry's device raw ids).

        A fresh FSM knows nothing of claims prepared by the previous
        incarnation; without this replay, a post-restart hot-unplug of
        an allocated device would orphan nothing. Devices not admitted
        yet keep their marks pending: applied at admission, or orphaned
        by the first inventory sync if the device never returns (it was
        hot-unplugged while the daemon was down)."""
        with self._lock:
            for raw, uids in claims_by_raw.items():
                if not uids:
                    continue
                rec = self._records.get(raw)
                if rec is None:
                    self._pending_claims.setdefault(raw, set()).update(uids)
                elif rec.state in (BOUND, ALLOCATED, DETACHING):
                    if rec.state == BOUND:
                        self._transition_locked(rec, ALLOCATED)
                    rec.claims.update(uids)

    def note_allocated(self, raw: str, claim_uid: Optional[str]) -> None:
        """A DRA claim was prepared against `raw` (claim_uid tracked) or
        the device was granted anonymously (claim_uid None)."""
        with self._lock:
            rec = self._records.get(raw)
            if rec is None:
                return
            self._drain_alloc_events_locked()
            # DETACHING included: a new claim may prepare while another
            # claim's detach is in flight — its UID must be tracked or a
            # later hot-unplug would fail to orphan it
            if rec.state in (BOUND, ALLOCATED, DETACHING):
                self._transition_locked(rec, ALLOCATED)
                if claim_uid is not None:
                    rec.claims.add(claim_uid)

    def note_detaching(self, raw: str, claim_uid: Optional[str]) -> None:
        """An orderly unprepare/migration handoff of `raw` began."""
        with self._lock:
            rec = self._records.get(raw)
            if rec is None:
                return
            if rec.state == ALLOCATED:
                self._transition_locked(rec, DETACHING)

    def note_released(self, raw: str, claim_uid: Optional[str]) -> None:
        """The unprepare completed (durably): the claim no longer holds
        the device; the last claim out returns it to BOUND."""
        with self._lock:
            rec = self._records.get(raw)
            if rec is None:
                return
            if claim_uid is not None:
                rec.claims.discard(claim_uid)
            if not rec.claims and rec.state in (DETACHING, ALLOCATED):
                self._transition_locked(rec, BOUND)

    def note_allocation_event(self, device_ids: Sequence[str]) -> None:
        """LOCK-FREE producer for the classic Allocate hot path: one
        C-atomic deque append, zero registered locks (the server.Allocate
        read-path gate pins this). Drained under the lock by the next
        writer-side call."""
        self._alloc_events.append(tuple(device_ids))

    # ---------------------------------------------------------- read side

    def state_of(self, raw: str) -> str:
        rec = self._records.get(raw)        # GIL-atomic dict.get
        return rec.state if rec is not None else ABSENT

    def needs_identity(self, raw: str) -> bool:
        """Whether the next sync_inventory needs `raw`'s serial: only
        admission (untracked) and replug reconciliation (GONE) compare
        identity, so a warm rediscovery tick reads NO serial files
        (lock-free peek; discovery's read-count guards pin this)."""
        rec = self._records.get(raw)
        return rec is None or rec.state == GONE

    def stats(self) -> dict:
        """Counters + per-state gauges for /status and /metrics.

        LOCK-FREE read side (the /status lockdep gate): attribute/int
        reads are GIL-atomic, `dict(d)`/`list(d)` are C-atomic copies —
        a racing transition costs at most a one-step-stale value, and a
        scrape never queues behind the writer lock. The classic-path
        allocation queue is NOT drained here (that needs the lock); its
        marks land on the next writer-side event.
        """
        states: Dict[str, int] = {}
        for rec in list(self._records.values()):
            st = rec.state
            states[st] = states.get(st, 0) + 1
        return {
            "devices": len(self._records),
            "states": states,
            "transitions": dict(self.transition_counts),
            "claims_orphaned_total": self.claims_orphaned_total,
            "identity_swaps_total": self.identity_swaps_total,
            "invalid_transitions_total": self.invalid_transitions_total,
            "surprise_removals": [dict(e) for e in
                                  list(self._surprise_removals)],
        }
