"""tools/weave — the deterministic interleaving checker, checked.

Four claims have to hold for weave's verdicts to mean anything, and
each gets a direct test here:

1. DPOR explores the schedules that matter: a seeded 3-thread bug that
   BOTH naive baselines (one-thread-at-a-time and strict round-robin)
   execute clean is still found by `explore()`.
2. The preemption bound is honest: a bug needing two preemptions is
   found at bound 2, and at bound 1 it is missed WITH the pruning
   reported (`bound_pruned > 0`), never silently.
3. Counterexamples replay: the recorded schedule re-executes step for
   step and reproduces the identical failure.
4. The production hooks are inert outside a weave run: yield points
   no-op and the patched seams are restored after exploration.

Then the production matrix: every scenario in tools/weave/scenarios.py
must hold over its (complete or stated-bounded) schedule space, and
every seeded-bug twin must FIRE — a checker that cannot fire is a
failing test.
"""

import threading

import pytest

from tools.weave.core import (Counterexample, Scenario, explore, replay,
                              run_once)
from tools.weave.scenarios import SCENARIOS, TWINS
from tpu_device_plugin import schedcheck


# ------------------------------------------------------- tiny scenarios

class _GapBug(Scenario):
    """Seeded 3-thread bug built to dodge the naive baselines: the
    writer publishes two flags with a window between them, but only
    after two pad steps — so the observer's single read lands on the
    window only under an asymmetric schedule. One-thread-at-a-time
    never sees the window; strict round-robin reads one cycle too
    early. DPOR branches on the flag-location dependency and finds it."""

    name = "engine-gap-bug"

    def setup(self):
        return {"a": [0], "b": [0], "gap": []}

    def threads(self, state):
        def writer():
            schedcheck.yield_point("pad.w1", key="pad")
            schedcheck.yield_point("pad.w2", key="pad")
            schedcheck.yield_point("gap.a", key="gvar")
            state["a"][0] = 1
            schedcheck.yield_point("gap.b", key="gvar")
            state["b"][0] = 1

        def pad():
            for i in range(4):
                schedcheck.yield_point(f"pad.p{i}", key="pad")

        def obs():
            schedcheck.yield_point("pad.o", key="pad")
            schedcheck.yield_point("gap.read", key="gvar", mode="r")
            state["gap"].append(
                state["a"][0] == 1 and state["b"][0] == 0)

        return [("writer", writer), ("pad", pad), ("obs", obs)]

    def invariant(self, state, run):
        assert not state["gap"][0], "observer saw the a-set/b-unset window"


class _DepthTwoBug(Scenario):
    """Violation needs the full alternation w1 r1 w2 r2 across two
    threads — exactly two preemptions; no schedule with fewer shows
    (o1, o2) == (1, 3)."""

    name = "engine-depth-two-bug"

    def setup(self):
        return {"x": [0], "obs": []}

    def threads(self, state):
        def t_writer():
            schedcheck.yield_point("x.w1", key="x")
            state["x"][0] = 1
            schedcheck.yield_point("x.w2", key="x")
            state["x"][0] = 3

        def t_reader():
            schedcheck.yield_point("x.r1", key="x", mode="r")
            o1 = state["x"][0]
            schedcheck.yield_point("x.r2", key="x", mode="r")
            state["obs"].append((o1, state["x"][0]))

        return [("t-writer", t_writer), ("t-reader", t_reader)]

    def invariant(self, state, run):
        assert state["obs"][0] != (1, 3), \
            f"mid-update state observed twice: {state['obs']}"


class _ToctouLockBug(Scenario):
    """Check and apply in separate lock crossings, no explicit yield
    point — the branch point is the lock-acquire dependency alone.
    Regression for the DPOR dependency relation: lock RELEASES must not
    participate (a release op's pre-state has only the holder enabled,
    so a release logged as the 'last dependent access' would hide the
    acquire behind it and this bug would never be found)."""

    name = "engine-toctou-lock-bug"

    def setup(self):
        return {"lock": threading.Lock(), "committed": []}

    def threads(self, state):
        def committer(tag):
            def body():
                with state["lock"]:
                    free = not state["committed"]
                if free:
                    with state["lock"]:
                        state["committed"].append(tag)
            return body

        return [("c-a", committer("a")), ("c-b", committer("b"))]

    def invariant(self, state, run):
        assert len(state["committed"]) <= 1, \
            f"both committers won: {state['committed']}"


# -------------------------------------------- 1. DPOR vs naive baselines

def _naive_schedules(per_thread_steps):
    """The baseline schedule families: every one-thread-at-a-time order
    and the strict round-robin, built from {name: step_count}."""
    import itertools
    names = list(per_thread_steps)
    for perm in itertools.permutations(names):
        yield [n for n in perm for _ in range(per_thread_steps[n])]
    remaining = dict(per_thread_steps)
    rr = []
    while any(remaining.values()):
        for n in names:
            if remaining[n]:
                remaining[n] -= 1
                rr.append(n)
    yield rr


def test_dpor_finds_what_naive_schedules_miss():
    # begin + one step per yield point (no step for thread exit)
    counts = {"writer": 5, "pad": 5, "obs": 3}
    for schedule in _naive_schedules(counts):
        run, failure = run_once(_GapBug(), schedule)
        assert failure is None, \
            f"baseline unexpectedly failing ({schedule}): {failure}"
        assert [t for t, _ in run.steps] == schedule
    res = explore(_GapBug())
    assert res.counterexample is not None, \
        "DPOR missed the 3-thread gap bug every baseline also misses"
    assert "window" in res.counterexample.failure


# ------------------------------------------ 2. preemption-bound honesty

def test_preemption_bound_two_finds_depth_two_bug():
    res = explore(_DepthTwoBug(), preemption_bound=2)
    assert res.counterexample is not None
    assert "(1, 3)" in res.counterexample.failure


def test_preemption_bound_one_misses_and_reports():
    res = explore(_DepthTwoBug(), preemption_bound=1)
    assert res.counterexample is None, \
        "a depth-2 bug cannot be reachable under preemption bound 1"
    assert res.bound_pruned > 0, \
        "bounded exploration must REPORT what it pruned, never imply " \
        "the space was covered"
    assert res.ok


def test_unbounded_exploration_reports_no_pruning():
    res = explore(_DepthTwoBug())
    assert res.counterexample is not None
    assert res.bound_pruned == 0


# ------------------------------------------------- 3. replay exactness

def test_counterexample_replays_exact_schedule_and_failure():
    res = explore(_DepthTwoBug())
    ce = res.counterexample
    assert ce is not None
    assert ce.schedule == [t for t, _ in ce.steps]
    reproduced = replay(_DepthTwoBug(), ce)
    assert reproduced == ce.failure
    run, failure = run_once(_DepthTwoBug(), ce.schedule)
    assert failure == ce.failure
    assert run.steps == ce.steps


def test_counterexample_json_round_trip():
    res = explore(_DepthTwoBug())
    ce = res.counterexample
    back = Counterexample.from_json(ce.to_json())
    assert back.scenario == ce.scenario
    assert back.schedule == ce.schedule
    assert back.failure == ce.failure
    assert replay(_DepthTwoBug(), back) == ce.failure


# ------------------------------------- 4. hooks inert outside weave runs

def test_yield_points_are_noops_when_not_exploring():
    assert not schedcheck.active()
    # no run installed: a production yield point is a falsy-global check
    schedcheck.yield_point("anything", obj=object(), mode="w", key="k")


def test_patch_seams_restored_after_explore():
    real_lock_cls = threading.Lock
    real_monotonic = __import__("time").monotonic
    explore(_DepthTwoBug())
    assert threading.Lock is real_lock_cls
    assert __import__("time").monotonic is real_monotonic
    assert not schedcheck.active()


class _UnacquiredCondMisuse(Scenario):
    """Waiting/notifying without holding the lock must raise exactly as
    CPython's threading.Condition does — a scenario that would deadlock
    on the real primitives must not silently 'work' under weave."""

    name = "engine-unacquired-cond"

    def __init__(self, method):
        self._method = method

    def setup(self):
        return {"cond": threading.Condition()}

    def threads(self, state):
        def misuse():
            getattr(state["cond"], self._method)()

        return [("misuser", misuse)]

    def invariant(self, state, run):
        pass


@pytest.mark.parametrize("method", ["wait", "notify"])
def test_weave_condition_matches_cpython_unacquired_semantics(method):
    res = explore(_UnacquiredCondMisuse(method))
    assert res.counterexample is not None
    assert "RuntimeError" in res.counterexample.failure
    assert "un-acquired lock" in res.counterexample.failure


# ------------------------------------------------- DPOR lock regression

def test_dpor_finds_check_apply_split_across_lock_crossings():
    res = explore(_ToctouLockBug())
    assert res.counterexample is not None, \
        "lock-acquire dependencies must seed DPOR branch points " \
        "(release ops are enabledness plumbing, not conflicts)"


# --------------------------------------------------- production matrix

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_production_scenario_holds(name):
    cls = SCENARIOS[name]
    res = explore(cls())
    if not res.ok:
        pytest.fail(f"{name}: {res.counterexample.render()}")
    # the schedule space was either exhausted or bounded ON PURPOSE —
    # a budget exhaustion without a declared preemption bound means the
    # scenario outgrew its budget silently
    assert res.complete or cls.preemption_bound is not None, \
        f"{name}: exploration hit the execution budget " \
        f"({res.executions}) without a declared preemption bound"


@pytest.mark.parametrize("name", sorted(TWINS))
def test_seeded_bug_twin_fires(name):
    cls = TWINS[name]
    res = explore(cls())
    assert res.counterexample is not None, \
        f"{name}: the seeded bug was NOT found — the " \
        f"'{cls.twin_of}' invariant cannot fire"
    # and the find is reproducible, not a fluke of exploration order
    assert replay(cls(), res.counterexample) is not None


def test_every_scenario_has_a_twin():
    covered = {cls.twin_of for cls in TWINS.values()}
    # the two dra scenarios share one protocol checker; the failure-path
    # twin proves the ACK-vs-durability invariant live for both
    uncovered = {
        n for n in SCENARIOS
        if n not in covered and n != "dra-group-commit"}
    assert not uncovered, \
        f"scenarios without a seeded-bug twin: {sorted(uncovered)}"
