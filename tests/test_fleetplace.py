"""Fleet placement control plane tests (ISSUE 14).

Selector-engine edge cases (unknown attribute, type mismatch, empty =
match-all, malformed fails at COMPILE not at match), the cross-host
mesh algebra (pod-grid wrap-around windows), the reflector-fed slice
cache and its published-attribute parser (pinned against the daemon's
REAL build_slice output so publisher and parser cannot drift), the
cluster fragmentation rollup, global defrag waves applied through the
migration-handoff machinery, the cluster-wide exactly-once commit-log
audit (including a scheduler decision replayed under injected faults),
the zero-lock read-path gates for selector evaluation and fleet
accounting, and the flight-recorder span every decision emits.
"""

import os

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import fleetplace, lockdep, placement
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.dra import DraDriver
from tpu_device_plugin.fleetplace import (
    CompiledSelector, FleetScheduler, SelectorError, SliceCache,
    cluster_fragmentation, compile_selector, device_attrs,
    host_views_from_slices)
from tpu_device_plugin.placement import HostView


# ------------------------------------------------------ selector engine


def test_selector_typed_comparisons_and_boolean_ops():
    s = compile_selector(
        'topology.generation == "v5e" && topology.ring_size >= 4')
    assert s.matches({"generation": "v5e", "ringSize": 4})
    assert not s.matches({"generation": "v5e", "ringSize": 2})
    assert not s.matches({"generation": "v4", "ringSize": 8})
    s2 = compile_selector('numaNode != 0 || bdf == "0000:00:04.0"')
    assert s2.matches({"numaNode": 0, "bdf": "0000:00:04.0"})
    assert s2.matches({"numaNode": 1, "bdf": "x"})
    assert not s2.matches({"numaNode": 0, "bdf": "x"})


def test_selector_empty_is_match_all():
    for text in ("", "   ", None):
        s = compile_selector(text)
        assert s.matches({}) and s.matches({"anything": 1})
        assert s.snapshot()["matches_total"] == 2


def test_selector_unknown_attribute_is_no_match_counted():
    s = compile_selector("topology.no_such_attr >= 4")
    assert not s.matches({"ringSize": 8})
    assert s.snapshot()["unknown_attribute_total"] == 1
    # negation of a poisoned predicate is still NO MATCH, not a
    # surprise True (the miss aborts the whole evaluation)
    s2 = compile_selector("!(topology.no_such_attr >= 4)")
    assert not s2.matches({"ringSize": 8})
    assert s2.snapshot()["unknown_attribute_total"] == 1


def test_selector_type_mismatch_is_no_match_counted():
    cases = [
        ('topology.generation >= 4', {"generation": "v5e"}),
        ('topology.ring_size == "v5e"', {"ringSize": 4}),
        ('topology.healthy < true', {"healthy": True}),   # no bool order
        ('topology.ring_size in ["a", "b"]', {"ringSize": 4}),
    ]
    for text, attrs in cases:
        s = compile_selector(text)
        assert not s.matches(attrs), text
        assert s.snapshot()["type_mismatch_total"] == 1, text
    # a bare non-bool operand cannot stand as a predicate
    s = compile_selector("topology.ring_size")
    assert not s.matches({"ringSize": 4})
    assert s.snapshot()["type_mismatch_total"] == 1


def test_selector_short_circuit_never_touches_poisoned_branch():
    s = compile_selector('topology.ring_size >= 4 || missing == 1')
    assert s.matches({"ringSize": 8})          # left True: right unread
    assert s.snapshot()["unknown_attribute_total"] == 0
    s2 = compile_selector('topology.ring_size >= 99 && missing == 1')
    assert not s2.matches({"ringSize": 8})     # left False: right unread
    assert s2.snapshot()["unknown_attribute_total"] == 0


@pytest.mark.parametrize("bad", [
    "topology.generation ==",          # dangling operator
    "(topology.ring_size >= 4",        # unbalanced paren
    "topology.ring_size >= 4)",        # trailing input
    "ring_size ~ 4",                   # unknown operator
    "in [1, 2]",                       # 'in' with no left operand
    'x in [1, "a"]',                   # mixed-type list literal
    "x in [1, 2",                      # unterminated list
    "&& true",                         # operator with no left term
    'x == "unterminated',              # bad string token
])
def test_selector_malformed_fails_at_compile_not_at_match(bad):
    with pytest.raises(SelectorError):
        compile_selector(bad)


def test_selector_membership_bools_and_negation():
    s = compile_selector('topology.generation in ["v5e", "v5p"]')
    assert s.matches({"generation": "v5p"})
    assert not s.matches({"generation": "v4"})
    s2 = compile_selector("!healthy")
    assert s2.matches({"healthy": False})
    assert not s2.matches({"healthy": True})
    s3 = compile_selector("healthy == true")
    assert s3.matches({"healthy": True})


def test_selector_string_escapes_consistent_across_positions():
    """A quoted literal denotes the SAME value in == and in contexts
    (the list-literal position shares the operand's unescape)."""
    attrs = {"hostId": 'a"b', "path": "a\\b"}
    assert compile_selector('host_id == "a\\"b"').matches(attrs)
    assert compile_selector('host_id in ["a\\"b"]').matches(attrs)
    assert compile_selector('path == "a\\\\b"').matches(attrs)
    assert compile_selector('path in ["a\\\\b", "other"]').matches(attrs)


def test_selector_snake_case_resolves_wire_camel_case():
    """Selectors read like specs (`ring_size`); the wire publishes
    camelCase (`ringSize`); both prefixes address the same map."""
    attrs = {"ringSize": 8, "hostId": "n7", "iciX": 1}
    assert compile_selector("topology.ring_size == 8").matches(attrs)
    assert compile_selector('device.host_id == "n7"').matches(attrs)
    assert compile_selector("ici_x == 1").matches(attrs)
    assert compile_selector("ringSize == 8").matches(attrs)


def test_device_attrs_flattens_both_api_shapes():
    v1beta1 = {"name": "d0", "basic": {"attributes": {
        "generation": {"string": "v5e"}, "ringSize": {"int": 4},
        "healthy": {"bool": True}}}}
    flat = {"name": "d0", "attributes": {
        "generation": {"string": "v5e"}, "ringSize": {"int": 4},
        "healthy": {"bool": True}}}
    for entry in (v1beta1, flat):
        attrs = device_attrs(entry)
        assert attrs["generation"] == "v5e"
        assert attrs["ringSize"] == 4
        assert attrs["healthy"] is True
        assert attrs["name"] == "d0"


# -------------------------------------------------- cross-host mesh


def _mesh_view(node, host_coords, dims=(2, 4), occupied=()):
    import itertools
    coords, names = {}, {}
    for c in itertools.product(*[range(d) for d in dims]):
        raw = f"{node}-c" + "-".join(str(x) for x in c)
        coords[raw] = c
        names[raw] = raw
    raw_at = {c: r for r, c in coords.items()}
    claims = {f"{node}-claim-{i}": (raw_at[c],)
              for i, c in enumerate(occupied)}
    held = {r for raws in claims.values() for r in raws}
    return HostView(node=node, dims=dims, coords=coords, names=names,
                    free=frozenset(r for r in coords if r not in held),
                    departed=frozenset(), claims=claims,
                    host_coords=host_coords)


def test_cyclic_cover_wraps_pod_axes():
    assert placement.cyclic_cover([(0, 0), (0, 3)], (4, 4)) == 2
    assert placement.cyclic_cover([(0, 0), (0, 2)], (4, 4)) == 3
    assert placement.cyclic_cover([(0, 0), (3, 0)], (4, 4)) == 2
    assert placement.mesh_score([(0, 0), (0, 3)], (4, 4)) == 1.0
    assert placement.mesh_score([(0, 0), (0, 2)], (4, 4)) < 1.0
    assert placement.mesh_score([(0, 0), None], (4, 4)) == 0.0


def test_multi_host_plan_requires_pod_adjacency():
    """With the pod grid modeled, two fully-free hosts only tile a mesh
    when a wrap-aware host-grid window joins them. A 2x8 slice over
    2x4-host tori on a 1x4 pod row needs two hosts side by side along
    the pod's second axis — including the wrap pair (0,0)+(0,3)."""
    adjacent = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 1))]
    gap = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 2))]
    wrap = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 3))]
    plan = placement.plan_slice((2, 8), adjacent, pod_dims=(1, 4))
    assert plan is not None and plan.score == 1.0 and plan.hosts == 2
    assert placement.plan_slice((2, 8), gap, pod_dims=(1, 4)) is None
    plan_w = placement.plan_slice((2, 8), wrap, pod_dims=(1, 4))
    assert plan_w is not None and plan_w.score == 1.0
    # a 4x4 needs two hosts stacked along pod axis 0 — a 1x4 row has
    # no such link, however free the tori are
    assert placement.plan_slice((4, 4), adjacent,
                                pod_dims=(1, 4)) is None


def test_mesh_scatter_scores_down_non_adjacent_hosts():
    gap = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 2))]
    plan = placement.plan_slice((2, 8), gap, best_effort=True,
                                pod_dims=(1, 4))
    assert plan is not None and plan.hosts == 2
    assert 0.0 < plan.score < 1.0
    assert plan.score == placement.mesh_score([(0, 0), (0, 2)], (1, 4))


def test_coordinate_less_views_legacy_vs_modeled_pod():
    legacy = [_mesh_view("a", None), _mesh_view("b", None)]
    # pod grid unmodeled: inter-host edges unknown, legacy 1.0 holds
    plan = placement.plan_slice((4, 4), legacy)
    assert plan is not None and plan.score == 1.0 and plan.hosts == 2
    # pod grid MODELED: a coordinate-less host cannot prove adjacency,
    # so it never joins a score-1.0 mesh (mid-rollout honesty)
    assert placement.plan_slice((2, 8), legacy, pod_dims=(1, 4)) is None
    # ... and a coordinate-bearing adjacent pair keeps its constraint
    # even when an unrelated host lacks coordinates
    mixed = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 1)),
             _mesh_view("c", None)]
    plan_m = placement.plan_slice((2, 8), mixed, pod_dims=(1, 4))
    assert plan_m is not None and plan_m.score == 1.0
    assert {s[0] for s in plan_m.shards} == {"a", "b"}


def test_rank_mismatched_pod_grid_never_claims_contiguity():
    """A 2-D pod grid over 3-D v4-style host cubes (2x2x1) cannot
    prove adjacency on the missing axis — the generation must form NO
    contiguous multi-host plan rather than silently reverting to the
    legacy any-two-tori-score-1.0 claim."""
    cubes = [_mesh_view("a", (0, 0), dims=(2, 2, 1)),
             _mesh_view("b", (0, 1), dims=(2, 2, 1))]
    # rank-matched pod model: a 3-D pod grid proves the link
    plan = placement.plan_slice((2, 4, 1), cubes, pod_dims=(1, 2, 1))
    assert plan is None   # 2D host_coords don't match the 3D pod
    cubes3d = [_mesh_view("a", (0, 0, 0), dims=(2, 2, 1)),
               _mesh_view("b", (0, 1, 0), dims=(2, 2, 1))]
    plan3 = placement.plan_slice((2, 4, 1), cubes3d,
                                 pod_dims=(1, 2, 1))
    assert plan3 is not None and plan3.score == 1.0
    # rank-MISMATCHED pod model (2-D grid, 3-D hosts): unprovable —
    # no contiguous plan, not a false 1.0
    assert placement.plan_slice((2, 4, 1), cubes,
                                pod_dims=(1, 2)) is None
    assert placement.plan_slice((2, 4, 1), cubes3d,
                                pod_dims=(1, 2)) is None


def test_single_host_plan_still_preferred_over_mesh():
    views = [_mesh_view("a", (0, 0)), _mesh_view("b", (0, 1))]
    plan = placement.plan_slice((2, 2), views, pod_dims=(1, 4))
    assert plan is not None and plan.hosts == 1 and plan.score == 1.0


# ----------------------------------------- slice cache + parsed views


def _slice_obj(node, gen="v5e", dims=(2, 4), host=(0, 0), rv=1):
    import itertools
    devices = []
    for i, c in enumerate(itertools.product(*[range(d) for d in dims])):
        attrs = {
            "type": {"string": "passthrough"},
            "generation": {"string": gen},
            "bdf": {"string": f"0000:00:{4 + i:02x}.0"},
            "ringSize": {"int": max(dims)},
            "hostId": {"string": node},
        }
        for axis, coord in zip("xyz", c):
            attrs[f"ici{axis.upper()}"] = {"int": coord}
        for axis, d in zip("xyz", dims):
            attrs[f"torus{axis.upper()}"] = {"int": d}
        if host is not None:
            for axis, coord in zip("xyz", host):
                attrs[f"host{axis.upper()}"] = {"int": coord}
        devices.append({"name": f"{node}-dev-{i}",
                        "basic": {"attributes": attrs}})
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceSlice",
        "metadata": {"name": f"{node}-slice", "resourceVersion": str(rv)},
        "spec": {"driver": "tpu.example.com", "nodeName": node,
                 "pool": {"name": node, "generation": 1,
                          "resourceSliceCount": 1},
                 "devices": devices},
    }


def test_slice_cache_events_idempotent_and_delete():
    cache = SliceCache()
    cache.on_sync([_slice_obj("n0"), _slice_obj("n1")])
    assert set(cache.snapshot()) == {"n0-slice", "n1-slice"}
    snap_before = cache.snapshot()
    evt = {"type": "MODIFIED", "object": _slice_obj("n0", rv=2)}
    cache.on_event(evt)
    cache.on_event(dict(evt))      # at-least-once duplicate delivery
    assert len(cache.snapshot()) == 2
    # snapshots are immutable swaps: the old one is untouched
    assert snap_before["n0-slice"]["metadata"]["resourceVersion"] == "1"
    cache.on_event({"type": "DELETED", "object": _slice_obj("n0")})
    assert set(cache.snapshot()) == {"n1-slice"}
    cache.on_event({"type": "DELETED", "object": _slice_obj("n0")})
    assert set(cache.snapshot()) == {"n1-slice"}


def test_host_views_from_slices_rebuild_grids_and_ledger():
    slices = {s["metadata"]["name"]: s
              for s in (_slice_obj("n0", host=(0, 0)),
                        _slice_obj("n1", host=(0, 1)))}
    claims = {"u1": (("u1-n0", "n0",
                      ("0000:00:04.0", "0000:00:05.0")),)}
    views, attrs_index = host_views_from_slices(slices, claims)
    assert set(views) == {"v5e"}
    by_node = {v.node: v for v in views["v5e"]}
    assert by_node["n0"].dims == (2, 4)
    assert by_node["n0"].host_coords == (0, 0)
    assert by_node["n1"].host_coords == (0, 1)
    assert len(by_node["n0"].free) == 6          # 8 - 2 claimed
    # claims keyed by the NODE-LEVEL sub-claim id — the id the node
    # driver's checkpoint holds, so defrag advisories name claims the
    # handoff machinery can really unprepare
    assert by_node["n0"].claims["u1-n0"] == \
        ("0000:00:04.0", "0000:00:05.0")
    # the SAME bdfs on n1 stay free: the ledger is (node, bdf)-keyed
    assert len(by_node["n1"].free) == 8
    assert attrs_index[("n0", "v5e")]["0000:00:04.0"]["ringSize"] == 4


def test_published_slice_parses_back_to_the_drivers_own_view(short_root):
    """THE anti-drift pin: the daemon's real build_slice output —
    topology attributes and all (the ISSUE 14 satellite) — parses back
    into exactly the host view the driver computes locally."""
    from dataclasses import replace as dc_replace
    host = FakeHost(short_root)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 4))
    cfg = dc_replace(Config().with_root(host.root), host_coords=(1, 2))
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="pub-n")
    try:
        obj = driver.build_slice()
        # satellite: every chip entry publishes coords, torus dims,
        # generation, ring/host ids, pod-grid slot
        for entry in obj["spec"]["devices"]:
            attrs = device_attrs(entry)
            assert attrs["generation"] == "v5e"
            assert (attrs["torusX"], attrs["torusY"]) == (2, 4)
            assert attrs["ringSize"] == 4
            assert attrs["hostId"] == "pub-n"
            assert attrs["ringId"].startswith("pub-n/v5e/")
            assert (attrs["hostX"], attrs["hostY"]) == (1, 2)
            assert "iciX" in attrs and "iciY" in attrs
        views, _idx = host_views_from_slices(
            {obj["metadata"]["name"]: obj}, {})
        parsed = views["v5e"][0]
        local = driver.host_views()["v5e"]
        assert parsed.dims == local.dims
        assert dict(parsed.coords) == dict(local.coords)
        assert parsed.free == local.free
        assert parsed.host_coords == (1, 2)
        # a selector can address the published fields
        sel = compile_selector(
            'topology.generation == "v5e" && topology.ring_size >= 4 '
            '&& topology.host_id == "pub-n"')
        idx = {e["name"]: device_attrs(e) for e in obj["spec"]["devices"]}
        assert all(sel.matches(a) for a in idx.values())
    finally:
        driver.stop()


# ------------------------------------------- cluster fragmentation


def test_cluster_fragmentation_rolls_up_hosts_and_meshes():
    views = {"v5e": [
        _mesh_view("a", (0, 0)),                       # fully free
        _mesh_view("b", (0, 1)),                       # fully free
        _mesh_view("c", (1, 0), occupied=[(0, 0)]),    # 7 free
        _mesh_view("d", (1, 1), occupied=[(0, 1), (1, 2)]),
    ]}
    roll = cluster_fragmentation(views, pod_dims=(2, 2))["v5e"]
    assert roll["hosts"] == 4 and roll["chips"] == 32
    assert roll["free"] == 8 + 8 + 7 + 6
    assert roll["fully_free_hosts"] == 2
    assert roll["largest_free_box"] == 8
    assert roll["largest_free_mesh"] == 16       # a+b adjacent windows
    assert 0.0 < roll["fragmentation"] < 1.0
    assert roll["fragmentation"] == round(1.0 - 16 / 29, 4)
    # without the pod grid the mesh term vanishes
    roll2 = cluster_fragmentation(views)["v5e"]
    assert roll2["largest_free_mesh"] == 0
    assert roll2["fragmentation"] == round(1.0 - 8 / 29, 4)


# ------------------------------------------------- scheduler (fleetsim)


@pytest.fixture()
def fleet():
    from tpu_device_plugin.fleetsim import FleetSim
    sim = FleetSim(n_nodes=4, devices_per_node=8, latency_s=0.0,
                   max_inflight=0, seed=14)
    for node in sim.nodes:
        node.driver.publish_resource_slices()
    yield sim
    sim.stop()


def _release_all(sched, sim):
    for uid in list(sched._claims):
        sched.release(uid)


def test_scheduler_selector_filtering_and_decisions(fleet):
    sched = fleet.scheduler(watch=False)
    res = sched.schedule(
        "2x2", "sel-1",
        selector='topology.generation == "v5e" && topology.ring_size >= 4')
    assert res["placed"] and res["score"] == 1.0
    miss = sched.schedule("2x2", "sel-2",
                          selector='topology.generation == "v4"')
    assert not miss["placed"] and miss["reason"] == "unplaceable"
    with pytest.raises(SelectorError):
        sched.schedule("2x2", "sel-3", selector="topology.generation ==")
    assert sched.snapshot()["selector_compile_errors_total"] == 1
    # compile-once: the selector cache holds one entry per text
    assert sched.selector('topology.generation == "v4"') is \
        sched.selector('topology.generation == "v4"')
    audit = sched.audit(fabric_audit=fleet.apiserver.multiclaim_audit())
    assert audit["exactly_once"]
    _release_all(sched, fleet)


def test_scheduler_cross_host_mesh_through_watch_cache(fleet):
    """Decisions consume the PR 12 Reflector's slice cache: LIST seeds
    it, the published topology attributes rebuild the grids, and a
    cross-host mesh claim commits through the multiclaim fabric."""
    sched = fleet.scheduler(watch=True, resync_s=1.0)
    sched.start()
    try:
        assert sched.wait_synced(timeout_s=15, min_slices=4)
        res = sched.schedule("4x4", "mesh-1")
        assert res["placed"] and res["score"] == 1.0 and res["hosts"] == 2
        nodes = [n for n, _raws in res["shards"]]
        coords = {node.name: node.cfg.host_coords
                  for node in fleet.nodes}
        assert placement.mesh_score(
            [coords[n] for n in nodes], fleet.pod_dims) == 1.0
        audit = sched.audit(
            fabric_audit=fleet.apiserver.multiclaim_audit())
        assert audit["exactly_once"] and audit["fabric_agrees"]
        assert sched.release("mesh-1")
    finally:
        sched.stop()


def test_scheduler_decision_replayed_under_faults_exactly_once(fleet):
    """The ISSUE 14 convergence pin: a scheduler decision whose shard
    prepare dies on an injected checkpoint.write fault rolls back
    cleanly (no residue), and the REPLAYED decision converges with
    exactly ONE commit on the cluster-wide log — fabric cross-check
    included."""
    from tpu_device_plugin import faults
    sched = fleet.scheduler(watch=False)
    faults.arm("checkpoint.write", kind="error", count=1)
    try:
        res = sched.schedule("4x4", "replay-1")
        assert not res["placed"] and res.get("rolled_back")
        assert fleet.slice_residue("replay-1") == []
    finally:
        faults.disarm("checkpoint.write")
    res2 = sched.schedule("4x4", "replay-1")
    assert res2["placed"]
    audit = sched.audit(fabric_audit=fleet.apiserver.multiclaim_audit())
    assert audit["exactly_once"], audit
    assert audit["committed"].count("replay-1") == 1
    entries = [k for k, uid, _d in sched._log if uid == "replay-1"]
    assert entries.count("committed") == 1
    assert entries.count("aborted") == 1
    assert sched.release("replay-1")


def test_defrag_wave_applied_node_by_node_via_handoff(fleet):
    """Global wave: checkerboard one host so a 2x2 is unplaceable,
    plan the wave over EVERY host's view, apply it node-by-node through
    the PR 7 handoff machinery, and verify placeability flips."""
    sched = fleet.scheduler(watch=False)
    node = fleet.nodes[0]
    view = node.host_view()
    raw_at = {c: r for r, c in view.coords.items()}
    # occupy the rest of the fleet so the wave must work on node 0
    blockers = []
    for i, other in enumerate(fleet.nodes[1:]):
        uid = f"wavefill-{i}"
        other.claim_devices(uid, sorted(other.host_view().free))
        blockers.append(uid)
    for i, c in enumerate([(0, 1), (1, 0), (0, 3), (1, 2)]):
        node.claim_devices(f"wave-claim-{i}", [raw_at[c]])
    handoffs_before = sum(
        n.driver.handoff_stats["handoffs_completed_total"]
        for n in fleet.nodes)
    proposal = sched.plan_defrag_wave("2x2")
    assert not proposal["placeable"] and proposal["satisfiable"]
    assert proposal["moves"] >= 1
    assert proposal["cluster_fragmentation"]["fragmentation"] > 0
    report = sched.apply_defrag_wave(proposal)
    assert report["moves_applied"] == report["moves_planned"] >= 1
    handoffs_after = sum(
        n.driver.handoff_stats["handoffs_completed_total"]
        for n in fleet.nodes)
    assert handoffs_after - handoffs_before == report["moves_applied"]
    views, _ = sched.views_by_generation()
    plan = placement.plan_slice((2, 2), views["v5e"])
    assert plan is not None and plan.score == 1.0
    assert sched.snapshot()["defrag_moves_total"] >= 1
    audit = sched.audit()
    assert audit["exactly_once"]
    # unknown generation = typed 400-shaped error
    with pytest.raises(ValueError):
        sched.plan_defrag_wave("2x2", generation="nope")
    # cleanup for the module-scoped fleet
    for i in range(4):
        node.detach([f"wave-claim-{i}"])
    for i, other in enumerate(fleet.nodes[1:]):
        other.detach([f"wavefill-{i}"])


def test_defrag_migrates_scheduler_claims_then_release_clean(fleet):
    """The claim-uid plane regression (review finding): a defrag wave
    migrating SCHEDULER-placed claims in cache mode must unprepare the
    real node-level sub-claims (not phantom parent uids), re-point the
    ledger, and a later release of the migrated tenant must leave ZERO
    residue anywhere — node checkpoints, CDI dirs, fabric records."""
    sched = fleet.scheduler(watch=True, resync_s=1.0)
    sched.start()
    try:
        assert sched.wait_synced(timeout_s=15, min_slices=4)
        # fill three hosts through the scheduler; pack the fourth with
        # eight single-chip tenants, then release a checkerboard of
        # them so a 2x2 is unplaceable-but-satisfiable there
        for i in range(3):
            assert sched.schedule("2x4", f"mig-fill-{i}")["placed"]
        singles = []
        for i in range(8):
            res = sched.schedule("1", f"mig-one-{i}")
            assert res["placed"], res
            singles.append((f"mig-one-{i}", res["shards"]))
        board_node = singles[0][1][0][0]
        coords_of = {}
        for uid, shards in singles:
            node_name, raws = shards[0]
            assert node_name == board_node   # pristine-avoid packs one
            view = next(v for v in sched.views_by_generation()[0]["v5e"]
                        if v.node == board_node)
            coords_of[uid] = view.coords[raws[0]]
        checker = {(0, 0), (0, 2), (1, 1), (1, 3)}
        for uid, c in coords_of.items():
            if c in checker:
                assert sched.release(uid)
        plan = placement.plan_slice(
            (2, 2), sched.views_by_generation()[0]["v5e"])
        assert plan is None
        prop = sched.plan_defrag_wave("2x2")
        assert not prop["placeable"] and prop["satisfiable"]
        assert prop["moves"] >= 1
        # every named migration is a node-level claim id the board
        # node's checkpoint really holds
        for mig in prop["migrations"]:
            assert mig["claim"].startswith("mig-one-")
        report = sched.apply_defrag_wave(prop)
        assert report["moves_applied"] == report["moves_planned"]
        plan2 = placement.plan_slice(
            (2, 2), sched.views_by_generation()[0]["v5e"])
        assert plan2 is not None and plan2.score == 1.0
        # release EVERY remaining tenant — including migrated ones —
        # then prove nothing is left anywhere
        for uid in list(sched._claims):
            assert sched.release(uid), uid
        for node in fleet.nodes:
            assert node.driver.prepared_claim_count() == 0, node.name
        with fleet.apiserver._lock:
            assert not fleet.apiserver.claims
        audit = sched.audit(
            fabric_audit=fleet.apiserver.multiclaim_audit())
        assert audit["exactly_once"], audit
    finally:
        sched.stop()


# --------------------------------------------- zero-lock read gates


def test_selector_and_fleet_accounting_reads_acquire_zero_locks():
    """THE ISSUE 14 read-path gate: selector evaluation and fleet
    accounting run on lock-free snapshots — counted by lockdep proxies
    inside the `fleetplace.select` / `fleetplace.frag` brackets."""
    with lockdep.scoped():
        cache = SliceCache()
        cache.on_sync([_slice_obj("n0", host=(0, 0)),
                       _slice_obj("n1", host=(0, 1))])
        sched = FleetScheduler(cache=cache, pod_dims=(1, 2))
        sel = 'topology.generation == "v5e" && topology.ring_size >= 4'
        sched.selector(sel)         # compile outside the measured reads
        lockdep.reset()
        for _ in range(5):
            views, _c = sched.eligible_views(sel)
            assert len(views) == 2
            frag = sched.fragmentation()
            assert frag["v5e"]["free"] == 16
        stats = lockdep.path_stats()
        for path in ("fleetplace.select", "fleetplace.frag"):
            rec = stats[path]
            assert rec["calls"] >= 5, stats
            assert rec["lock_acquisitions"] == 0, \
                f"{path} acquired {rec['lock_acquisitions']} locks"


def test_schedule_decisions_are_flight_recorder_spans():
    from tpu_device_plugin import trace
    cache = SliceCache()
    cache.on_sync([_slice_obj("n0", host=(0, 0))])
    sched = FleetScheduler(cache=cache, pod_dims=(1, 1))
    res = sched.schedule("2x2", "span-claim-1")
    assert res["placed"] and res.get("advisory")   # plan-only mode
    spans = trace.snapshot(claim="span-claim-1")
    assert any(s["op"] == "fleetplace.schedule" for s in spans)


def test_fleet_trace_waterfall_scheduler_to_migrated_shard(fleet):
    """ACCEPTANCE (ISSUE 15, small-N live half): ONE trace= query over
    the fleet flight collector reconstructs a scheduler-placed claim's
    full waterfall — scheduler decision → per-shard prepare (with its
    broker crossing) → migration handoff → destination prepare — across
    3+ nodes, purely from the /debug/fleet/trace body shape."""
    from tpu_device_plugin import trace
    sim = fleet
    sched = sim.scheduler(watch=False)
    res = sched.schedule("1x2", "wf-claim")
    assert res["placed"]
    tid = res["trace_id"]
    assert tid and len(tid) == 32
    # migrate the shard to ANOTHER host via the handoff machinery
    sub_uid, node_name, raws = list(sched._claims["wf-claim"])[0]
    by_name = sim._node_by_name()
    src = by_name[node_name]
    dst = next(n for n in sim.nodes if n.name != node_name
               and len(n.host_view().free) >= len(raws))
    sched.apply_defrag_wave({"migrations": [{
        "claim": sub_uid, "source_node": src.name,
        "target_node": dst.name, "devices": list(raws),
        "target_devices": sorted(dst.host_view().free)[:len(raws)]}]})
    story = sim.fleet_flight().trace(tid)
    assert story["trace"] == tid
    ops = set(story["ops"])
    for needed in ("fleetplace.schedule", "dra.prepare.claim",
                   "broker.ipc", "dra.unprepare.claim",
                   "dra.handoff.completed"):
        assert needed in ops, (needed, sorted(ops))
    # scheduler + source host + destination host all answer
    assert {"scheduler", src.name, dst.name} <= set(story["nodes"])
    # time-ordered: the decision precedes every shard span
    ts = [r["ts"] for r in story["spans"]]
    assert ts == sorted(ts)
    by_op = {r["op"]: i for i, r in enumerate(story["spans"])}
    assert by_op["fleetplace.schedule"] <= by_op["dra.prepare.claim"]
    # the unprepare/destination-prepare joined via LINKS (their own
    # trace ids differ; the link carries tid)
    unprep = [r for r in story["spans"]
              if r["op"] == "dra.unprepare.claim"]
    assert unprep and unprep[-1]["link"]["trace_id"] == tid
    # the fabric's multiclaim record names the decision's trace
    with sim.apiserver._lock:
        rec = sim.apiserver.multiclaims["wf-claim"]
    assert trace.parse_traceparent(rec["traceparent"])["trace_id"] == tid
    _release_all(sched, sim)


def test_schedule_returns_trace_id_even_when_unplaceable(fleet):
    sched = fleet.scheduler(watch=False)
    res = sched.schedule("64x64", "huge-claim")
    assert not res["placed"]
    assert res["trace_id"] and len(res["trace_id"]) == 32


def test_audit_detects_seeded_violations():
    cache = SliceCache()
    sched = FleetScheduler(cache=cache)
    # duplicated commit
    sched._note("decided", "dup", None)
    sched._note("committed", "dup", None)
    sched._note("committed", "dup", None)
    # commit with no decision
    sched._note("committed", "ghost", None)
    # dirty abort: a prepared shard never rolled back
    sched._note("decided", "dirty", None)
    sched._note("shard_prepared", "dirty", "dirty-n0")
    sched._note("aborted", "dirty", "boom")
    audit = sched.audit()
    assert not audit["exactly_once"]
    assert audit["duplicated_commits"] == ["dup"]
    assert audit["undecided_commits"] == ["ghost"]
    assert audit["dirty_aborts"] == ["dirty"]
    # fabric disagreement surfaces
    audit2 = sched.audit(fabric_audit={"exactly_once": True,
                                       "committed": ["other"]})
    assert not audit2["fabric_agrees"]
    assert "dup" in audit2["scheduler_only"]
    assert audit2["fabric_only"] == ["other"]
