"""TpuDevicePlugin — one gRPC plugin server per advertised resource.

TPU analogue of the reference's `GenericDevicePlugin`
(generic_device_plugin.go:72-690): serves the five DevicePlugin RPCs on a
unix socket under the kubelet's device-plugin dir, registers with the
kubelet, streams device health over ListAndWatch, and restarts itself when
the kubelet wipes its socket dir. Differences by design:

- health events flow through immutable copy-on-write epochs (epoch.py):
  the writer publishes a frozen device table + pre-serialized
  ListAndWatch payload with one atomic reference swap, readers never
  lock (the reference's unbuffered channels can deadlock healthCheck
  when ListAndWatch is gone, SURVEY.md §7e);
- `restart()` builds a fresh stop event per Start, so a restart never
  orphans a shared stop channel (ibid.);
- GetPreferredAllocation is ICI-topology aware (topology.py).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from concurrent import futures
from datetime import datetime, timezone
from typing import Dict, List, Optional, Sequence, Tuple

import grpc

from . import allocate as allocate_mod
from . import broker as broker_mod
from . import epoch as epoch_mod
from . import faults
from . import kubeletapi as api
from . import lockdep
from . import placement
from . import trace
from .config import Config
from .log import get_logger
from .healthhub import HealthHub, HubSubscription
from .kubeletapi import pb
from .native import TpuHealth, link_is_degraded
from .registry import Registry, TpuDevice
from .resilience import BackoffPolicy
from .topology import AllocatableDevice, AllocationIndex, MustIncludeTooLarge

log = get_logger(__name__)

# GetPreferredAllocation memo capacity (see _pref_cache): the memo is a
# per-epoch plain dict (swapped wholesale on every epoch publish, so
# invalidation is by construction and lookups take no lock); at capacity
# new keys recompute instead of evicting — the scan is a pure ~12 us
# fallback, and a bounded no-evict dict is the only shape that stays
# GIL-atomic without a lock.
PREF_CACHE_SIZE = 128


def _invocation_link(context) -> Optional[str]:
    """The caller's ``traceparent`` from gRPC invocation metadata, if it
    sent one (r17): an instrumented kubelet — or the fleet scheduler
    driving the servicer surface — joins the daemon's trace instead of
    the daemon minting a parallel one. None for direct in-process calls
    (context=None) and metadata-less callers; malformed values are
    counted dropped at link-coercion time, never raised into the RPC."""
    meta = getattr(context, "invocation_metadata", None)
    if meta is None:
        return None
    try:
        pairs = meta()
    except Exception:
        return None
    for key, value in pairs or ():
        if key == "traceparent":
            return value
    return None
# Starvation cap for the ListAndWatch coalesce window: a relentless flap
# storm may never produce a quiet window, so after this many windows of
# deferral the current state is sent anyway (the trailing edge still
# re-sends the final state afterwards).
LW_MAX_DEFER_WINDOWS = 10

# Channel/server options for the loopback-unix-socket regime the kubelet
# actually talks to (round 15, transport endgame): bias for latency over
# throughput, and drop the BDP probe — bandwidth estimation is WAN
# machinery, and on a loopback unix socket it only adds ping traffic the
# small unary attach responses then queue behind. Shared by the serving
# side, the self-dial readiness probe, and the bench rig (bench.py),
# which must measure the production configuration.
LOOPBACK_GRPC_OPTIONS = (
    ("grpc.optimization_target", "latency"),
    ("grpc.http2.bdp_probe", 0),
)


class RegistrationError(Exception):
    """register() failed. Subclasses tell callers whether the failure is
    the expected boot race (kubelet socket not up yet — retry quietly) or
    a protocol-level rejection (version mismatch, bad resource name —
    retrying without a fix is futile and the log should say so)."""


class KubeletUnavailable(RegistrationError):
    """The kubelet did not answer: socket missing, dial timeout, or
    UNAVAILABLE/DEADLINE_EXCEEDED from the transport."""


class RegistrationRejected(RegistrationError):
    """The kubelet answered and refused the registration (e.g. version
    mismatch) — a retry will fail the same way until something changes."""


class TpuDevicePlugin(api.DevicePluginServicer):
    """Passthrough plugin server for one TPU generation/model."""

    def __init__(
        self,
        cfg: Config,
        resource_suffix: str,
        registry: Registry,
        devices: Sequence[TpuDevice],
        torus_dims: Optional[Tuple[int, ...]] = None,
        health_shim: Optional[TpuHealth] = None,
        cdi_enabled: bool = False,
        health_listener=None,
        health_hub: Optional[HealthHub] = None,
        lifecycle=None,
        policy=None,
        remediation=None,
        byte_plane: bool = True,
    ) -> None:
        # arm-time validation, matching faults.py's fail-loud convention: a
        # NaN window makes every condvar timeout comparison silently false
        # and a negative one raises deep inside a stream thread mid-flap —
        # refuse to build the server instead
        debounce = cfg.lw_debounce_s
        if not isinstance(debounce, (int, float)) or math.isnan(debounce) \
                or math.isinf(debounce) or debounce < 0:
            raise ValueError(
                f"lw_debounce_s must be a finite number >= 0, got "
                f"{debounce!r}")
        self.cfg = cfg
        # Optional observer called with {device_id: effective_health} on
        # every EFFECTIVE transition (after the ANDed-sources verdict flips),
        # outside the device-table lock. The DRA driver subscribes here so a
        # dead chip leaves the published ResourceSlice on the same event
        # that marks it Unhealthy on the ListAndWatch stream — without a
        # second, driftable health watcher.
        self._health_listener = health_listener
        # Optional lifecycle_fsm.DeviceLifecycle: successful Allocates
        # mark their devices allocated. The mark is a single C-atomic
        # deque append (note_allocation_event) — the Allocate read-path
        # gate stays at zero registered-lock acquisitions.
        self._lifecycle = lifecycle
        # Optional policy.PolicyEngine (operator hooks): None (the
        # default, and what the zero-lock gates run against) costs every
        # consultation one attribute check. With hooks loaded, scoring/
        # health/admission decisions consult operator code under the
        # engine's deadline + breaker containment.
        self._policy = policy
        # Optional remediation.RemediationEngine: while the self-heal
        # plane has an admission throttle armed (burning attach/prepare
        # SLO), Allocates above the shed rate get a typed
        # RESOURCE_EXHAUSTED — counted, never a silent drop. The
        # unarmed fast path is one attribute read.
        self._remediation = remediation
        # serializes listener deliveries; see set_devices_health
        self._listener_lock = lockdep.instrument(
            "server.TpuDevicePlugin._listener_lock", threading.Lock())
        # CDI names are only valid when this resource's spec file was written
        self.cdi_enabled = cdi_enabled
        self.resource_suffix = resource_suffix
        self.resource_name = f"{cfg.resource_namespace}/{resource_suffix}"
        self.registry = registry
        self.devices = list(devices)
        self.torus_dims = torus_dims
        self.health_shim = health_shim or TpuHealth(cfg.native_lib_path)
        self.socket_path = os.path.join(
            cfg.device_plugin_path, f"{cfg.socket_prefix}-{resource_suffix}.sock")

        # The read plane (epoch.py): readers — Allocate,
        # GetPreferredAllocation, ListAndWatch assembly, /status — grab
        # `self._store.current` and never lock; the store's internal
        # condition is the WRITER lock (health/table updates) and the
        # channel ListAndWatch waiters park on. `_health_sources` is
        # writer-owned state (mutated only under store.lock()).
        self._store = epoch_mod.EpochStore()
        self._health_sources: Dict[str, Dict[str, bool]] = {}
        self._server: Optional[grpc.Server] = None
        # Shared health plane: the PluginManager passes the host-level hub
        # (one inotify fd + one probe scheduler for every resource); a
        # standalone plugin (tests, bench) lazily builds a private hub so
        # the code path is identical either way.
        self._health_hub = health_hub
        self._own_hub: Optional[HealthHub] = None
        self._health_sub: Optional[HubSubscription] = None
        self._stop = threading.Event()
        self._closed = threading.Event()   # terminal stop(); restarts must abort
        self._lifecycle_lock = lockdep.instrument(
            "server.TpuDevicePlugin._lifecycle_lock",
            threading.RLock())  # serializes start/teardown
        # the in-flight socket-loss restart thread (at most one matters: a
        # newer restart superseding an older one re-points this); joined
        # with a timeout by stop() so a terminal stop leaves no runner
        self._restart_thread: Optional[threading.Thread] = None
        self._serving = False
        self._restart_count = 0
        # shared restart backoff (decorrelated jitter): N plugins bounced by
        # one kubelet restart must not re-dial in lockstep. Reset at the top
        # of each restart() so the first retry is always near base; chaos
        # tests swap in a seeded/faster policy before injecting storms.
        self._restart_backoff = BackoffPolicy(base_s=1.0, cap_s=30.0)
        self._allocatable = [
            AllocatableDevice(d.bdf, d.numa_node, d.ici_coords)
            for d in self.devices
        ]
        # device set + torus are fixed for this server's lifetime, so the
        # box-membership precompute happens once, not per RPC
        self._alloc_index = AllocationIndex(self._allocatable,
                                            torus_dims=self.torus_dims)
        self._allowed_bdfs = frozenset(d.bdf for d in self.devices)
        # per-(cfg, registry, resource) precomputation for the Allocate hot
        # path; rebuilt with the server on every rediscovery restart.
        # byte_records rides the byte_plane knob: the A/B/escape-hatch
        # message path must not build (or ledger) records it never serves
        self._planner = allocate_mod.AllocationPlanner(
            cfg, registry, resource_suffix,
            allowed_bdfs=self._allowed_bdfs, cdi_enabled=cdi_enabled,
            byte_records=byte_plane)
        # last few successful allocations, surfaced on /status for debugging
        # VMI attach issues (what was handed out, when); deque appends are
        # C-atomic, so the hot path records without a lock
        self._recent_allocs: deque = deque(maxlen=16)
        self._alloc_count = epoch_mod.AtomicCounter()
        # The response byte plane (round 15): hot RPC answers (Allocate +
        # GetPreferredAllocation) served from pre-serialized epoch-keyed
        # bytes vs response-plane protobuf serializations actually paid.
        # The serializations counter is SHARED with the planner (fragment
        # segment builds count on the same ledger); both are lock-free
        # owned (AtomicCounter) — the zero-lock gate covers them.
        # `byte_plane=False` restores the build-protos-per-call path
        # through the SAME handlers — the bench's interleaved A/B arm
        # and an operator escape hatch, never the production default.
        self._byte_plane = byte_plane
        self._alloc_bytes_reused = epoch_mod.AtomicCounter()
        self._alloc_serializations = self._planner.serializations
        # long-lived self-dial channel (round 15 satellite): restart
        # storms used to pay a fresh grpc channel setup per readiness
        # probe; one channel per socket path is kept and re-used across
        # restarts (gRPC re-dials the same unix target), closed only by
        # the terminal stop(). (path, channel); replaced if the socket
        # path changes (the vTPU subclass re-points it post-construction).
        self._self_dial: Optional[Tuple[str, grpc.Channel]] = None
        self._self_dial_reuses = epoch_mod.AtomicCounter()
        # Memo for the GetPreferredAllocation box scan (see handler): a
        # plain dict the WRITER swaps wholesale on every epoch publish, so
        # a lookup is one GIL-atomic dict.get and invalidation is by
        # construction — the old LRU + lock + version key are gone. Keys
        # still carry the epoch id (belt and braces for a reader racing
        # the swap). Invariant: the scan result depends on (availability,
        # must-include, size) over a static torus, never health, so a
        # stale hit is impossible even across the swap.
        # value = (ids, serialized container-response record | None):
        # round 15 caches the BUILTIN answer's bytes next to the ids
        # (None when byte_plane is off — the record is never built)
        self._pref_cache: Dict[tuple, Tuple[list, Optional[bytes]]] = {}
        self._pref_hits = epoch_mod.AtomicCounter()
        self._pref_misses = epoch_mod.AtomicCounter()
        # ListAndWatch re-sends since start (initial snapshots excluded):
        # the observable cost of health churn on the kubelet stream
        self._lw_resends = epoch_mod.AtomicCounter()
        # ICI placement scoring of every GetPreferredAllocation answer
        # (placement.selection_score): counter + last-score attr are
        # lock-free owned (AtomicCounter / single attribute store), and
        # the scoring itself runs inside the `placement.score` read-path
        # bracket the zero-lock gate pins (tests/test_epoch.py)
        self._placement_scored = epoch_mod.AtomicCounter()
        self._last_placement_score = 0.0
        # Epoch (and pre-serialized ListAndWatch payload) builds since
        # start: the scale-honesty counter. A health flip of SOME OTHER
        # resource must never bump this — untouched resources keep their
        # epoch (and its payload bytes) by identity; at 4096 devices a
        # spurious rebuild is a multi-ms serialize the flip did not need
        # (pinned by tests/test_epoch.py + bench.py --scale).
        self._epoch_builds = epoch_mod.AtomicCounter()
        # /status diagnostics cache: (monotonic ts, errors, degraded) —
        # one attribute store, read lock-free (cfg.diagnostics_ttl_s > 0
        # serves repeat scrapes without re-reading 2 sysfs files per
        # device; 0 = always live)
        self._diag_cache: Optional[Tuple[float, dict, dict]] = None
        self._build_device_table()

    # ------------------------------------------------------------------ state

    def _device_rows(self) -> Tuple[Tuple[str, int], ...]:
        """The static (device id, NUMA node) table the epoch builder
        renders; fixed for this server's lifetime (rediscovery rebuilds
        the server). The vTPU subclass rows its partitions instead."""
        return tuple((d.bdf, d.numa_node) for d in self.devices)

    def _build_device_table(self) -> None:
        self._rows = self._device_rows()
        self._row_ids = frozenset(dev_id for dev_id, _ in self._rows)
        # a rebuilt table retires the diagnostics cache: a departed
        # device's latched error bits must not be served (nor a
        # readmitted device's fresh ones hidden) for up to a TTL
        self._diag_cache = None
        with self._store.lock():
            self._publish_epoch_locked()

    def _publish_epoch_locked(self) -> epoch_mod.Epoch:
        """Build + publish the next epoch from the writer-owned state
        (caller holds store.lock()). Also swaps in a fresh pref memo —
        the epoch-id key makes stale hits impossible, the swap just stops
        dead entries from pinning the cap."""
        self._epoch_builds.add()
        ep = self._store.publish_locked(epoch_mod.build_server_epoch(
            self._store.current.epoch_id + 1, self._rows,
            self._health_sources))
        self._pref_cache = {}
        return ep

    def set_group_health(self, group: str, healthy: bool, source: str = "fs") -> None:
        """Fan a group-level event out to every member device (reference :664-676)."""
        members = [d.bdf for d in self.registry.iommu_map.get(group, ())]
        self.set_devices_health(members, healthy, source)

    def set_all_health(self, healthy: bool, source: str) -> None:
        """One source's verdict for every advertised device (drain path)."""
        self.set_devices_health(list(self._row_ids), healthy, source)

    def set_devices_health(self, device_ids: Sequence[str], healthy: bool,
                           source: str = "fs") -> None:
        """Record one source's verdict (after any policy override); a
        device is Healthy iff ALL sources agree. Policy health-verdict
        hooks run HERE — before the store lock, never under it — so a
        slow operator hook can delay this delivery but can never stall
        parked ListAndWatch waiters."""
        engine = self._policy
        if engine is not None and engine.has_hook("health_verdict"):
            flipped = [i for i in device_ids
                       if engine.health_verdict(i, healthy, source)
                       != healthy]
            if flipped:
                gone = set(flipped)
                self._apply_devices_health(flipped, not healthy, source)
                device_ids = [i for i in device_ids if i not in gone]
        self._apply_devices_health(device_ids, healthy, source)

    def _apply_devices_health(self, device_ids: Sequence[str],
                              healthy: bool, source: str) -> None:
        """The policy-free writer body of set_devices_health.

        Health has two independent observers — the filesystem watcher and the
        native liveness probe — that see different failure modes (a removed
        vfio node is invisible to a config-space read and vice versa), so
        their verdicts are ANDed rather than last-writer-wins.

        This is the WRITER side of the epoch contract: the per-source map
        mutates under store.lock(), and an EFFECTIVE verdict flip publishes
        one new epoch (readers switch on the atomic swap; ListAndWatch
        waiters observe the epoch id change). A delivery that flips no
        effective verdict — probe polls re-deliver every id each cycle —
        publishes nothing and costs readers nothing.
        """
        touched = []
        with self._store.lock():
            prev = self._store.current.device_health
            changed = False
            for dev_id in device_ids:
                if dev_id not in self._row_ids:
                    continue
                touched.append(dev_id)
                sources = self._health_sources.setdefault(dev_id, {})
                sources[source] = healthy
                state = api.HEALTHY if all(sources.values()) \
                    else api.UNHEALTHY
                if prev.get(dev_id) != state:
                    changed = True
            if changed:
                self._publish_epoch_locked()
        if touched and self._health_listener is not None:
            # Outside the store lock: the listener may do slow work (the
            # DRA driver republishes over HTTP) and must never stall
            # ListAndWatch wakeups. Deliveries are serialized under
            # _listener_lock and re-read the CURRENT effective health
            # inside it — sending the per-call delta instead would let two
            # racing verdicts arrive out of order and leave the listener's
            # state permanently inverted vs the device table. Every
            # touched id is delivered (not just effective transitions): a
            # plugin rebuilt on rediscovery starts all-HEALTHY, so a chip
            # that recovered while pruned produces NO transition on the
            # first probe poll — only the unconditional snapshot
            # reconciles the listener. The listener treats repeats as
            # no-ops.
            with self._listener_lock:
                health = self._store.current.device_health
                current = {i: health[i] == api.HEALTHY
                           for i in touched if i in health}
                try:
                    self._health_listener(current)
                except Exception as exc:
                    log.error("health listener failed: %s", exc)

    # -------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Serve + self-dial readiness + register + health watch (reference :216-256).

        Exception-safe: a failure after the gRPC server came up (e.g. the
        kubelet socket is not there yet) tears the server and socket back
        down before re-raising, so callers never leak a half-started plugin.
        """
        with self._lifecycle_lock:
            self._stop = threading.Event()
            self._cleanup_socket()
            os.makedirs(self.cfg.device_plugin_path, exist_ok=True)
            server = grpc.server(
                futures.ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix=f"dp-{self.resource_suffix}"),
                # Allocate sits on the pod-admission critical path: the
                # loopback-unix-socket tuning (latency bias, no BDP probe)
                options=LOOPBACK_GRPC_OPTIONS)
            api.add_device_plugin_servicer(server, self)
            server.add_insecure_port(f"unix://{self.socket_path}")
            server.start()
            self._server = server
            try:
                self._wait_ready()
                self.register()
                self._start_monitor()
            except Exception:
                self._teardown()
                raise
            self._serving = True
            log.info("%s: serving on %s", self.resource_name, self.socket_path)

    def _wait_ready(self) -> None:
        """Self-dial until our own socket answers (reference :186-213).

        The channel is LONG-LIVED (round 15 satellite): a kubelet restart
        storm bounces every plugin through restart() -> start() ->
        _wait_ready(), and a fresh `grpc.insecure_channel` per probe paid
        channel construction + connection state machinery every bounce.
        One cached channel per socket path re-dials the same unix target
        across restarts; the terminal stop() closes it."""
        grpc.channel_ready_future(self._self_channel()).result(
            timeout=self.cfg.grpc_timeout_s)

    def _self_channel(self) -> grpc.Channel:
        """The cached self-dial channel (created lazily so the vTPU
        subclass's post-construction socket re-point is honored; replaced
        if the path ever changes)."""
        cached = self._self_dial
        if cached is not None and cached[0] == self.socket_path:
            self._self_dial_reuses.add()
            return cached[1]
        if cached is not None:
            try:
                cached[1].close()
            except Exception:   # noqa: BLE001 — best-effort close
                pass
        ch = grpc.insecure_channel(f"unix://{self.socket_path}",
                                   options=LOOPBACK_GRPC_OPTIONS)
        self._self_dial = (self.socket_path, ch)
        return ch

    def _close_self_channel(self) -> None:
        cached = self._self_dial
        self._self_dial = None
        if cached is not None:
            try:
                cached[1].close()
            except Exception:   # noqa: BLE001
                pass

    def register(self) -> None:
        """Announce this plugin to the kubelet (reference :288-309).

        Raises typed errors so lifecycle.py can tell the boot race
        (KubeletUnavailable: socket not up yet, retry quietly) from a
        protocol rejection (RegistrationRejected: version mismatch — loud)."""
        faults.fire("kubelet.register", resource=self.resource_name)
        try:
            with grpc.insecure_channel(
                    f"unix://{self.cfg.kubelet_socket}") as ch:
                # wait_for_ready on the RPC itself, NOT a
                # channel_ready_future pre-wait: the ready future resolves
                # through gRPC's connectivity-state poller, which costs a
                # ~200 ms poll tick per fresh channel even when the socket
                # answers instantly — at restart that tick dominated every
                # plugin's registration wall. The RPC-level wait connects
                # event-driven (~1-2 ms) and still queues until the
                # kubelet answers, bounded by the same dial deadline (a
                # dead socket surfaces as DEADLINE_EXCEEDED below instead
                # of FutureTimeoutError; same KubeletUnavailable mapping).
                api.RegistrationStub(ch).Register(
                    pb.RegisterRequest(
                        version=api.API_VERSION,
                        endpoint=os.path.basename(self.socket_path),
                        resource_name=self.resource_name,
                        options=pb.DevicePluginOptions(
                            get_preferred_allocation_available=True),
                    ),
                    timeout=self.cfg.grpc_timeout_s,
                    wait_for_ready=True,
                )
        except grpc.RpcError as exc:
            code = exc.code()
            if code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED):
                raise KubeletUnavailable(
                    f"kubelet Register RPC failed: {code.name}") from exc
            raise RegistrationRejected(
                f"kubelet rejected {self.resource_name}: {code.name} "
                f"{exc.details()}") from exc
        log.info("registered %s with kubelet", self.resource_name)

    def _start_monitor(self) -> None:
        group_paths = {g: self.cfg.dev_path("dev/vfio", g)
                       for g in self._watched_groups()}
        group_bdfs = {g: [d.bdf for d in self.registry.iommu_map.get(g, ())]
                      for g in self._watched_groups()}
        # the "native.probe" fault point now fires inside the hub's probe
        # runner (healthhub._probe_one), so the closure here is the plain
        # native liveness read
        probe = lambda bdf, node: self.health_shim.chip_alive(  # noqa: E731
            self.cfg.pci_base_path, bdf, node)
        self._attach_probe_batch(probe)
        self._subscribe_health(HubSubscription(
            name=self.resource_name,
            socket_path=self.socket_path,
            on_socket_removed=self._restart_async,
            group_paths=group_paths,
            group_bdfs=group_bdfs,
            on_device_health=self.set_group_health,
            probe=probe,
        ))

    def _attach_probe_batch(self, probe, node_for=None) -> None:
        """Mark the probe closure batchable when the health shim can
        coalesce a whole cycle's probes into ONE broker crossing
        (spawn-mode BrokeredHealth): the hub groups closures sharing a
        batch_key — same shim, same pci root — into one submission.
        `node_for` substitutes the representative node per bdf exactly
        as the singular closure would (the vtpu parent mapping)."""
        shim = self.health_shim
        batch = getattr(shim, "chip_alive_batch", None)
        if batch is None:
            return
        base = self.cfg.pci_base_path
        if node_for is None:
            probe.batch = lambda items: batch(base, items)
        else:
            probe.batch = lambda items: batch(
                base, [(bdf, node_for(bdf)) for bdf, _node in items])
        probe.batch_key = (id(shim), base)

    def _subscribe_health(self, sub: HubSubscription) -> None:
        """Attach this server's health filter to the shared hub, or to a
        private single-subscriber hub when running standalone."""
        hub = self._health_hub
        if hub is None:
            hub = self._own_hub = HealthHub(
                poll_interval_s=self.cfg.health_poll_s,
                probe_workers=self.cfg.health_probe_workers,
                probe_deadline_s=self.cfg.health_probe_deadline_s)
        self._health_sub = hub.subscribe(sub)

    def _watched_groups(self) -> List[str]:
        return sorted({d.iommu_group for d in self.devices})

    def _restart_async(self) -> None:
        """Socket removed ⇒ kubelet restarted ⇒ re-serve + re-register
        (reference :677-687,274-285). Runs off the monitor thread, which is
        about to exit. A stop already in progress wins over a restart."""
        if self._closed.is_set() or self._stop.is_set():
            return
        thread = threading.Thread(target=self.restart, daemon=True,
                                  name=f"restart-{self.resource_suffix}")
        self._restart_thread = thread
        thread.start()

    def restart(self) -> None:
        """Re-serve + re-register, retrying with backoff until the kubelet is
        back. A terminal stop() (self._closed) aborts the loop at any point;
        the lifecycle lock makes a concurrent stop() either wait for an
        attempt to finish (and then tear it down) or win outright."""
        with self._lifecycle_lock:
            # counter mutation under its owning lock: restarts are spawned
            # from hub callbacks and can overlap a /status snapshot read
            self._restart_count += 1
            count = self._restart_count
            log.info("%s: restarting (count=%d)", self.resource_name, count)
            self._teardown()
        self._restart_backoff.reset()
        while not self._closed.is_set():
            deadline = time.monotonic() + self.cfg.grpc_timeout_s
            while not os.path.exists(self.cfg.kubelet_socket) \
                    and time.monotonic() < deadline \
                    and not self._closed.is_set():
                time.sleep(0.1)
            with self._lifecycle_lock:
                if self._closed.is_set():
                    return
                try:
                    self.start()
                    return
                except Exception as exc:
                    # jittered, growing delay (resilience.BackoffPolicy):
                    # sibling plugins bounced by the same kubelet restart
                    # spread out instead of re-dialing in lockstep
                    backoff = self._restart_backoff.next_delay()
                    log.error("%s: restart attempt failed (%s); retrying "
                              "in %.1fs", self.resource_name, exc, backoff)
            if self._closed.wait(timeout=backoff):
                return

    def stop(self) -> None:
        """Terminal stop: no restart may resurrect the plugin afterwards."""
        self._closed.set()
        with self._lifecycle_lock:
            self._teardown()
            self._close_self_channel()
        # reap the socket-loss restart runner: it observes _closed at its
        # next check (every wait is _closed-keyed), so a bounded join
        # suffices — unless WE are that runner (stop called from a restart
        # callback), where joining would deadlock on ourselves
        thread = self._restart_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2)

    def _teardown(self) -> None:
        self._serving = False
        self._stop.set()
        self._store.poke()   # wake parked ListAndWatch streams
        # unsubscribe BEFORE grpc unlinks the socket so the hub never
        # mistakes an intentional teardown for a kubelet restart
        if self._health_sub is not None:
            (self._health_hub or self._own_hub).unsubscribe(self._health_sub)
            self._health_sub = None
        if self._server is not None:
            self._server.stop(grace=0.5).wait()
            self._server = None
        if self._own_hub is not None:
            self._own_hub.stop()
            self._own_hub = None
        self._cleanup_socket()
        log.info("%s: stopped", self.resource_name)

    def _cleanup_socket(self) -> None:
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def status_snapshot(self) -> dict:
        """Public state snapshot for the status endpoint (/status).

        Served from the current epoch + atomic counters — ZERO registered
        locks (the lockdep read-path gate pins this): a slow /status
        scrape used to hold the device-table condition and stall
        ListAndWatch transitions behind itself."""
        with lockdep.read_path("server.status_snapshot"):
            ep = self._store.current
            devices = dict(ep.device_health)
            # latched PCI bus-error bits (XID-events analogue) + PCIe link
            # training state (CurrPcieLinkWidth analogue): diagnostic only,
            # ONE config read per device — sysfs reads must never block RPC
            # paths, and here nothing they could block on is held. At
            # fleet scale (4096 devices = 8192 reads/scrape) a small
            # cfg.diagnostics_ttl_s serves repeat scrapes from the last
            # read set; the cache is a single attribute store, lock-free.
            ttl = getattr(self.cfg, "diagnostics_ttl_s", 0.0)
            cached = self._diag_cache
            now = time.monotonic()
            if ttl > 0 and cached is not None and now - cached[0] < ttl:
                errors, degraded_links = cached[1], cached[2]
            else:
                errors = {}
                degraded_links = {}
                for d in self.devices:
                    bits, link = self.health_shim.chip_diagnostics(
                        self.cfg.pci_base_path, d.bdf)
                    if bits:
                        errors[d.bdf] = f"0x{bits:04x}"
                    if link_is_degraded(link):
                        degraded_links[d.bdf] = (
                            f"gen{link['cur_speed']}x{link['cur_width']} of "
                            f"gen{link['max_speed']}x{link['max_width']}")
                self._diag_cache = (now, errors, degraded_links)
            pref_cache = {"hits": self._pref_hits.value,
                          "misses": self._pref_misses.value,
                          "size": len(self._pref_cache),
                          "capacity": PREF_CACHE_SIZE}
            return {
                "resource": self.resource_name,
                "socket": self.socket_path,
                "serving": self._serving,
                "restarts": self._restart_count,
                # the read-plane generation (epoch.EpochStore): bumps on
                # every effective health transition / table rebuild
                "epoch": ep.epoch_id,
                # epoch builds this server actually paid (scale honesty:
                # flips of OTHER resources must not bump this — at 4096
                # devices each build re-serializes the full LW payload)
                "epoch_builds": self._epoch_builds.value,
                # GetPreferredAllocation memo effectiveness + ListAndWatch
                # re-send count (how much health churn reached the kubelet
                # stream after coalescing)
                "preferred_cache": pref_cache,
                # ICI placement scoring of preferred-allocation answers
                # (placement.selection_score; 1.0 = one sub-box)
                "placement": {
                    "scored_total": self._placement_scored.value,
                    "last_score": self._last_placement_score,
                },
                "lw_resends": self._lw_resends.value,
                # precompiled per-IOMMU-group Allocate fragment cache
                # (allocate._GroupFragment) effectiveness
                "alloc_fragments": self._planner.fragment_stats(),
                # the response byte plane (round 15): hot responses served
                # from pre-serialized epoch-keyed bytes vs response-plane
                # protobuf serializations actually paid (fragment/memo
                # segment builds + message-path fallbacks)
                "response_bytes": {
                    "reused": self._alloc_bytes_reused.value,
                    "serializations": self._alloc_serializations.value,
                },
                # long-lived self-dial channel reuses across restarts
                "self_dial_reuses": self._self_dial_reuses.value,
                # recovery-activity counters (resilience.BackoffPolicy):
                # how many backoff delays restart() has issued
                "restart_backoff": self._restart_backoff.snapshot(),
                "devices": devices,
                "pci_errors": errors,
                "degraded_links": degraded_links,
                "allocations_total": self._alloc_count.value,
                # timestamps are stored as epoch floats (record_allocation
                # is on the Allocate hot path) and rendered ISO here, off
                # it. list() first: it snapshots the deque in one atomic C
                # call, where iterating the live deque would race
                # concurrent record_allocation appends
                "recent_allocations": [
                    {"time": datetime.fromtimestamp(
                        e["ts"], timezone.utc).isoformat(timespec="seconds"),
                     "devices": e["devices"]}
                    for e in list(self._recent_allocs)],
            }

    def record_allocation(self, per_container_ids) -> None:
        # AtomicCounter + C-atomic deque append: the Allocate hot path
        # records without touching any lock
        self._alloc_count.add()
        self._recent_allocs.append({
            "ts": time.time(),
            "devices": per_container_ids,
        })
        if self._lifecycle is not None:
            # lock-free producer: the FSM drains this queue under its own
            # lock on the next writer-side event (lifecycle_fsm)
            self._lifecycle.note_allocation_event(
                [d for ids in per_container_ids for d in ids])

    @property
    def serving(self) -> bool:
        return self._serving

    # ------------------------------------------------------------------- RPCs

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(get_preferred_allocation_available=True)

    def _lw_response(self, ep: epoch_mod.Epoch, raw: bool = False):
        """Assemble one stream send from the epoch's pre-serialized
        payload. On the gRPC transport (`raw`) the payload is forwarded
        as-is (api.RawResponse — the passthrough serializer writes the
        epoch's bytes to the wire with NO parse and NO re-serialize);
        direct callers get a single parse (no locks, no per-device deep
        copies — the old _snapshot serialize/deserialize-per-device under
        the device-table condition). The lockdep read-path gate pins both
        shapes at zero registered-lock acquisitions."""
        with lockdep.read_path("server.ListAndWatch.assembly"), \
                trace.span("server.ListAndWatch.send",
                           resource=self.resource_name,
                           epoch_id=ep.epoch_id,
                           devices=len(ep.device_health)):
            if raw:
                return api.RawResponse(ep.lw_payload)
            return pb.ListAndWatchResponse.FromString(ep.lw_payload)

    def ListAndWatch(self, request, context):
        """Initial full list, then a re-send on epoch transitions
        (reference :312-349). Purely event-driven: the stream thread parks
        on the epoch store's condition with NO timeout — wakeups come from
        epoch publishes (health transitions), teardown, and an
        RPC-termination callback that fires when the kubelet drops the
        stream (otherwise a dead stream would pin its worker thread on the
        condvar forever). Payload ASSEMBLY is lock-free: the writer
        pre-serialized the response into the epoch.

        Re-sends are COALESCED on the trailing edge of a quiet window
        (cfg.lw_debounce_s): a vfio flap storm that flips N times inside the
        window produces one re-send carrying the final state, while a lone
        flip still goes out after a single window. LW_MAX_DEFER_WINDOWS
        bounds deferral so a relentless storm cannot starve the stream; the
        loop re-compares epoch ids after every send, so the LAST state
        always reaches the kubelet (the exactly-once/no-lost-final-state
        chaos guarantees ride on this)."""
        store = self._store
        ep = store.current
        raw = api.wants_raw(context)
        log.info("%s: ListAndWatch stream opened (%d devices)",
                 self.resource_name, len(ep.device_health))
        yield self._lw_response(ep, raw)

        if not context.add_callback(store.poke):
            return  # RPC already terminated
        version = ep.epoch_id
        while True:
            store.wait_for(
                lambda: store.current.epoch_id != version
                or self._stop.is_set() or not context.is_active())
            if self._stop.is_set() or not context.is_active():
                return
            debounce = self.cfg.lw_debounce_s
            if debounce > 0:
                deadline = time.monotonic() + debounce * LW_MAX_DEFER_WINDOWS
                while time.monotonic() < deadline:
                    v0 = store.current.epoch_id
                    moved = store.wait_for(
                        lambda: store.current.epoch_id != v0
                        or self._stop.is_set()
                        or not context.is_active(),
                        timeout=debounce)
                    if self._stop.is_set() or not context.is_active():
                        return
                    if not moved:
                        break  # one full quiet window: trailing edge
            ep = store.current
            version = ep.epoch_id
            self._lw_resends.add()
            log.info("%s: device state changed; re-sending %d devices",
                     self.resource_name, len(ep.device_health))
            yield self._lw_response(ep, raw)

    def GetPreferredAllocation(self, request, context):
        # span INSIDE the read-path bracket: the zero-lock gate
        # (tests/test_epoch.py) counts the tracing plane's acquisitions
        # too, so instrumentation can never silently re-lock the path
        with lockdep.read_path("server.GetPreferredAllocation"), \
                trace.span("server.GetPreferredAllocation",
                           resource=self.resource_name,
                           epoch_id=self._store.current.epoch_id,
                           link=_invocation_link(context)):
            index = self._alloc_index
            # The ICI sub-box scan is pure in (availability, must-include,
            # size) over a static torus, and the kubelet re-asks with the
            # same availability between allocations — memoize on those
            # plus the epoch id. The memo dict is swapped wholesale on
            # every epoch publish (invalidated by construction), so a
            # lookup is ONE GIL-atomic dict.get — the old path took the
            # device-table condition plus the memo lock per RPC. A racing
            # publish mid-RPC just misses into a recompute of the same
            # pure result (health is not an input to the scan). Since
            # round 15 the memo value is (ids, serialized container-
            # response record): a warm hit serves pre-serialized bytes,
            # and the whole response is assembled by concatenation.
            epoch_id = self._store.current.epoch_id
            memo = self._pref_cache
            engine = self._policy
            byte_plane = self._byte_plane
            scoring_hook = (engine is not None
                            and engine.has_hook("score_allocation"))
            segments = []
            chosen = []
            fresh = 0
            for creq in request.container_requests:
                key = (epoch_id,
                       tuple(creq.available_deviceIDs),
                       tuple(creq.must_include_deviceIDs),
                       creq.allocation_size)
                hit = memo.get(key)
                if hit is not None:
                    self._pref_hits.add()
                    ids, rec = hit
                else:
                    self._pref_misses.add()
                    try:
                        ids = index.preferred(
                            creq.available_deviceIDs,
                            creq.must_include_deviceIDs,
                            creq.allocation_size,
                        )
                    except MustIncludeTooLarge as exc:
                        context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                      str(exc))
                    rec = self._pref_record(ids) if byte_plane else None
                    fresh += 1
                    if len(memo) < PREF_CACHE_SIZE:
                        memo[key] = (ids, rec)
                # Policy scoring override (policy.py): operator hooks may
                # replace the builtin choice, composing with the
                # placement engine — the ctx carries the builtin answer
                # AND its ICI contiguity score so a policy can keep it
                # unless its own objective dominates. Runs AFTER the memo
                # (policies may be stateful; caching their answers would
                # freeze them) and only when a hook is loaded — the
                # default None engine costs one attribute check. An
                # override BYPASSES the byte cache: the memoized record
                # is the BUILTIN answer's bytes, and serving it would
                # resurrect a winner the policy just overruled — the
                # override is serialized fresh and never memoized.
                if scoring_hook:
                    coords_of = index.coords_of
                    override = engine.score_allocation({
                        "resource": self.resource_name,
                        "available": list(creq.available_deviceIDs),
                        "must_include": list(creq.must_include_deviceIDs),
                        "size": creq.allocation_size,
                        "builtin_choice": list(ids),
                        "builtin_score": placement.selection_score(
                            self.torus_dims,
                            [coords_of.get(i) for i in ids]),
                    })
                    if override is not None:
                        ids = override
                        rec = self._pref_record(ids) if byte_plane else None
                        fresh += 1
                # Score the answer's ICI contiguity (placement.py): 1.0 =
                # the chosen chips ARE one axis-aligned sub-box (one ICI
                # ring/tile), lower = stragglers. Scored on every call
                # (hits too — the score is the placement-quality signal
                # /status surfaces, ~1 us over immutable prebuilt maps)
                # inside its own read-path bracket so the epoch gate pins
                # the scoring itself at zero registered locks.
                with lockdep.read_path("placement.score"):
                    coords_of = index.coords_of
                    self._last_placement_score = placement.selection_score(
                        self.torus_dims, [coords_of.get(i) for i in ids])
                    self._placement_scored.add()
                segments.append(rec)
                chosen.append(ids)
            if byte_plane:
                if segments and not fresh:
                    # every container segment came from the byte memo
                    # (an empty request reuses nothing)
                    self._alloc_bytes_reused.add()
                return self._finish_bytes(b"".join(segments),
                                          pb.PreferredAllocationResponse,
                                          context)
            # byte plane disabled (A/B arm / escape hatch): build the
            # response message per call — the transport serializes it
            resp = pb.PreferredAllocationResponse()
            for ids in chosen:
                resp.container_responses.append(
                    pb.ContainerPreferredAllocationResponse(deviceIDs=ids))
            self._alloc_serializations.add()
            return resp

    def _pref_record(self, ids) -> bytes:
        """One serialized PreferredAllocationResponse.container_responses
        record (counted: the response plane's serialization ledger)."""
        self._alloc_serializations.add()
        return epoch_mod.encode_delimited(
            1, pb.ContainerPreferredAllocationResponse(
                deviceIDs=ids).SerializeToString())

    def _finish_bytes(self, data: bytes, cls, context):
        """Deliver assembled response bytes: raw passthrough on the gRPC
        transport (api.RawResponse — the serializer forwards the payload
        untouched), ONE parse for direct in-process callers (tests,
        bench, fleetsim)."""
        if api.wants_raw(context):
            return api.RawResponse(data)
        return cls.FromString(data)

    def Allocate(self, request, context):
        """Template method: log → subclass impl → record for /status.
        Failed allocations abort inside the impl and are never recorded.

        The impl returns either pre-serialized AllocateResponse BYTES
        (the passthrough byte plane — counted bytes_reused only when the
        whole response came from cached records, matching the
        GetPreferredAllocation convention) or a built message (the vTPU
        path and other fallbacks — counted as a response serialization,
        since the transport must serialize it)."""
        ids = [list(c.devices_ids) for c in request.container_requests]
        log.info("%s: Allocate(%s)", self.resource_name, ids)
        with lockdep.read_path("server.Allocate"), \
                trace.span("server.Allocate",
                           histogram="tdp_attach_wall_ms",
                           resource=self.resource_name,
                           epoch_id=self._store.current.epoch_id,
                           devices=sum(len(i) for i in ids),
                           link=_invocation_link(context)):
            # reuse accounting by ledger delta: a cold byte-path request
            # (fragment builds after an epoch bump) serializes segments
            # and must not also count as a reuse. A concurrent cold call
            # on another thread can suppress this call's reuse count —
            # a rare undercount, never an overcount.
            ser_before = self._alloc_serializations.value
            # crossings-per-claim bracket (round 20): the live gauge the
            # batching work is judged by — a multi-group claim must pay
            # ONE revalidation crossing, visible on /status + /metrics
            client = broker_mod.get_client()
            cross_before = client.crossings.value
            resp = self._allocate_impl(request, context)
            client.note_claim_crossings(
                client.crossings.value - cross_before)
            self.record_allocation(ids)
            if isinstance(resp, bytes):
                if ids and self._alloc_serializations.value == ser_before:
                    self._alloc_bytes_reused.add()
                resp = self._finish_bytes(resp, pb.AllocateResponse, context)
            else:
                self._alloc_serializations.add()
        return resp

    def _allocate_impl(self, request, context):
        engine = self._policy
        if engine is not None and engine.has_hook("admit"):
            reason = engine.admit({
                "op": "allocate", "resource": self.resource_name,
                "devices": sum(len(c.devices_ids)
                               for c in request.container_requests)})
            if reason is not None:
                log.warning("%s: allocate rejected by policy: %s",
                            self.resource_name, reason)
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              f"policy rejected allocation: {reason}")
        remediation = self._remediation
        if remediation is not None:
            shed = remediation.admit({"op": "allocate",
                                      "resource": self.resource_name})
            if shed is not None:
                log.warning("%s: allocate shed by remediation: %s",
                            self.resource_name, shed)
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, shed)
        try:
            # the epoch id keys the planner's precompiled fragments: a
            # health flip publishes a new epoch, so the next plan starts a
            # fresh fragment cache — no invalidation listeners. The byte
            # plane assembles the response from the fragments' serialized
            # records (one privilege crossing per REQUEST, even
            # multi-container — the coalesced fast path); byte_plane=False
            # (the bench A/B arm) keeps the build-protos-per-call path.
            if self._byte_plane:
                return self._planner.allocate_response_bytes(
                    request, epoch=self._store.current.epoch_id)
            return self._planner.allocate_response(
                request, epoch=self._store.current.epoch_id)
        except broker_mod.BrokerUnavailable as exc:
            # the privileged broker is gone (crash, injected drop): the
            # typed-unavailable degradation — the kubelet retries, and a
            # broker respawn + handshake recovers without restarting us
            log.error("%s: allocate degraded: %s", self.resource_name, exc)
            context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
        except allocate_mod.AllocationError as exc:
            log.error("%s: allocate failed: %s", self.resource_name, exc)
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()
