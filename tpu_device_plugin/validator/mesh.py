"""Slice → jax.sharding.Mesh mapping.

Axes follow the scaling-book decomposition: `dp` (pure data parallel,
gradient all-reduce), `tp` (tensor parallel, activation collectives on the
fastest links), `sp` (sequence parallel for long context). On a passed-
through slice all three ride ICI; the mesh construction puts `tp` innermost
so its collectives land on nearest-neighbor links.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def infer_mesh_shape(n_devices: int,
                     tp: Optional[int] = None,
                     sp: Optional[int] = None) -> Tuple[int, int, int]:
    """Factor `n_devices` into (dp, sp, tp).

    Defaults: tp takes the largest power-of-two ≤ min(n, 4) (one host's worth
    of nearest-neighbor links), sp stays 1 unless asked, dp absorbs the rest.
    """
    if tp is None:
        tp = 1
        while tp * 2 <= min(n_devices, 4) and n_devices % (tp * 2) == 0:
            tp *= 2
    if sp is None:
        sp = 1
    if n_devices % (tp * sp) != 0:
        raise ValueError(f"{n_devices} devices not divisible by tp={tp} * sp={sp}")
    dp = n_devices // (tp * sp)
    return dp, sp, tp


def slice_mesh(devices: Optional[Sequence[jax.Device]] = None,
               tp: Optional[int] = None,
               sp: Optional[int] = None) -> Mesh:
    """Build a ("dp", "sp", "tp") mesh over the visible slice."""
    if devices is None:
        devices = jax.devices()
    dp, sp_, tp_ = infer_mesh_shape(len(devices), tp=tp, sp=sp)
    grid = np.array(devices).reshape(dp, sp_, tp_)
    return Mesh(grid, axis_names=("dp", "sp", "tp"))
