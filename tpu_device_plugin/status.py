"""Optional HTTP status endpoint for the DaemonSet.

The reference exposes no health surface (SURVEY §5: "no Prometheus, no
/healthz"); a kubelet can only observe the process. This adds a minimal,
dependency-free endpoint for liveness probes and debugging:

  GET /healthz  -> liveness: 200 while the manager's run loop is alive —
                   including the boot state where plugins are still waiting
                   for the kubelet socket (killing the pod there would defeat
                   the manager's own retry loop); 503 only when the loop died
  GET /readyz   -> readiness: 200 once at least one plugin is serving
  GET /status   -> JSON: per-plugin resource name, socket, restart count,
                   device health table, latched PCI error bits, recent
                   allocations, pending (not-yet-registered) plugins,
                   native-shim facts, draining flag
  GET /metrics  -> Prometheus text format: device health gauges, serving
                   flags, restart counters, pending count, native-shim
                   facts, flight-recorder latency histograms (trace.py)
  GET /debug/flight -> the flight recorder (trace.py): the merged span
                   ring as time-ordered JSON, filterable by
                   ?claim=<uid> / ?bdf=<raw id> / ?op=<prefix> /
                   ?trace=<trace id> / ?limit=<n>, plus the slow-span
                   log — the "what happened to claim X" surface
                   (docs/observability.md). ?since_ms=<epoch ms> turns
                   the query into a limit-bounded paginated DRAIN:
                   oldest-first records strictly newer than the cursor,
                   with next_since_ms/more in the body — a large ring
                   exports in pages instead of all-or-nothing
  GET /debug/fleet/trace -> the fleet trace waterfall
                   (fleetplace.FleetFlight): ?trace=<trace id> merges
                   every registered flight source (this daemon by
                   default; per-node sources in fleetsim / registered
                   HTTP endpoints in real fleets) into one cross-node,
                   cross-process, node-labeled, time-ordered story —
                   the "follow one slice claim across hosts and the
                   broker" surface (docs/observability.md)
  GET /debug/policy -> the policy engine (policy.py): loaded modules,
                   per-hook call/override/error/deadline counters,
                   breaker states, and the bounded recent-decision
                   ring. 404 when no policy engine is attached.
  GET /debug/remediation -> the self-heal plane (remediation.py):
                   active knobs, cool-downs, totals, and the audited
                   action log (applied/vetoed/skipped/rolled-back).
                   404 when no remediation engine is attached.
  GET /debug/broker -> the privilege broker (broker.py): the client's
                   crossing counters plus — in spawn mode — the broker
                   process's own audit (held fds, per-op counts, the
                   recent-crossing ring with daemon-side span links).
                   This endpoint performs ONE broker IPC round-trip;
                   /status deliberately serves only the local
                   client-side counters.
  GET /debug/defrag -> the defrag advisor (placement.py): given
                   ?shape=2x2[&generation=v5e], the minimal claim
                   migrations that would free a contiguous ICI box for
                   that shape on this node, plus the per-generation
                   fragmentation records that motivated it
                   (docs/observability.md documents the query params;
                   docs/design.md "Slice placement" the proposal
                   format). 400 on a malformed/overflow shape or a
                   generation with no host view. Requires the DRA
                   driver; advisory only — applying it rides the
                   migration-handoff machinery (fleet-wide:
                   fleetplace.FleetScheduler.apply_defrag_wave).

Disabled by default (--status-port 0).

The /metrics exposition follows the Prometheus text format strictly:
every series carries # HELP and # TYPE lines and label values are
escaped per the spec (tests/test_metrics_format.py parses the full
scrape with a line grammar).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

log = logging.getLogger(__name__)


def _esc(value) -> str:
    """Escape a Prometheus label VALUE per the text-format spec
    (backslash, double-quote, newline)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class StatusServer:
    def __init__(self, manager, port: int = 0, host: str = "127.0.0.1",
                 dra_driver=None, fleet_flight=None,
                 fleet_scheduler=None):
        self.manager = manager
        self.dra_driver = dra_driver
        # placement control plane (fleetplace.FleetScheduler): when this
        # daemon hosts a scheduler shard, its decision/conflict/frag
        # counters ride the same /status + /metrics surface
        self.fleet_scheduler = fleet_scheduler
        # /debug/fleet/trace collector (fleetplace.FleetFlight): None
        # builds a local-only collector lazily on first query — a
        # single daemon serves its own ring under the SAME endpoint
        # shape a scheduler-side aggregator serves the fleet's
        self.fleet_flight = fleet_flight
        # assembly accounting of the most recent /metrics render (series,
        # parts, bytes_joined == bytes_rendered): the O(series) scrape
        # guard reads this (test_perf_honesty.py, bench.py --scale)
        self.scrape_stats: dict = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                log.debug("status: " + fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parts = urlsplit(self.path)
                route = parts.path
                if route == "/healthz":
                    if outer.alive():
                        self._send(200, b"ok", "text/plain")
                    else:
                        self._send(503, b"manager loop not running", "text/plain")
                elif route == "/readyz":
                    if outer.ready():
                        self._send(200, b"ok", "text/plain")
                    else:
                        self._send(503, b"no plugins serving", "text/plain")
                elif route == "/status":
                    self._send(200, json.dumps(outer.status(),
                                               sort_keys=True).encode())
                elif route == "/metrics":
                    self._send(200, outer.metrics().encode(),
                               "text/plain; version=0.0.4")
                elif route == "/debug/flight":
                    # keep_blank_values: "?claim=" with an empty value
                    # (a typo'd $UID in an incident script) must NOT
                    # silently degrade to the whole unfiltered ring —
                    # no claim/bdf/op/trace is the empty string, so
                    # reject it
                    query = parse_qs(parts.query, keep_blank_values=True)

                    def first(key):
                        values = query.get(key)
                        return values[0] if values else None

                    for key in ("claim", "bdf", "op", "trace"):
                        if first(key) == "":
                            return self._send(
                                400, f"empty {key} filter".encode(),
                                "text/plain")
                    limit = first("limit")
                    try:
                        limit = int(limit) if limit is not None else None
                    except ValueError:
                        return self._send(400, b"limit must be an integer",
                                          "text/plain")
                    since_ms = first("since_ms")
                    try:
                        since_ms = (float(since_ms)
                                    if since_ms is not None else None)
                    except ValueError:
                        return self._send(
                            400, b"since_ms must be a number (epoch "
                            b"milliseconds)", "text/plain")
                    self._send(200, json.dumps(outer.flight(
                        claim=first("claim"), bdf=first("bdf"),
                        op=first("op"), limit=limit,
                        trace=first("trace"), since_ms=since_ms),
                        sort_keys=True).encode())
                elif route == "/debug/fleet/trace":
                    query = parse_qs(parts.query, keep_blank_values=True)
                    trace_id = (query.get("trace") or [None])[0]
                    if not trace_id:
                        return self._send(
                            400, b"trace=<trace id> query parameter "
                            b"required", "text/plain")
                    limit = (query.get("limit") or [None])[0]
                    try:
                        limit = int(limit) if limit is not None else None
                    except ValueError:
                        return self._send(400, b"limit must be an integer",
                                          "text/plain")
                    self._send(200, json.dumps(
                        outer.fleet_trace(trace_id, limit=limit),
                        sort_keys=True).encode())
                elif route == "/debug/policy":
                    body = outer.policy_debug()
                    if body is None:
                        return self._send(
                            404, b"no policy engine attached", "text/plain")
                    self._send(200, json.dumps(body,
                                               sort_keys=True).encode())
                elif route == "/debug/remediation":
                    body = outer.remediation_debug()
                    if body is None:
                        return self._send(
                            404, b"no remediation engine attached",
                            "text/plain")
                    self._send(200, json.dumps(body,
                                               sort_keys=True).encode())
                elif route == "/debug/broker":
                    self._send(200, json.dumps(
                        outer.broker_debug(), sort_keys=True,
                        default=str).encode())
                elif route == "/debug/defrag":
                    if outer.dra_driver is None:
                        return self._send(
                            404, b"no DRA driver attached", "text/plain")
                    query = parse_qs(parts.query, keep_blank_values=True)
                    shape = (query.get("shape") or [None])[0]
                    generation = (query.get("generation") or [None])[0]
                    if not shape:
                        return self._send(
                            400, b"shape=NxN[xN] query parameter required",
                            "text/plain")
                    try:
                        proposal = outer.defrag(shape, generation)
                    except ValueError as exc:
                        return self._send(400, str(exc).encode(),
                                          "text/plain")
                    self._send(200, json.dumps(proposal,
                                               sort_keys=True).encode())
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="status-http")

    def start(self) -> None:
        self._thread.start()
        host, port = self._httpd.server_address[:2]
        log.info("status endpoint on http://%s:%d", host, port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        # shutdown() returns once serve_forever has exited its loop; the
        # bounded join reaps the server thread itself so a stopped status
        # endpoint never leaks a thread (the lockdep leak check counts)
        if self._thread.is_alive():
            self._thread.join(timeout=2)

    def alive(self) -> bool:
        return self.manager.running.is_set()

    def ready(self) -> bool:
        plugins = self.manager.plugins
        return bool(plugins) and any(p.serving for p in plugins)

    def status(self) -> dict:
        from . import lockdep
        with lockdep.read_path("status.endpoint"):
            return self._status_impl()

    def defrag(self, shape: str, generation=None) -> dict:
        """The /debug/defrag body: this node's defrag advisory for the
        requested slice shape (DraDriver.propose_defrag over lock-free
        host views; raises ValueError on a malformed shape or unknown
        generation — the handler answers 400)."""
        return self.dra_driver.propose_defrag(shape, generation)

    def policy_debug(self):
        """The /debug/policy body (None when no engine is attached):
        PolicyEngine.debug() — snapshot + recent-decision ring."""
        engine = getattr(self.manager, "policy_engine", None)
        if engine is None:
            return None
        return engine.debug()

    def remediation_debug(self):
        """The /debug/remediation body (None when no engine is
        attached): RemediationEngine.debug() — the snapshot plus the
        audited action log (applied/vetoed/skipped/rolled-back, oldest
        first, bounded ring)."""
        engine = getattr(self.manager, "remediation_engine", None)
        if engine is None:
            return None
        return engine.debug()

    def broker_debug(self) -> dict:
        """The /debug/broker body: the full broker stats — one IPC
        round-trip in spawn mode (held fds, per-op audit), just the
        local crossing counters in-process."""
        from . import broker
        return broker.get_client().stats()

    def flight(self, claim=None, bdf=None, op=None, limit=None,
               trace=None, since_ms=None) -> dict:
        """The /debug/flight body: merged span ring (time-ordered,
        filtered), the slow-span log, and the recorder's own stats.
        Entirely lock-free (trace.snapshot merges C-atomic ring copies) —
        draining the flight recorder during an incident can never stall
        the paths being debugged.

        With `since_ms` the query becomes one page of a bounded DRAIN
        (trace.drain — the one paging implementation): oldest-first
        records strictly newer than the cursor, `limit` per page
        (extended through an equal-timestamp run so the cursor never
        loses a record), plus `next_since_ms` (the last returned
        record's ts — pass it back for the next page) and `more` — a
        10k-span ring exports in pages instead of all-or-nothing."""
        from . import trace as trace_mod
        body = {
            "filters": {"claim": claim, "bdf": bdf, "op": op,
                        "limit": limit, "trace": trace,
                        "since_ms": since_ms},
            "slow": trace_mod.slow_spans(),
            "stats": trace_mod.stats(),
        }
        if since_ms is not None:
            page, more = trace_mod.drain(since_ms, limit=limit,
                                         claim=claim, bdf=bdf, op=op,
                                         trace=trace)
            body["spans"] = page
            body["more"] = more
            body["next_since_ms"] = (page[-1]["ts"] * 1e3 if page
                                     else since_ms)
        else:
            body["spans"] = trace_mod.snapshot(
                claim=claim, bdf=bdf, op=op, limit=limit, trace=trace)
        return body

    def fleet_trace(self, trace_id: str, limit=None) -> dict:
        """The /debug/fleet/trace body: the merged cross-node waterfall
        for one trace id (fleetplace.FleetFlight). Without a registered
        fleet collector this daemon serves its OWN ring under the fleet
        endpoint shape — the single-node degenerate fleet."""
        ff = self.fleet_flight
        if ff is None:
            from .fleetplace import FleetFlight
            ff = FleetFlight()
            name = getattr(self.dra_driver, "node_name", None) or "local"
            ff.add_local_source(str(name))
            self.fleet_flight = ff
        return ff.trace(trace_id, limit=limit)

    def _status_impl(self) -> dict:
        from . import faults
        from . import lockdep
        out = {
            "plugins": [p.status_snapshot() for p in self.manager.plugins],
            "pending": [p.resource_name for p in self.manager.pending],
            "native": getattr(self.manager, "native_info", {}),
            "draining": getattr(self.manager, "draining", False),
        }
        # recovery-activity counters (resilience.py): publish-retry backoff
        # state plus any armed/fired fault points, so chaos behavior is
        # observable from the same surface operators already scrape
        publish_backoff = getattr(self.manager, "publish_backoff", None)
        if publish_backoff is not None:
            out["inventory_publish_backoff"] = publish_backoff.snapshot()
        # incremental-discovery scan counters (full walks vs dirty-set
        # rescans + sysfs reads of the last scan)
        discovery_stats = getattr(self.manager, "discovery_stats", None)
        if discovery_stats is not None:
            out["discovery"] = discovery_stats()
        # restart fast path (lifecycle.PluginManager.start): boot wall
        # times, readiness edges and the snapshot-cache outcome of the
        # most recent boot
        boot_stats = getattr(self.manager, "boot_stats", None)
        if boot_stats:
            out["boot"] = dict(boot_stats)
        # shared-health-plane counters (healthhub.HealthHub): hub fd/thread
        # gauges, probe-cycle latency, per-probe timeout/error counters
        health_stats = getattr(self.manager, "health_stats", None)
        if health_stats is not None:
            out["health"] = health_stats()
        # device lifecycle FSM (lifecycle_fsm.DeviceLifecycle): per-state
        # gauges, transition counters, orphaned-claim / identity-swap
        # totals and the recent surprise-removal ring
        lifecycle_stats = getattr(self.manager, "lifecycle_stats", None)
        if lifecycle_stats is not None:
            out["lifecycle"] = lifecycle_stats()
        fault_stats = faults.stats()
        armed = faults.armed_sites()
        if fault_stats or armed:
            out["faults"] = {"armed": armed, "fired": fault_stats}
        # flight-recorder gauges (trace.py): ring occupancy/overwrites,
        # slow-span pressure — lock-free reads like everything else here
        from . import trace
        out["trace"] = trace.stats()
        # SLO plane (slo.py): the scrape drives one burn-rate evaluation
        # (the writer side takes only the engine's plain unregistered
        # lock — invisible to the zero-lock gate, same contract as the
        # trace maintenance lock), then surfaces the lock-free snapshot
        from . import slo as slo_mod
        slo_engine = getattr(self.manager, "slo_engine", None) \
            or slo_mod.get_engine()
        slo_engine.evaluate()
        out["slo"] = slo_engine.snapshot()
        # privilege-boundary crossings (broker.py): the CLIENT-side
        # counters only — lock-free AtomicCounter reads; the broker
        # process's own audit (an IPC round-trip) lives on /debug/broker
        from . import broker
        out["broker"] = broker.get_client().client_stats()
        # operator policy decisions (policy.py): per-hook counters +
        # breaker states when an engine is loaded
        engine = getattr(self.manager, "policy_engine", None)
        if engine is not None:
            out["policy"] = engine.snapshot()
        # self-heal plane (remediation.py): active knobs, cool-downs,
        # action/rollback/veto/shed totals, per-action last trace id —
        # plain-lock snapshot, never a knob turn (tick() runs elsewhere)
        rem = getattr(self.manager, "remediation_engine", None)
        if rem is not None:
            out["remediation"] = rem.snapshot()
        # hot-read-path lock accounting (lockdep.read_path): only present
        # under TDP_LOCKDEP=1 — steady-state acquisitions pinned at 0 by
        # the read-path gate (tests/test_epoch.py)
        if lockdep.enabled():
            paths = lockdep.path_stats()
            if paths:
                out["read_paths"] = paths
        # sharded placement control plane (fleetplace.FleetScheduler):
        # decision/wave/conflict/replan counters plus the shard's
        # FragAccountant delta-vs-recompute accounting — all lock-free
        # AtomicCounter/attribute reads
        sched = self.fleet_scheduler
        if sched is not None:
            out["fleet"] = sched.snapshot()
        d = self.dra_driver
        if d is not None:
            out["dra"] = {
                "driver": d.driver_name,
                "serving": d.serving,
                "kubelet_registered": (d.registered.is_set()
                                       and d.registration_error is None),
                "registration_error": d.registration_error,
                "prepared_claims": d.prepared_claim_count(),
                "unhealthy_devices": d.unhealthy_devices(),
                # lifecycle survivability: claims whose device was
                # surprise-removed, and devices gone from the inventory
                # (hot-unplug) awaiting replug readmission
                "orphaned_claims": d.orphaned_claims(),
                "departed_devices": d.departed_devices(),
                # prepare-ack byte plane (round 15): acks served from
                # pre-serialized per-claim segments vs serializations
                # paid — lock-free AtomicCounter reads
                "ack_bytes": d.ack_byte_stats(),
                # slice placement (placement.py): per-generation
                # fragmentation records (largest placeable sub-box vs
                # free capacity, recomputed per epoch publish) and the
                # advisor counters — all lock-free attribute reads
                "fragmentation": d.fragmentation_stats(),
                "placement": dict(d.placement_stats),
                "republish_backoff": d.republish_backoff.snapshot(),
                # delta (generation-keyed guarded PUT) vs full
                # (read-modify-write) slice publishes
                "publish_stats": dict(d.publish_stats),
                # publish pacing + coalescing (kubeapi.PublishPacer):
                # wave/coalesce/throttle counters and the live adaptive
                # admission window — lock-free snapshot
                "pacing": d.pacer.snapshot(),
                # watch-stream convergence plane (kubeapi.Reflector):
                # stream/event/relist/resync counters, the degraded-mode
                # gauge, and watch-triggered repairs — zeros (enabled:
                # false) when the driver runs in pre-watch polling mode
                "watch": d.watch_stats(),
            }
            # attach plane: in-flight claim tasks, prepare pool size, and
            # group-commit effectiveness (commits vs claims coalesced)
            out["dra"].update(d.checkpoint_stats())
            if d.api is not None:
                out["dra"]["api_breaker"] = d.api.breaker.snapshot()
        return out

    def metrics(self) -> str:
        """Prometheus text exposition of the /status facts."""
        s = self.status()
        lines = [
            "# HELP tpu_plugin_devices Devices by resource and health state.",
            "# TYPE tpu_plugin_devices gauge",
        ]
        for p in s["plugins"]:
            counts = {"Healthy": 0, "Unhealthy": 0}
            for health in p["devices"].values():
                counts[health] = counts.get(health, 0) + 1
            for health, n in sorted(counts.items()):
                lines.append(
                    f'tpu_plugin_devices{{resource="{_esc(p["resource"])}",'
                    f'health="{health}"}} {n}')
        lines += ["# HELP tpu_plugin_serving Plugin serving state (1=serving).",
                  "# TYPE tpu_plugin_serving gauge"]
        for p in s["plugins"]:
            lines.append(f'tpu_plugin_serving{{resource="{_esc(p["resource"])}"}} '
                         f'{int(p["serving"])}')
        lines += ["# HELP tpu_plugin_degraded_links Chips whose PCIe link "
                  "trained below its maximum (diagnostic).",
                  "# TYPE tpu_plugin_degraded_links gauge"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_degraded_links{{resource="{_esc(p["resource"])}"}} '
                f'{len(p.get("degraded_links", {}))}')
        lines += ["# HELP tpu_plugin_epoch Read-plane epoch generation "
                  "(epoch.EpochStore): bumps on every effective health "
                  "transition or device-table rebuild.",
                  "# TYPE tpu_plugin_epoch gauge"]
        for p in s["plugins"]:
            lines.append(f'tpu_plugin_epoch{{resource="{_esc(p["resource"])}"}} '
                         f'{p.get("epoch", 0)}')
        lines += ["# HELP tpu_plugin_restarts_total Socket-loss restarts.",
                  "# TYPE tpu_plugin_restarts_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_restarts_total{{resource="{_esc(p["resource"])}"}} '
                f'{p["restarts"]}')
        lines += ["# HELP tpu_plugin_restart_retries_total Backoff delays "
                  "issued while re-registering after socket loss.",
                  "# TYPE tpu_plugin_restart_retries_total counter"]
        for p in s["plugins"]:
            retries = p.get("restart_backoff", {}).get("total_attempts", 0)
            lines.append(
                f'tpu_plugin_restart_retries_total'
                f'{{resource="{_esc(p["resource"])}"}} {retries}')
        lines += ["# HELP tpu_plugin_allocations_total Successful Allocate "
                  "RPCs since plugin start.",
                  "# TYPE tpu_plugin_allocations_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_allocations_total{{resource="{_esc(p["resource"])}"}} '
                f'{p["allocations_total"]}')
        lines += ["# HELP tpu_plugin_pref_cache_total GetPreferredAllocation "
                  "LRU memo lookups by outcome.",
                  "# TYPE tpu_plugin_pref_cache_total counter"]
        for p in s["plugins"]:
            cache = p.get("preferred_cache", {})
            for outcome, key in (("hit", "hits"), ("miss", "misses")):
                lines.append(
                    f'tpu_plugin_pref_cache_total{{resource='
                    f'"{_esc(p["resource"])}",outcome="{outcome}"}} '
                    f'{cache.get(key, 0)}')
        lines += ["# HELP tpu_plugin_pref_placement_scored_total "
                  "GetPreferredAllocation answers scored for ICI "
                  "contiguity (placement.selection_score).",
                  "# TYPE tpu_plugin_pref_placement_scored_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_pref_placement_scored_total'
                f'{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("placement", {}).get("scored_total", 0)}')
        lines += ["# HELP tpu_plugin_pref_placement_score ICI contiguity "
                  "of the most recent preferred-allocation answer "
                  "(1 = one axis-aligned sub-box, lower = stragglers).",
                  "# TYPE tpu_plugin_pref_placement_score gauge"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_pref_placement_score'
                f'{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("placement", {}).get("last_score", 0.0)}')
        lines += ["# HELP tpu_plugin_lw_resends_total ListAndWatch re-sends "
                  "after debounce coalescing (initial snapshots excluded).",
                  "# TYPE tpu_plugin_lw_resends_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_lw_resends_total{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("lw_resends", 0)}')
        lines += ["# HELP tpu_plugin_alloc_fragment_total Precompiled "
                  "per-IOMMU-group Allocate fragment lookups by outcome.",
                  "# TYPE tpu_plugin_alloc_fragment_total counter"]
        for p in s["plugins"]:
            frags = p.get("alloc_fragments", {})
            for outcome, key in (("hit", "hits"), ("miss", "misses")):
                lines.append(
                    f'tpu_plugin_alloc_fragment_total{{resource='
                    f'"{_esc(p["resource"])}",outcome="{outcome}"}} '
                    f'{frags.get(key, 0)}')
        # the response byte plane (round 15, transport endgame): hot RPC
        # responses served from pre-serialized epoch-keyed bytes vs the
        # protobuf serializations the response plane still pays
        lines += ["# HELP tpu_plugin_alloc_bytes_reused_total Hot RPC "
                  "responses (Allocate + GetPreferredAllocation) served "
                  "from pre-serialized epoch-keyed bytes.",
                  "# TYPE tpu_plugin_alloc_bytes_reused_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_alloc_bytes_reused_total'
                f'{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("response_bytes", {}).get("reused", 0)}')
        lines += ["# HELP tpu_plugin_alloc_serializations_total Response-"
                  "plane protobuf serializations paid on the allocate "
                  "path (fragment/memo segment builds at miss time + "
                  "message-path fallbacks).",
                  "# TYPE tpu_plugin_alloc_serializations_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_alloc_serializations_total'
                f'{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("response_bytes", {}).get("serializations", 0)}')
        lines += ["# HELP tpu_plugin_self_dial_reuses_total Readiness "
                  "probes served by the long-lived self-dial channel "
                  "instead of a fresh gRPC channel per restart.",
                  "# TYPE tpu_plugin_self_dial_reuses_total counter"]
        for p in s["plugins"]:
            lines.append(
                f'tpu_plugin_self_dial_reuses_total'
                f'{{resource="{_esc(p["resource"])}"}} '
                f'{p.get("self_dial_reuses", 0)}')
        disc = s.get("discovery")
        if disc:
            lines += [
                "# HELP tpu_plugin_discovery_scans_total Discovery walks by "
                "kind (full sysfs walk vs dirty-set rescan).",
                "# TYPE tpu_plugin_discovery_scans_total counter",
                f'tpu_plugin_discovery_scans_total{{kind="full"}} '
                f'{disc.get("full_scans", 0)}',
                f'tpu_plugin_discovery_scans_total{{kind="dirty"}} '
                f'{disc.get("dirty_rescans", 0)}',
                "# HELP tpu_plugin_discovery_last_scan_reads Sysfs reads "
                "made by the most recent discovery scan.",
                "# TYPE tpu_plugin_discovery_last_scan_reads gauge",
                f'tpu_plugin_discovery_last_scan_reads '
                f'{disc.get("last_scan_reads", 0)}',
                "# HELP tpu_plugin_discovery_snapshot_hits_total Devices "
                "revalidated straight from the persisted discovery "
                "snapshot at boot (no cold sysfs reads paid).",
                "# TYPE tpu_plugin_discovery_snapshot_hits_total counter",
                f'tpu_plugin_discovery_snapshot_hits_total '
                f'{disc.get("snapshot_hits", 0)}',
                "# HELP tpu_plugin_discovery_snapshot_invalidated_total "
                "Cached devices invalidated by boot revalidation (paid "
                "counted cold re-reads).",
                "# TYPE tpu_plugin_discovery_snapshot_invalidated_total "
                "counter",
                f'tpu_plugin_discovery_snapshot_invalidated_total '
                f'{disc.get("snapshot_invalidated", 0)}',
                "# HELP tpu_plugin_discovery_snapshot_fallbacks_total "
                "Snapshot-cache loads refused (missing/corrupt/version/"
                "fault) — boots that degraded to the full cold walk.",
                "# TYPE tpu_plugin_discovery_snapshot_fallbacks_total "
                "counter",
                f'tpu_plugin_discovery_snapshot_fallbacks_total '
                f'{disc.get("snapshot_fallbacks", 0)}',
            ]
        health = s.get("health")
        if health:
            lines += [
                "# HELP tpu_plugin_health_inotify_fds Inotify fds held by "
                "the shared health hub (one per HOST, not per resource).",
                "# TYPE tpu_plugin_health_inotify_fds gauge",
                f"tpu_plugin_health_inotify_fds {health['inotify_fds']}",
                "# HELP tpu_plugin_health_threads Hub loop + probe-pool "
                "threads (the per-resource monitor threads are gone).",
                "# TYPE tpu_plugin_health_threads gauge",
                f"tpu_plugin_health_threads {health['threads']}",
                "# HELP tpu_plugin_health_subscriptions Resources "
                "subscribed to the shared health hub.",
                "# TYPE tpu_plugin_health_subscriptions gauge",
                f"tpu_plugin_health_subscriptions "
                f"{health['subscriptions']}",
                "# HELP tpu_plugin_health_probe_cycles_total Deduped "
                "probe cycles run by the hub.",
                "# TYPE tpu_plugin_health_probe_cycles_total counter",
                f"tpu_plugin_health_probe_cycles_total "
                f"{health['probe_cycles_total']}",
                "# HELP tpu_plugin_health_last_cycle_ms Wall time of the "
                "most recent probe cycle (deadline-bounded).",
                "# TYPE tpu_plugin_health_last_cycle_ms gauge",
                f"tpu_plugin_health_last_cycle_ms "
                f"{health['last_cycle_ms']}",
                "# HELP tpu_plugin_health_probe_timeouts_total Probes "
                "scored dead at the per-cycle deadline.",
                "# TYPE tpu_plugin_health_probe_timeouts_total counter",
                f"tpu_plugin_health_probe_timeouts_total "
                f"{health['probe_timeouts_total']}",
                "# HELP tdp_probe_errors_total Probe callbacks that "
                "raised; each scored its group Unhealthy instead of "
                "killing the health plane.",
                "# TYPE tdp_probe_errors_total counter",
                f"tdp_probe_errors_total {health['probe_errors_total']}",
                "# HELP tpu_plugin_health_probes_last_cycle Unique BDFs "
                "probed by the most recent cycle (after dedup).",
                "# TYPE tpu_plugin_health_probes_last_cycle gauge",
                f"tpu_plugin_health_probes_last_cycle "
                f"{health['probes_last_cycle']}",
                "# HELP tpu_plugin_health_probes_deduped_last_cycle "
                "Probe requests collapsed by the per-BDF dedup in the "
                "most recent cycle.",
                "# TYPE tpu_plugin_health_probes_deduped_last_cycle gauge",
                f"tpu_plugin_health_probes_deduped_last_cycle "
                f"{health['probes_deduped_last_cycle']}",
                "# HELP tpu_plugin_health_existence_scans_total Periodic "
                "existence-reconciler passes run by the hub.",
                "# TYPE tpu_plugin_health_existence_scans_total counter",
                f"tpu_plugin_health_existence_scans_total "
                f"{health['existence_scans_total']}",
            ]
        lifecycle = s.get("lifecycle")
        if lifecycle:
            lines += [
                "# HELP lifecycle_transitions_total Device lifecycle FSM "
                "transitions (present/bound/allocated/detaching/gone/"
                "replugged; lifecycle_fsm.py).",
                "# TYPE lifecycle_transitions_total counter",
            ]
            for key, n in sorted(lifecycle.get("transitions", {}).items()):
                frm, _, to = key.partition("->")
                lines.append(
                    f'lifecycle_transitions_total{{from="{_esc(frm)}",'
                    f'to="{_esc(to)}"}} {n}')
            lines += [
                "# HELP claims_orphaned_total Prepared claims orphaned by "
                "PCIe surprise removal of their device.",
                "# TYPE claims_orphaned_total counter",
                f"claims_orphaned_total "
                f"{lifecycle.get('claims_orphaned_total', 0)}",
                "# HELP tpu_plugin_lifecycle_identity_swaps_total Replugs "
                "whose BDF+serial reconciliation found different silicon "
                "in the slot.",
                "# TYPE tpu_plugin_lifecycle_identity_swaps_total counter",
                f"tpu_plugin_lifecycle_identity_swaps_total "
                f"{lifecycle.get('identity_swaps_total', 0)}",
                "# HELP tpu_plugin_lifecycle_invalid_transitions_total "
                "Lifecycle FSM transitions refused by the allowed-"
                "transition table (counted, never raised).",
                "# TYPE tpu_plugin_lifecycle_invalid_transitions_total "
                "counter",
                f"tpu_plugin_lifecycle_invalid_transitions_total "
                f"{lifecycle.get('invalid_transitions_total', 0)}",
                "# HELP tpu_plugin_lifecycle_devices Devices by lifecycle "
                "state.",
                "# TYPE tpu_plugin_lifecycle_devices gauge",
            ]
            for state, n in sorted(lifecycle.get("states", {}).items()):
                lines.append(
                    f'tpu_plugin_lifecycle_devices{{state="{_esc(state)}"}} {n}')
        read_paths = s.get("read_paths")
        if read_paths:
            lines += [
                "# HELP tdp_read_path_lock_acquisitions_total Registered-"
                "lock acquisitions charged to each hot read path "
                "(lockdep.read_path; steady state is pinned at 0).",
                "# TYPE tdp_read_path_lock_acquisitions_total counter",
            ]
            for name, rec in sorted(read_paths.items()):
                lines.append(
                    f'tdp_read_path_lock_acquisitions_total'
                    f'{{path="{_esc(name)}"}} {rec["lock_acquisitions"]}')
            lines += [
                "# HELP tdp_read_path_calls_total Entries into each hot "
                "read path bracket.",
                "# TYPE tdp_read_path_calls_total counter",
            ]
            for name, rec in sorted(read_paths.items()):
                lines.append(f'tdp_read_path_calls_total{{path="{_esc(name)}"}} '
                             f'{rec["calls"]}')
        lines += [
            "# HELP tpu_plugin_pending_plugins Plugins awaiting registration.",
            "# TYPE tpu_plugin_pending_plugins gauge",
            f"tpu_plugin_pending_plugins {len(s['pending'])}",
            "# HELP tpu_plugin_native_shim Native libtpuhealth loaded (1=yes).",
            "# TYPE tpu_plugin_native_shim gauge",
            f"tpu_plugin_native_shim {int(s['native'].get('native_shim', False))}",
            "# HELP tpu_plugin_libtpu_available libtpu.so loadable (1=yes).",
            "# TYPE tpu_plugin_libtpu_available gauge",
            "tpu_plugin_libtpu_available "
            f"{int(s['native'].get('libtpu_available', False))}",
        ]
        if "dra" in s:
            lines += [
                "# HELP tpu_plugin_dra_prepared_claims ResourceClaims "
                "currently prepared by the DRA driver.",
                "# TYPE tpu_plugin_dra_prepared_claims gauge",
                f"tpu_plugin_dra_prepared_claims {s['dra']['prepared_claims']}",
                "# HELP tpu_plugin_dra_registered DRA driver registered "
                "with the kubelet (1=yes).",
                "# TYPE tpu_plugin_dra_registered gauge",
                f"tpu_plugin_dra_registered "
                f"{int(s['dra']['kubelet_registered'])}",
                "# HELP tpu_plugin_dra_unhealthy_devices Devices pruned "
                "from the ResourceSlice by health.",
                "# TYPE tpu_plugin_dra_unhealthy_devices gauge",
                f"tpu_plugin_dra_unhealthy_devices "
                f"{len(s['dra']['unhealthy_devices'])}",
                "# HELP tpu_plugin_dra_republish_retries_total Backoff "
                "delays issued by the slice republish retry.",
                "# TYPE tpu_plugin_dra_republish_retries_total counter",
                f"tpu_plugin_dra_republish_retries_total "
                f"{s['dra']['republish_backoff']['total_attempts']}",
                "# HELP tpu_plugin_dra_slice_publishes_total Successful "
                "ResourceSlice publishes by kind (delta = generation-keyed "
                "guarded PUT, full = read-modify-write).",
                "# TYPE tpu_plugin_dra_slice_publishes_total counter",
                f'tpu_plugin_dra_slice_publishes_total{{kind="delta"}} '
                f"{s['dra']['publish_stats']['delta']}",
                f'tpu_plugin_dra_slice_publishes_total{{kind="full"}} '
                f"{s['dra']['publish_stats']['full']}",
                "# HELP tpu_plugin_dra_prepare_inflight Claim prepare/"
                "unprepare tasks currently in flight.",
                "# TYPE tpu_plugin_dra_prepare_inflight gauge",
                f"tpu_plugin_dra_prepare_inflight "
                f"{s['dra']['prepare_inflight']}",
                "# HELP tpu_plugin_dra_attach_active Claim tasks still "
                "before their checkpoint durability barrier (the group-"
                "commit window's input).",
                "# TYPE tpu_plugin_dra_attach_active gauge",
                f"tpu_plugin_dra_attach_active "
                f"{s['dra']['attach_active']}",
                "# HELP tpu_plugin_dra_prepare_workers Bounded pool size "
                "fanning out multi-claim prepare RPCs.",
                "# TYPE tpu_plugin_dra_prepare_workers gauge",
                f"tpu_plugin_dra_prepare_workers "
                f"{s['dra']['prepare_workers']}",
                "# HELP tpu_plugin_dra_checkpoint_commits_total Atomic "
                "checkpoint file writes (group commits).",
                "# TYPE tpu_plugin_dra_checkpoint_commits_total counter",
                f"tpu_plugin_dra_checkpoint_commits_total "
                f"{s['dra']['checkpoint_commits_total']}",
                "# HELP tpu_plugin_dra_checkpoint_claims_coalesced_total "
                "Claim mutations made durable by those commits (claims >> "
                "commits under a burst is the group-commit win).",
                "# TYPE tpu_plugin_dra_checkpoint_claims_coalesced_total "
                "counter",
                f"tpu_plugin_dra_checkpoint_claims_coalesced_total "
                f"{s['dra']['checkpoint_claims_coalesced_total']}",
                "# HELP handoffs_completed_total Migration claim handoffs "
                "validated and completed by this node's prepare.",
                "# TYPE handoffs_completed_total counter",
                f"handoffs_completed_total "
                f"{s['dra']['handoffs_completed_total']}",
                "# HELP tpu_plugin_dra_handoffs_emitted_total Migration "
                "handoff records durably emitted by unprepare.",
                "# TYPE tpu_plugin_dra_handoffs_emitted_total counter",
                f"tpu_plugin_dra_handoffs_emitted_total "
                f"{s['dra']['handoffs_emitted_total']}",
                "# HELP tpu_plugin_dra_orphan_specs_removed Stale claim-"
                "spec files swept at startup (spec written, checkpoint "
                "commit never landed).",
                "# TYPE tpu_plugin_dra_orphan_specs_removed gauge",
                f"tpu_plugin_dra_orphan_specs_removed "
                f"{s['dra']['orphan_specs_removed']}",
                "# HELP tpu_plugin_dra_orphaned_claims Prepared claims "
                "currently marked orphaned (device surprise-removed).",
                "# TYPE tpu_plugin_dra_orphaned_claims gauge",
                f"tpu_plugin_dra_orphaned_claims "
                f"{len(s['dra']['orphaned_claims'])}",
                "# HELP tpu_plugin_dra_checkpoint_bytes Size of the last "
                "committed checkpoint write (compact serialization) — "
                "the checkpoint-growth observability gauge.",
                "# TYPE tpu_plugin_dra_checkpoint_bytes gauge",
                f"tpu_plugin_dra_checkpoint_bytes "
                f"{s['dra']['checkpoint_bytes']}",
                # prepare-ack byte plane (round 15, transport endgame)
                "# HELP tpu_plugin_dra_ack_bytes_reused_total "
                "NodePrepareResources claim acks served from the "
                "pre-serialized per-claim segment cache.",
                "# TYPE tpu_plugin_dra_ack_bytes_reused_total counter",
                f"tpu_plugin_dra_ack_bytes_reused_total "
                f"{s['dra']['ack_bytes']['reused']}",
                "# HELP tpu_plugin_dra_ack_serializations_total Prepare-"
                "ack protobuf serializations paid (first build per "
                "claim + error acks).",
                "# TYPE tpu_plugin_dra_ack_serializations_total counter",
                f"tpu_plugin_dra_ack_serializations_total "
                f"{s['dra']['ack_bytes']['serializations']}",
                "# HELP tpu_plugin_dra_publish_waves_total ResourceSlice "
                "publish waves sent through the pacing layer "
                "(kubeapi.PublishPacer).",
                "# TYPE tpu_plugin_dra_publish_waves_total counter",
                f"tpu_plugin_dra_publish_waves_total "
                f"{s['dra']['pacing']['publish_waves_total']}",
                "# HELP tpu_plugin_dra_publishes_coalesced_total Publish "
                "requests whose state rode another request's wave instead "
                "of issuing their own PUT.",
                "# TYPE tpu_plugin_dra_publishes_coalesced_total counter",
                f"tpu_plugin_dra_publishes_coalesced_total "
                f"{s['dra']['pacing']['publishes_coalesced_total']}",
                "# HELP tpu_plugin_dra_publish_throttled_total Publish "
                "waves the apiserver answered 429 (re-admitted through a "
                "grown window).",
                "# TYPE tpu_plugin_dra_publish_throttled_total counter",
                f"tpu_plugin_dra_publish_throttled_total "
                f"{s['dra']['pacing']['publish_throttled_total']}",
                "# HELP tpu_plugin_dra_pacing_window_ms Current adaptive "
                "admission window of the publish pacer (0 = uncongested).",
                "# TYPE tpu_plugin_dra_pacing_window_ms gauge",
                f"tpu_plugin_dra_pacing_window_ms "
                f"{s['dra']['pacing']['window_ms']}",
                # watch-stream convergence plane (ISSUE 12)
                "# HELP tpu_plugin_dra_watch_streams_active Watch streams "
                "currently established against the apiserver.",
                "# TYPE tpu_plugin_dra_watch_streams_active gauge",
                f"tpu_plugin_dra_watch_streams_active "
                f"{s['dra']['watch']['watch_streams_active']}",
                "# HELP tpu_plugin_dra_watch_events_total Watch events "
                "delivered to the slice reconciler (at-least-once; "
                "duplicates counted).",
                "# TYPE tpu_plugin_dra_watch_events_total counter",
                f"tpu_plugin_dra_watch_events_total "
                f"{s['dra']['watch']['watch_events_total']}",
                "# HELP tpu_plugin_dra_watch_relists_total Collection "
                "relists (watch resume after 410/stream break, degraded "
                "polling, and resyncs).",
                "# TYPE tpu_plugin_dra_watch_relists_total counter",
                f"tpu_plugin_dra_watch_relists_total "
                f"{s['dra']['watch']['watch_relists_total']}",
                "# HELP tpu_plugin_dra_watch_resyncs_total Periodic "
                "resync relists (the missed-event backstop).",
                "# TYPE tpu_plugin_dra_watch_resyncs_total counter",
                f"tpu_plugin_dra_watch_resyncs_total "
                f"{s['dra']['watch']['watch_resyncs_total']}",
                "# HELP tpu_plugin_dra_watch_degraded_mode Watch plane "
                "degraded to paced-relist polling (1 = degraded; typed, "
                "self-healing).",
                "# TYPE tpu_plugin_dra_watch_degraded_mode gauge",
                f"tpu_plugin_dra_watch_degraded_mode "
                f"{s['dra']['watch']['watch_degraded_mode']}",
                "# HELP tpu_plugin_dra_watch_repairs_total Slice repairs "
                "triggered by watch observations (wiped/diverged/missing "
                "slices republished through the guarded-write path).",
                "# TYPE tpu_plugin_dra_watch_repairs_total counter",
                f"tpu_plugin_dra_watch_repairs_total "
                f"{s['dra']['watch']['watch_repairs_total']}",
                "# HELP tpu_plugin_dra_publish_reads_skipped_total "
                "Unchanged-projection publishes that skipped their "
                "liveness GET because a live watch stream covers wipe "
                "detection.",
                "# TYPE tpu_plugin_dra_publish_reads_skipped_total "
                "counter",
                f"tpu_plugin_dra_publish_reads_skipped_total "
                f"{s['dra']['publish_stats']['watch_read_skips']}",
                # slice placement / fragmentation (placement.py)
                "# HELP tpu_plugin_dra_frag_recomputes_total Fragmentation "
                "snapshot rebuilds (one per inventory-epoch publish or "
                "checkpoint group commit).",
                "# TYPE tpu_plugin_dra_frag_recomputes_total counter",
                f"tpu_plugin_dra_frag_recomputes_total "
                f"{s['dra']['placement']['frag_recomputes_total']}",
                "# HELP tpu_plugin_dra_defrag_proposals_total Defrag "
                "advisories computed (/debug/defrag + fleetsim).",
                "# TYPE tpu_plugin_dra_defrag_proposals_total counter",
                f"tpu_plugin_dra_defrag_proposals_total "
                f"{s['dra']['placement']['defrag_proposals_total']}",
                "# HELP tpu_plugin_dra_defrag_unsatisfiable_total Defrag "
                "advisories whose shape exceeded total free capacity "
                "(no migration set can help; add hosts instead).",
                "# TYPE tpu_plugin_dra_defrag_unsatisfiable_total counter",
                f"tpu_plugin_dra_defrag_unsatisfiable_total "
                f"{s['dra']['placement']['defrag_unsatisfiable_total']}",
            ]
            frag = s["dra"].get("fragmentation") or {}
            if frag:
                lines += [
                    "# HELP tpu_plugin_dra_fragmentation Per-generation "
                    "fragmentation score: 1 - largest placeable sub-box "
                    "/ free chips (0 = one contiguous box).",
                    "# TYPE tpu_plugin_dra_fragmentation gauge",
                ]
                for gen, rec in sorted(frag.items()):
                    lines.append(
                        f'tpu_plugin_dra_fragmentation'
                        f'{{generation="{_esc(gen)}"}} '
                        f'{rec["fragmentation"]}')
                lines += [
                    "# HELP tpu_plugin_dra_largest_free_box Chips in the "
                    "largest axis-aligned free sub-box of the host torus.",
                    "# TYPE tpu_plugin_dra_largest_free_box gauge",
                ]
                for gen, rec in sorted(frag.items()):
                    lines.append(
                        f'tpu_plugin_dra_largest_free_box'
                        f'{{generation="{_esc(gen)}"}} '
                        f'{rec["largest_free_box"]}')
                lines += [
                    "# HELP tpu_plugin_dra_free_chips Chips free for "
                    "placement (healthy, unclaimed, present).",
                    "# TYPE tpu_plugin_dra_free_chips gauge",
                ]
                for gen, rec in sorted(frag.items()):
                    lines.append(
                        f'tpu_plugin_dra_free_chips'
                        f'{{generation="{_esc(gen)}"}} {rec["free"]}')
            breaker = s["dra"].get("api_breaker")
            if breaker is not None:
                lines += [
                    "# HELP tpu_plugin_kubeapi_breaker_open API-client "
                    "circuit breaker state (1=open/half-open).",
                    "# TYPE tpu_plugin_kubeapi_breaker_open gauge",
                    f"tpu_plugin_kubeapi_breaker_open "
                    f"{int(breaker['state'] != 'closed')}",
                    "# HELP tpu_plugin_kubeapi_breaker_trips_total Times "
                    "the API-client circuit breaker tripped open.",
                    "# TYPE tpu_plugin_kubeapi_breaker_trips_total counter",
                    f"tpu_plugin_kubeapi_breaker_trips_total "
                    f"{breaker['trips']}",
                    "# HELP tpu_plugin_kubeapi_breaker_rejected_total "
                    "Requests failed fast while the breaker was open.",
                    "# TYPE tpu_plugin_kubeapi_breaker_rejected_total "
                    "counter",
                    f"tpu_plugin_kubeapi_breaker_rejected_total "
                    f"{breaker['rejected']}",
                    "# HELP tpu_plugin_kubeapi_breaker_half_open_"
                    "rejected_total Requests failed fast while losing "
                    "the half-open single-probe race.",
                    "# TYPE tpu_plugin_kubeapi_breaker_half_open_"
                    "rejected_total counter",
                    f"tpu_plugin_kubeapi_breaker_half_open_rejected_total "
                    f"{breaker.get('half_open_rejected', 0)}",
                ]
        fired = (s.get("faults") or {}).get("fired") or {}
        if fired:
            lines += [
                "# HELP tdp_fault_fires_total Injected-fault fires by "
                "site (faults.py; chaos runs only — absent when no "
                "fault ever fired).",
                "# TYPE tdp_fault_fires_total counter",
            ]
            for site, n in sorted(fired.items()):
                lines.append(f'tdp_fault_fires_total{{site="{_esc(site)}"}} '
                             f'{n}')
        # sharded placement control plane (fleetplace.FleetScheduler):
        # emitted only when this daemon hosts a scheduler shard; the
        # per-shard decision-latency histogram (tdp_fleet_decision_ms)
        # rides trace.render_prometheus below
        flt = s.get("fleet")
        if flt is not None:
            shard = f'{{shard="{_esc(flt.get("shard_index", 0))}"}}'
            for help_text, family, key in (
                    ("Placement decisions finished (placed, unplaceable, "
                     "or conflicted terminal).",
                     "tpu_plugin_fleet_decisions_total",
                     "decisions_total"),
                    ("Batched decision waves settled (one snapshot, one "
                     "sorted pass, one commit round each).",
                     "tpu_plugin_fleet_decision_waves_total",
                     "decision_waves_total"),
                    ("Optimistic commits refused by the fabric CAS (peer "
                     "scheduler consumed a planned chip first); every one "
                     "is a clean counted abort.",
                     "tpu_plugin_fleet_commit_conflicts_total",
                     "commit_conflicts_total"),
                    ("Replans after a commit conflict (bounded per "
                     "claim by replan_max).",
                     "tpu_plugin_fleet_replans_total",
                     "replans_total"),
                    ("Incremental fragmentation delta applies (one per "
                     "watch-observed slice change — O(request), not "
                     "O(fleet)).",
                     "tpu_plugin_fleet_frag_delta_applies_total",
                     "frag_delta_applies_total"),
                    ("Full per-slice fragmentation recomputes (LIST "
                     "relists only).",
                     "tpu_plugin_fleet_frag_full_recomputes_total",
                     "frag_full_recomputes_total"),
                    ("Relisted slices skipped because resourceVersion/"
                     "generation was unchanged (the 410-relist "
                     "delta-skip guard).",
                     "tpu_plugin_fleet_relist_unchanged_skips_total",
                     "relist_unchanged_skips_total")):
                lines += [f"# HELP {family} {help_text}",
                          f"# TYPE {family} counter",
                          f"{family}{shard} {flt.get(key, 0)}"]
        # privilege-boundary crossings (broker.py): client-side counters,
        # present in every scrape whichever mode the daemon runs in
        brk = s.get("broker") or {}
        lines += [
            "# HELP tdp_broker_crossings_total Privilege-boundary "
            "crossings through the broker seam (broker.ipc spans; "
            "in-process and spawned modes both count).",
            "# TYPE tdp_broker_crossings_total counter",
            f"tdp_broker_crossings_total {brk.get('crossings_total', 0)}",
            "# HELP tdp_broker_errors_total Broker crossings that failed "
            "(connection lost, refused, injected drop).",
            "# TYPE tdp_broker_errors_total counter",
            f"tdp_broker_errors_total {brk.get('errors_total', 0)}",
            "# HELP tdp_broker_spawn_mode Privilege separation active "
            "(1 = privileged operations run in a separate broker "
            "process).",
            "# TYPE tdp_broker_spawn_mode gauge",
            f"tdp_broker_spawn_mode {int(brk.get('mode') == 'spawn')}",
            "# HELP tdp_broker_batched_ops_total Sub-operations carried "
            "by batched broker crossings (the gap to crossings_total is "
            "round trips the batch path saved).",
            "# TYPE tdp_broker_batched_ops_total counter",
            f"tdp_broker_batched_ops_total {brk.get('batched_ops_total', 0)}",
            "# HELP tdp_broker_ring_hits_total Hot reads served from the "
            "shared-memory response ring without a socket round trip.",
            "# TYPE tdp_broker_ring_hits_total counter",
            f"tdp_broker_ring_hits_total {brk.get('ring_hits_total', 0)}",
            "# HELP tdp_broker_ring_fallbacks_total Ring lookups that "
            "fell back to the socket path (miss, stale, torn slot, or "
            "injected broker.ring fault).",
            "# TYPE tdp_broker_ring_fallbacks_total counter",
            f"tdp_broker_ring_fallbacks_total "
            f"{brk.get('ring_fallbacks_total', 0)}",
            "# HELP tdp_broker_crossings_per_claim Privilege crossings "
            "the most recent claim paid (Allocate or DRA prepare; the "
            "batching budget is 1 revalidation crossing per claim).",
            "# TYPE tdp_broker_crossings_per_claim gauge",
            f"tdp_broker_crossings_per_claim "
            f"{brk.get('crossings_per_claim', 0)}",
        ]
        # operator policy decisions (policy.py): emitted only when an
        # engine is loaded, like the dra section
        pol = s.get("policy")
        if pol is not None:
            lines += [
                "# HELP tdp_policy_invalid_overrides_total Policy "
                "scoring overrides discarded as invalid allocations.",
                "# TYPE tdp_policy_invalid_overrides_total counter",
                f"tdp_policy_invalid_overrides_total "
                f"{pol.get('invalid_overrides', 0)}",
            ]
            hooks = pol.get("hooks", [])
            for help_text, family, key in (
                    ("Policy hook invocations.",
                     "tdp_policy_hook_calls_total", "calls"),
                    ("Policy hook decisions that overrode builtin "
                     "behavior.",
                     "tdp_policy_hook_overrides_total", "overrides"),
                    ("Policy hook invocations that raised (builtin "
                     "behavior kept).",
                     "tdp_policy_hook_errors_total", "errors"),
                    ("Policy hook results discarded for exceeding the "
                     "per-call deadline.",
                     "tdp_policy_hook_deadline_exceeded_total",
                     "deadline_exceeded"),
                    ("Policy hook consultations skipped while the "
                     "hook's circuit breaker was open.",
                     "tdp_policy_hook_rejected_open_total",
                     "rejected_while_open")):
                lines += [f"# HELP {family} {help_text}",
                          f"# TYPE {family} counter"]
                for h in hooks:
                    lines.append(
                        f'{family}{{hook="{_esc(h["hook"])}",module='
                        f'"{_esc(h["module"])}"}} {h.get(key, 0)}')
            lines += [
                "# HELP tdp_policy_breaker_open Policy hook circuit "
                "breaker state (1 = open/half-open: hook skipped, "
                "builtin behavior).",
                "# TYPE tdp_policy_breaker_open gauge",
            ]
            for h in hooks:
                state = h.get("breaker", {}).get("state", "closed")
                lines.append(
                    f'tdp_policy_breaker_open{{hook="{_esc(h["hook"])}",'
                    f'module="{_esc(h["module"])}"}} '
                    f'{int(state != "closed")}')
        # flight-recorder exposition (trace.py): latency histograms
        # (_bucket/_sum/_count families) + the trace-plane counters
        from . import trace
        lines += trace.render_prometheus()
        # SLO plane (slo.py): burn rates, breach state, budget, exemplar
        # info — evaluated by the status() call above, rendered from the
        # lock-free snapshot
        from . import slo as slo_mod
        lines += slo_mod.render_prometheus(
            getattr(getattr(self, "manager", None), "slo_engine", None)
            or slo_mod.get_engine())
        # self-heal plane (remediation.py): emitted only when an engine
        # is attached, like the policy section
        rem = getattr(getattr(self, "manager", None),
                      "remediation_engine", None)
        if rem is not None:
            from . import remediation as remediation_mod
            lines += remediation_mod.render_prometheus(rem)
        # ONE join materializes the scrape: every byte of the response is
        # produced exactly once (list-append assembly — incremental `+=`
        # string building re-copies the accumulated prefix per line,
        # O(series²) bytes at 4096 devices). The accounting below is a
        # consistency gauge (bytes_joined == rendered length, parts
        # O(series)) recorded by bench.py --scale; the regression
        # TRIPWIRE is test_perf_honesty.py's AST scan refusing any
        # non-`lines` augmented assignment on this render path.
        text = "\n".join(lines) + "\n"
        n_series = sum(1 for ln in lines if ln and not ln.startswith("#"))
        # joined bytes = part bytes + (parts-1) separators + trailing \n
        self.scrape_stats = {
            "series": n_series,
            "parts": len(lines),
            "bytes_joined": sum(len(ln) for ln in lines) + len(lines),
            "bytes_rendered": len(text),
        }
        return text
