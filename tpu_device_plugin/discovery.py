"""Host discovery: VFIO-bound TPU chips, /dev/accel correlation, partitions.

TPU analogue of the reference's sysfs walks
(`createIommuDeviceMap` device_plugin.go:187-247, `createVgpuIDMap` :255-291):
walk /sys/bus/pci/devices filtering vendor 1ae0 + vfio drivers, read the
iommu_group symlink / NUMA node / device id, then additionally correlate
/sys/class/accel char devices and stamp each chip with ICI torus coordinates.
Discovery is one-shot and side-effect free: it returns an immutable Registry.
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

from .config import Config
from .naming import GenerationInfo, load_generation_map
from .registry import Registry, TpuDevice, TpuPartition
from .topology import assign_coords, load_topology_hints

log = logging.getLogger(__name__)

_ACCEL_RE = re.compile(r"^accel(\d+)$")


# --- low-level sysfs readers (unit-testable against tmpdir fixtures) ---------

def read_id_from_file(path: str) -> Optional[str]:
    """Read a sysfs hex id file, stripping the 0x prefix.

    The reference slices bytes 2: unconditionally (device_plugin.go:294-302);
    we only strip an actual `0x` so hand-written fixtures also parse.
    """
    try:
        with open(path, "r", encoding="ascii", errors="replace") as f:
            data = f.read().strip()
    except OSError as exc:
        log.debug("could not read %s: %s", path, exc)
        return None
    return data[2:] if data.lower().startswith("0x") else data


def read_link_basename(path: str) -> Optional[str]:
    """Basename of a sysfs symlink target (driver name, iommu group number)."""
    try:
        return os.path.basename(os.readlink(path))
    except OSError as exc:
        log.debug("could not readlink %s: %s", path, exc)
        return None


def read_numa_node(path: str) -> int:
    """NUMA node, clamping negatives (unset) to 0 (reference :304-320)."""
    try:
        with open(path, "r", encoding="ascii") as f:
            node = int(f.read().strip())
    except (OSError, ValueError) as exc:
        log.debug("could not read numa node %s: %s", path, exc)
        return 0
    return max(node, 0)


def pcie_path(pci_base_path: str, bdf: str) -> str:
    """Resolved sysfs hierarchy path for a chip (its PCIe position).

    /sys/bus/pci/devices/<bdf> is a symlink into /sys/devices/...; sorting
    chips by the resolved path groups co-packaged chips at ANY nesting
    depth — chips behind one switch share the upstream-port prefix even
    though each sits under its own downstream port. This is the host-side
    ICI-adjacency signal assign_coords uses (SURVEY §7 hard part (a)). On
    flat layouts (fixtures, no symlinks) the path order degenerates to BDF
    order.
    """
    return os.path.realpath(os.path.join(pci_base_path, bdf))


def scan_accel_class(accel_class_path: str) -> Dict[str, int]:
    """Map PCI BDF → /dev/accelN index via /sys/class/accel/accelN/device.

    Only populated on hosts where the accel driver still owns chips (i.e. the
    vTPU/logical-partition path); vfio-bound chips vanish from this class.
    """
    out: Dict[str, int] = {}
    try:
        entries = sorted(os.listdir(accel_class_path))
    except OSError:
        return out
    for entry in entries:
        m = _ACCEL_RE.match(entry)
        if not m:
            continue
        bdf = read_link_basename(os.path.join(accel_class_path, entry, "device"))
        if bdf:
            out[bdf] = int(m.group(1))
    return out


# --- passthrough discovery ---------------------------------------------------

def discover_passthrough(
    cfg: Config,
    accel_by_bdf: Optional[Dict[str, int]] = None,
) -> Tuple[Registry, Dict[str, GenerationInfo]]:
    """Walk the PCI bus for VFIO-bound TPU endpoints; build the registry maps."""
    generations = load_generation_map(cfg.generation_map_path)
    hints = load_topology_hints(cfg.topology_hints_path)
    if accel_by_bdf is None:
        accel_by_bdf = scan_accel_class(cfg.accel_class_path)

    raw: List[TpuDevice] = []
    try:
        entries = sorted(os.listdir(cfg.pci_base_path))
    except OSError as exc:
        log.warning("PCI sysfs %s unreadable: %s", cfg.pci_base_path, exc)
        entries = []
    for bdf in entries:
        base = os.path.join(cfg.pci_base_path, bdf)
        if not os.path.isdir(base):
            continue
        vendor = read_id_from_file(os.path.join(base, "vendor"))
        if vendor is None or vendor.lower() not in cfg.vendor_ids:
            continue
        driver = read_link_basename(os.path.join(base, "driver"))
        if driver not in cfg.vfio_drivers:
            log.info("TPU %s bound to %r, not a vfio driver; skipping", bdf, driver)
            continue
        group = read_link_basename(os.path.join(base, "iommu_group"))
        if group is None:
            log.warning("TPU %s has no iommu_group; skipping", bdf)
            continue
        device_id = read_id_from_file(os.path.join(base, "device"))
        if device_id is None:
            log.warning("TPU %s has no device id; skipping", bdf)
            continue
        raw.append(
            TpuDevice(
                bdf=bdf,
                device_id=device_id.lower(),
                iommu_group=group,
                numa_node=read_numa_node(os.path.join(base, "numa_node")),
                accel_index=accel_by_bdf.get(bdf),
            )
        )

    # Stamp ICI coordinates per model (coords are host-local per generation).
    by_model: Dict[str, List[TpuDevice]] = {}
    for dev in raw:
        by_model.setdefault(dev.device_id, []).append(dev)
    devices_by_model: Dict[str, Tuple[TpuDevice, ...]] = {}
    iommu_map: Dict[str, List[TpuDevice]] = {}
    bdf_to_group: Dict[str, str] = {}
    for model, devs in by_model.items():
        paths = {d.bdf: pcie_path(cfg.pci_base_path, d.bdf) for d in devs}
        coords = assign_coords([d.bdf for d in devs], generations.get(model),
                               hints, pcie_paths=paths)
        stamped = tuple(
            TpuDevice(
                bdf=d.bdf, device_id=d.device_id, iommu_group=d.iommu_group,
                numa_node=d.numa_node, accel_index=d.accel_index,
                ici_coords=coords.get(d.bdf),
            )
            for d in devs
        )
        devices_by_model[model] = stamped
        for d in stamped:
            iommu_map.setdefault(d.iommu_group, []).append(d)
            bdf_to_group[d.bdf] = d.iommu_group

    registry = Registry(
        devices_by_model=devices_by_model,
        iommu_map={g: tuple(ds) for g, ds in iommu_map.items()},
        bdf_to_group=bdf_to_group,
    )
    log.info("discovered %d VFIO TPU chips in %d iommu groups",
             len(raw), len(registry.iommu_map))
    return registry, generations


# --- vTPU (partition) discovery ----------------------------------------------

def _sanitize_type(raw: str) -> str:
    return raw.strip().replace(" ", "_")


def discover_mdev_partitions(cfg: Config) -> List[TpuPartition]:
    """Enumerate kernel mdev devices (reference vGPU path, :255-291)."""
    out: List[TpuPartition] = []
    try:
        uuids = sorted(os.listdir(cfg.mdev_base_path))
    except OSError:
        return out
    for uuid in uuids:
        base = os.path.join(cfg.mdev_base_path, uuid)
        type_name = None
        name_path = os.path.join(base, "mdev_type", "name")
        try:
            with open(name_path, "r", encoding="ascii", errors="replace") as f:
                type_name = _sanitize_type(f.read())
        except OSError as exc:
            log.warning("mdev %s has no type name (%s); skipping", uuid, exc)
            continue
        # Parent BDF = second-to-last element of the resolved mdev path
        # (reference derives it the same way, :347-357).
        try:
            real = os.path.realpath(base)
            parent_bdf = real.rstrip("/").split("/")[-2]
        except (OSError, IndexError):
            log.warning("mdev %s parent unresolvable; skipping", uuid)
            continue
        numa = read_numa_node(os.path.join(cfg.pci_base_path, parent_bdf, "numa_node"))
        out.append(TpuPartition(uuid=uuid, type_name=type_name,
                                parent_bdf=parent_bdf, numa_node=numa,
                                provider="mdev"))
    return out


def discover_logical_partitions(
    cfg: Config,
    generations: Dict[str, GenerationInfo],
    accel_by_bdf: Optional[Dict[str, int]] = None,
) -> List[TpuPartition]:
    """Synthesize partitions where hardware lacks mdev (SURVEY.md §7 hard part d).

    TPU chips expose no mediated-device layer; multi-tenant chip sharing is a
    host-software construct. Two declaration styles in the partition config
    JSON (Config.partition_config_path):

    - {"per_core": true} — split every accel-owned chip into
      `cores_per_chip` partitions named `<gen>-core`, uuid `<bdf>-coreN`.
    - {"partitions": [{"uuid": ..., "type": ..., "parent_bdf": ...}]} —
      explicit list.
    """
    if not cfg.partition_config_path:
        return []
    try:
        with open(cfg.partition_config_path, "r", encoding="utf-8") as f:
            spec = json.load(f)
        if not isinstance(spec, dict):
            raise ValueError("top level must be an object")
    except (OSError, ValueError) as exc:
        log.warning("partition config %s unreadable: %s", cfg.partition_config_path, exc)
        return []
    out: List[TpuPartition] = []
    if accel_by_bdf is None:
        accel_by_bdf = scan_accel_class(cfg.accel_class_path)
    if spec.get("per_core"):
        for bdf, accel_idx in sorted(accel_by_bdf.items()):
            vendor = read_id_from_file(os.path.join(cfg.pci_base_path, bdf, "vendor"))
            if vendor is None or vendor.lower() not in cfg.vendor_ids:
                continue  # foreign accel-class hardware (VPU/Habana/...) is not a TPU
            device_id = read_id_from_file(os.path.join(cfg.pci_base_path, bdf, "device"))
            info = generations.get((device_id or "").lower())
            cores = info.cores_per_chip if info else 1
            gen = info.name if info else "tpu"
            numa = read_numa_node(os.path.join(cfg.pci_base_path, bdf, "numa_node"))
            for core in range(cores):
                out.append(TpuPartition(
                    uuid=f"{bdf}-core{core}", type_name=f"{gen}-core",
                    parent_bdf=bdf, numa_node=numa,
                    provider="logical", accel_index=accel_idx,
                ))
    for entry in spec.get("partitions", []):
        try:
            bdf = entry["parent_bdf"]
            out.append(TpuPartition(
                uuid=entry["uuid"], type_name=_sanitize_type(entry["type"]),
                parent_bdf=bdf,
                numa_node=read_numa_node(os.path.join(cfg.pci_base_path, bdf, "numa_node")),
                provider="logical", accel_index=accel_by_bdf.get(bdf),
            ))
        except KeyError as exc:
            log.warning("partition entry %r missing %s; skipped", entry, exc)
    return out


def discover(cfg: Config) -> Tuple[Registry, Dict[str, GenerationInfo]]:
    """Full discovery: passthrough chips + mdev/logical partitions."""
    accel_by_bdf = scan_accel_class(cfg.accel_class_path)
    registry, generations = discover_passthrough(cfg, accel_by_bdf)
    partitions = discover_mdev_partitions(cfg)
    partitions += discover_logical_partitions(cfg, generations, accel_by_bdf)
    # A partition type named like a passthrough resource suffix would make
    # two plugins register the same extended-resource name with the kubelet.
    # Refuse the partitions here (not later in the lifecycle), so their
    # parent chips stay advertised as passthrough instead of being consumed
    # by a plugin that can never be built.
    from .naming import resource_name_for
    passthrough_suffixes = set()
    for m in registry.devices_by_model:
        suffix = resource_name_for(m, generations, cfg.pci_ids_path)
        passthrough_suffixes.add(suffix)
        if m not in generations:
            # The packaged ids are documented placeholders (no public Cloud
            # TPU PCI-id table): an unmatched id on a real fleet means the
            # operator must supply --generation-map before resource names
            # mean anything. Warn on BOTH entry points (daemon and
            # --discover-only) — this is the shared path.
            log.warning(
                "device id %s is not in the generation table; advertising "
                "fallback resource name %r — supply --generation-map with "
                "this fleet's real ids (see utils/README.md)", m, suffix)
    kept: List[TpuPartition] = []
    for p in partitions:
        if p.type_name in passthrough_suffixes:
            log.error("partition %s: type %r collides with a passthrough "
                      "resource suffix; dropping partition", p.uuid, p.type_name)
            continue
        kept.append(p)
    partitions = kept
    # A logical partition is only allocatable through its parent's accel node
    # or VFIO group; one with neither would hand a VMI zero DeviceSpecs —
    # refuse it here with a reason instead of failing at Allocate time.
    # And a VFIO group attaches to exactly ONE container at a time, so a
    # vfio-bound IOMMU group can back at most ONE advertised partition —
    # keyed by group, not parent BDF: two partitions on different parents
    # that share a group would still collide in VFIO_GROUP_SET_CONTAINER
    # (EBUSY), making any extra advertised capacity unusable. (Accel-node
    # partitions CAN share — the accel driver multiplexes.)
    allocatable: List[TpuPartition] = []
    vfio_group_seen: Dict[str, str] = {}
    for p in partitions:
        if p.provider == "logical" and p.accel_index is None:
            parent_group = registry.bdf_to_group.get(p.parent_bdf)
            if parent_group is None:
                log.warning(
                    "partition %s (type %s): parent %s has no accel node and "
                    "is not vfio-bound; refusing to advertise an "
                    "unallocatable partition", p.uuid, p.type_name, p.parent_bdf)
                continue
            holder = vfio_group_seen.setdefault(parent_group, p.uuid)
            if holder != p.uuid:
                log.warning(
                    "partition %s (type %s): parent %s's VFIO group %s is "
                    "already backing partition %s — a VFIO group attaches to "
                    "one VM at a time, dropping the extra partition",
                    p.uuid, p.type_name, p.parent_bdf, parent_group, holder)
                continue
        allocatable.append(p)
    partitions = allocatable
    # Operator-set blast-radius cap: accel-backed logical partitions share
    # one /dev/accelN with no hardware isolation (docs/design.md "vTPU
    # trust boundary"), so a fleet can bound tenants-per-chip regardless of
    # what the partition config declares. mdev (kernel-mediated) and
    # vfio-backed (already 1/group) partitions are not capped.
    if cfg.max_partitions_per_chip > 0:
        per_parent: Dict[str, int] = {}
        capped: List[TpuPartition] = []
        for p in partitions:
            if p.provider == "logical" and p.accel_index is not None:
                n = per_parent.get(p.parent_bdf, 0)
                if n >= cfg.max_partitions_per_chip:
                    log.warning(
                        "partition %s (type %s): parent %s already has %d "
                        "advertised partitions (--max-partitions-per-chip); "
                        "dropping", p.uuid, p.type_name, p.parent_bdf, n)
                    continue
                per_parent[p.parent_bdf] = n + 1
            capped.append(p)
        partitions = capped
    # A vfio-bound chip that backs logical partitions is consumed by the vTPU
    # resource: advertising it as passthrough too would let the kubelet grant
    # the same VFIO group to two VMIs. Exclusion is by IOMMU GROUP, not BDF —
    # plan_allocation expands a passthrough request to its whole group, so a
    # kept chip sharing a group with a consumed parent would mount the same
    # /dev/vfio/<group> the vTPU plugin hands out (lookup maps stay intact —
    # the vTPU plugin resolves the parent's group through them). The
    # reference never faces this: mdev parents are bound to the vendor
    # driver, so the sets are disjoint there.
    consumed = {p.parent_bdf for p in partitions
                if p.provider == "logical" and p.accel_index is None}
    consumed_groups = {registry.bdf_to_group[b] for b in consumed
                       if b in registry.bdf_to_group}
    if consumed_groups:
        devices_by_model = {}
        for model, devs in registry.devices_by_model.items():
            kept = tuple(d for d in devs
                         if d.iommu_group not in consumed_groups)
            if kept:
                devices_by_model[model] = kept
        log.info("VFIO groups %s back logical partitions; their chips are "
                 "excluded from passthrough", ",".join(sorted(consumed_groups)))
        registry = Registry(
            devices_by_model=devices_by_model,
            iommu_map=registry.iommu_map,
            bdf_to_group=registry.bdf_to_group,
        )
    by_type: Dict[str, List[TpuPartition]] = {}
    parent_map: Dict[str, List[str]] = {}
    for p in partitions:
        by_type.setdefault(p.type_name, []).append(p)
        parent_map.setdefault(p.parent_bdf, []).append(p.uuid)
    registry = Registry(
        devices_by_model=registry.devices_by_model,
        iommu_map=registry.iommu_map,
        bdf_to_group=registry.bdf_to_group,
        partitions_by_type={t: tuple(ps) for t, ps in by_type.items()},
        parent_to_partitions={b: tuple(us) for b, us in parent_map.items()},
    )
    return registry, generations
